#!/usr/bin/env python
"""hlo_audit — AOT-lower zoo train steps over virtual wide meshes and
audit the compiled HLO (paddle_tpu.analysis.hlo's CLI face).

Where tools/graph_lint.py lints what the user *traced*, this audits what
XLA *compiled*: per mesh width it builds a sharded TrainStep for each zoo
model, lowers + compiles it ABSTRACTLY (no execution, no chip — the
script provisions ``--xla_force_host_platform_device_count`` before jax
imports, so a 64-device v5e layout audits on any build host), and runs
the hlo pass family: full-gathers of ZeRO-sharded state (ERROR),
collective census with ring-model wire bytes, per-device memory + FLOPs.

Usage:
    python tools/hlo_audit.py --zoo --mesh 16x2 --strict --json
    python tools/hlo_audit.py --model bert --mesh 4x2x2 --zero 3
    python tools/hlo_audit.py --seeded --mesh 8x2 --strict   # must exit 1

``--mesh DPxMP[xSP]`` is repeatable; every lowering is recompile-ledgered
at kind ``hlo_audit`` with a labeled ``arg:mesh`` key (the
zero-steady-state-recompile convention extended to audit runs; the JSON
report carries the events).  ``--strict`` exits non-zero on any
ERROR-severity finding — the zoo must pass clean at every width, and the
``--seeded`` de-sharded-ZeRO fixture must fail.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ZOO_MODELS = ("lenet", "resnet_block", "bert", "gpt", "gpt_moe",
              "wide_deep")

# --autoshard: shard models through the FLAGS_autoshard=apply TrainStep
# hook (analysis.autoshard rules engine) instead of the models' explicit
# annotation entry points — audits the rules-driven path end-to-end
_AUTOSHARD = [False]


def parse_mesh(spec: str):
    """'16x2' -> {dp:16, mp:2}; '8x2x2' -> {dp:8, mp:2, sp:2}.  Parts
    may also NAME their axis ('ep8', 'dp4xep2' — the expert-parallel
    meshes MoE shards over); bare numbers keep the positional
    DP[xMP[xSP]] meaning."""
    import re
    raw = [p for p in spec.lower().replace("*", "x").split("x") if p]
    named = {}
    positional = []
    for p in raw:
        m = re.fullmatch(r"([a-z]+)(\d+)", p)
        if m:
            named[m.group(1)] = int(m.group(2))
        else:
            positional.append(int(p))
    if len(positional) > 3 or any(p < 1 for p in positional) \
            or any(v < 1 for v in named.values()):
        raise ValueError(
            f"bad mesh spec {spec!r}: want DP[xMP[xSP]] or named parts "
            f"like ep8")
    axes = {}
    for name, v in zip(("dp", "mp", "sp"), positional):
        axes[name] = v
    for name, v in named.items():
        if name in axes:
            raise ValueError(f"axis {name!r} given twice in {spec!r}")
        axes[name] = v
    if not axes:
        raise ValueError(f"empty mesh spec {spec!r}")
    return axes


def _provision(n_devices: int) -> None:
    """Force an ``n_devices``-wide virtual CPU platform BEFORE jax
    initializes (the one simulated-chip provisioning recipe; explicit
    JAX_PLATFORMS in the env wins)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("PALLAS_AXON_POOL_IPS", "")  # no TPU tunnel
    flags = " ".join(f for f in os.environ.get("XLA_FLAGS", "").split()
                     if not f.startswith("--xla_force_host_platform"))
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n_devices}"
    ).strip()


# -- zoo train-step builders (called after provisioning/imports) ------------

def _build_lenet(mesh, zero):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.vision.models import LeNet
    paddle.seed(0)
    model = LeNet()
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                     mesh=mesh, zero=zero)
    dp = dict(mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    x = rng.randn(2 * dp, 1, 28, 28).astype("float32")
    y = rng.randint(0, 10, (2 * dp,))
    return step, (x,), y


def _build_resnet_block(mesh, zero, ch=8, hw=8):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.parallel import TrainStep

    class Block(nn.Layer):
        """Residual conv-BN-ReLU pair + linear head (bench.py's high-res
        stage with a classification tail so it trains end-to-end)."""

        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
            self.b1 = nn.BatchNorm2D(ch)
            self.c2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
            self.b2 = nn.BatchNorm2D(ch)
            self.relu = nn.ReLU()
            self.head = nn.Linear(ch, 16)

        def forward(self, x):
            h = self.relu(self.b1(self.c1(x)))
            h = self.relu(self.b2(self.c2(h)) + x)
            return self.head(h.mean(axis=[2, 3]))

    paddle.seed(0)
    model = Block()
    opt = paddle.optimizer.Momentum(parameters=model.parameters(),
                                    learning_rate=0.1, momentum=0.9)
    step = TrainStep(model, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                     mesh=mesh, zero=zero)
    dp = dict(mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    x = rng.randn(2 * dp, ch, hw, hw).astype("float32")
    y = rng.randint(0, 16, (2 * dp,))
    return step, (x,), y


def _build_bert(mesh, zero):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.text.models.bert import (
        BertConfig, BertForPretraining, apply_tensor_parallel)
    cfg = BertConfig.tiny(vocab_size=64, hidden_size=16, layers=2,
                          heads=2, seq=32)
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_probs_dropout_prob = 0.0
    paddle.seed(0)
    model = BertForPretraining(cfg)
    if not _AUTOSHARD[0]:
        apply_tensor_parallel(model)
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=zero, remat=True)
    dp = dict(mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4 * dp, 16))
    labels = np.where(rng.rand(*ids.shape) < 0.15, ids, -100)
    return step, (ids, None, None, labels), None


def _build_gpt(mesh, zero):
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.text.models.gpt import (GPTConfig, GPTModel,
                                            apply_tensor_parallel)
    cfg = GPTConfig.tiny(vocab_size=64, hidden_size=16, layers=2,
                         heads=2, seq=32)
    cfg.dropout = 0.0
    paddle.seed(0)
    model = GPTModel(cfg)
    if not _AUTOSHARD[0]:
        apply_tensor_parallel(model)
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=zero, remat=True)
    dp = dict(mesh.shape).get("dp", 1)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (4 * dp, 16))
    return step, (ids, ids.copy()), None


def _build_gpt_moe(mesh, zero):
    """Expert-parallel GPT-MoE step (ISSUE 14): every other block's FFN
    is a MoELayer whose stacked expert parameters shard over the mesh's
    expert axis ('ep' when the mesh has one, else EP=DP over 'dp'), and
    whose token dispatch is two lax.all_to_alls inside shard_map — the
    fourth collective pattern (token-routing-heavy, wire bytes ∝
    capacity, never vocab).  The batch is FIXED across widths (strong
    scaling), so per-device routed bytes stay ~flat as the mesh widens.
    Expert count adapts to the axis (2 experts per shard) so every
    width keeps whole experts per device."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.text.models.gpt import GPTMoEConfig, GPTMoEModel
    axes = dict(mesh.shape)
    axis = "ep" if axes.get("ep", 1) > 1 else "dp"
    n = max(1, axes.get(axis, 1))
    # the rules table reads FLAGS_moe_axis, so proposals and the
    # layer's own annotations must name the same axis
    set_flags({"FLAGS_moe_axis": axis})
    paddle.seed(0)
    cfg = GPTMoEConfig.tiny(vocab_size=64, hidden_size=16, layers=2,
                            heads=2, seq=32, experts=max(4, 2 * n),
                            top_k=2, capacity_factor=1.25)
    cfg.dropout = 0.0
    model = GPTMoEModel(cfg, mesh=mesh, dispatch="routed",
                        annotate=not _AUTOSHARD[0])
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=zero)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (8, 32))   # 256 tokens, fixed
    return step, (ids, ids.copy()), None


def _build_wide_deep(mesh, zero):
    """Sharded-embedding CTR step (ISSUE 10): the deep-leg table is
    row-partitioned over dp via ShardedEmbedding, so the compiled step
    carries the all-to-all routing pattern — dot-light, all-to-all-heavy,
    the collective mix the transformer zoo never produces.  The batch is
    FIXED across mesh widths (strong scaling: the table grows, the batch
    does not have to), so per-device routed bytes stay ~flat."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.rec.sharded_embedding import ShardedWideDeep
    paddle.seed(0)
    model = ShardedWideDeep(vocab=4096, emb_dim=16, num_slots=8,
                            dense_dim=8, hidden=(32, 16), mesh=mesh)
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=1e-3)
    step = TrainStep(model, opt, mesh=mesh, zero=zero)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 4096, (128, 8))
    dense = rng.randn(128, 8).astype("float32")
    labels = (rng.rand(128, 1) > 0.5).astype("float32")
    return step, (ids, dense, labels), None


BUILDERS = {"lenet": _build_lenet, "resnet_block": _build_resnet_block,
            "bert": _build_bert, "gpt": _build_gpt,
            "gpt_moe": _build_gpt_moe, "wide_deep": _build_wide_deep}


def audit_model(name: str, axes: dict, zero: int, suppress=()):
    """Build + AOT-lower + audit one zoo model over one mesh.  Returns an
    ``analysis.hlo.HloAuditResult``."""
    import jax
    from paddle_tpu.analysis import hlo as hlo_audit
    from paddle_tpu.parallel import make_mesh
    n = 1
    for v in axes.values():
        n *= v
    mesh = make_mesh(dict(axes), devices=jax.devices()[:n])
    step, inputs, label = BUILDERS[name](mesh, zero)
    return hlo_audit.audit_train_step(
        step, inputs, label, site=f"hlo_audit:zoo:{name}",
        suppress=suppress, do_emit=False)


def audit_seeded(axes: dict, zero: int):
    """The negative gate: the de-sharded ZeRO fixture over this mesh."""
    import jax
    from paddle_tpu.analysis import hlo as hlo_audit
    from paddle_tpu.analysis.hlo.fixtures import desharded_zero_step
    from paddle_tpu.parallel import make_mesh
    n = 1
    for v in axes.values():
        n *= v
    mesh = make_mesh(dict(axes), devices=jax.devices()[:n])
    step, inputs, label = desharded_zero_step(mesh, zero=zero)
    return hlo_audit.audit_train_step(
        step, inputs, label, site="hlo_audit:seeded", do_emit=False)


def audit_seeded_table(axes: dict):
    """Second negative gate: the de-sharded embedding-TABLE fixture —
    an annotated ``P('dp', None)`` table stored replicated must fail the
    annotation contract at ERROR, independent of any ZeRO stage."""
    import jax
    from paddle_tpu.analysis import hlo as hlo_audit
    from paddle_tpu.analysis.hlo.fixtures import desharded_table_step
    from paddle_tpu.parallel import make_mesh
    n = 1
    for v in axes.values():
        n *= v
    mesh = make_mesh(dict(axes), devices=jax.devices()[:n])
    step, inputs, label = desharded_table_step(mesh)
    return hlo_audit.audit_train_step(
        step, inputs, label, site="hlo_audit:seeded_table", do_emit=False)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="hlo_audit",
        description="compiled-program audit over zoo train steps on "
                    "virtual wide meshes (abstract AOT lowering; no "
                    "device execution, no chip)")
    ap.add_argument("--model", action="append", choices=sorted(BUILDERS),
                    help="audit one model (repeatable)")
    ap.add_argument("--zoo", action="store_true",
                    help="audit every zoo model")
    ap.add_argument("--mesh", action="append", default=None,
                    help="mesh spec DP[xMP[xSP]], repeatable "
                         "(default 4x2)")
    ap.add_argument("--zero", type=int, default=1, choices=(0, 1, 2, 3),
                    help="ZeRO stage for the train steps (default 1)")
    ap.add_argument("--seeded", action="store_true",
                    help="also audit the de-sharded-ZeRO negative "
                         "fixture (must produce ERROR findings)")
    ap.add_argument("--autoshard", action="store_true",
                    help="shard models via the FLAGS_autoshard=apply "
                         "rules engine (analysis.autoshard) instead of "
                         "their explicit annotation entry points")
    ap.add_argument("--strict", action="store_true",
                    help="exit non-zero if any ERROR finding fires")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit a JSON report instead of text")
    ap.add_argument("--suppress", default="",
                    help="comma-separated audit pass ids to skip")
    args = ap.parse_args(argv)

    meshes = [parse_mesh(s) for s in (args.mesh or ["4x2"])]
    names = list(args.model or [])
    if args.zoo or (not names and not args.seeded):
        names = sorted(BUILDERS)
    suppress = tuple(s.strip() for s in args.suppress.split(",")
                     if s.strip())

    import math
    need = max(math.prod(m.values()) for m in meshes)
    _provision(max(1, need))

    from paddle_tpu.analysis import hlo as hlo_audit
    if args.autoshard:
        from paddle_tpu.framework.flags import set_flags
        _AUTOSHARD[0] = True
        set_flags({"FLAGS_autoshard": "apply"})

    results, n_errors = [], 0
    for axes in meshes:
        label = "x".join(f"{a}{v}" for a, v in axes.items())
        for name in names:
            res = audit_model(name, axes, args.zero, suppress=suppress)
            n_errors += res.report.n_errors
            results.append((name, label, res))
        if args.seeded:
            res = audit_seeded(axes, args.zero or 1)
            n_errors += res.report.n_errors
            results.append(("seeded_desharded_zero", label, res))
            res_t = audit_seeded_table(axes)
            n_errors += res_t.report.n_errors
            results.append(("seeded_desharded_table", label, res_t))

    total = sum(len(r.report) for _, _, r in results)
    if args.as_json:
        payload = {
            "results": [{"model": n, **r.as_dict()}
                        for n, _m, r in results],
            "total_findings": total, "n_errors": n_errors,
            "strict": bool(args.strict),
            "ledger": [{"site": e["site"], "key": e["key"],
                        "ms": e["ms"]}
                       for e in hlo_audit.audit_compile_events()],
        }
        print(json.dumps(payload, indent=1))
    else:
        for name, mesh_label, res in results:
            head = (f"[{name} @ {mesh_label}] "
                    f"collectives={res.stats.collective_count} "
                    f"wire={res.stats.collective_wire_bytes / 1024:.1f}KiB "
                    f"hbm={res.stats.memory.get('peak_bytes', 0) / 1048576:.2f}MiB "
                    f"flops={res.stats.cost.get('flops', 0):.3g}")
            print(head)
            if res.report:
                print(res.report.format())
        print(f"hlo_audit: {len(results)} audit(s), {total} finding(s), "
              f"{n_errors} error(s)")
    return 1 if (args.strict and n_errors) else 0


if __name__ == "__main__":
    sys.exit(main())
