#!/usr/bin/env python
"""exec_cache — inspect, verify, and GC the persistent executable cache.

The CLI face of ``paddle_tpu.jit.persistent_cache`` (the on-disk AOT
executable cache behind ``FLAGS_executable_cache``):

    python tools/exec_cache.py list   --dir /cache [--json]
    python tools/exec_cache.py verify --dir /cache [--json]
    python tools/exec_cache.py gc     --dir /cache --max-gb 2 --max-age-days 7

``list`` prints one row per entry (digest, kind, site, payload size, age,
hit count, ledger-key head).  ``verify`` re-hashes every payload against
its sha256 manifest — rc != 0 on any torn/corrupt entry, so a CI lane can
gate a shared cache dir (the loader would invalidate these lazily at the
next warm start; verify surfaces them eagerly).  ``gc`` evicts entries
unused for ``--max-age-days``, then least-recently-used entries until the
payload total fits ``--max-gb``; orphan payloads (a dead writer's debris,
never loadable) always go.

``--dir`` defaults to ``PADDLE_TPU_EXEC_CACHE_DIR`` /
``FLAGS_executable_cache_dir``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _fmt_bytes(n):
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024


def _fmt_age(s):
    if s < 120:
        return f"{s:.0f}s"
    if s < 7200:
        return f"{s / 60:.0f}m"
    if s < 172800:
        return f"{s / 3600:.1f}h"
    return f"{s / 86400:.1f}d"


def cmd_list(cache, args):
    rows = cache.entries()
    report = {"dir": cache.dir, "entries": len(rows),
              "total_payload_bytes": cache.total_bytes(),
              "rows": [{k: m.get(k) for k in
                        ("digest", "kind", "site", "size", "age_s",
                         "hits", "key")} for m in rows]}
    if args.as_json:
        print(json.dumps(report, indent=1))
        return 0
    if not rows:
        print(f"{cache.dir}: empty")
        return 0
    for m in rows:
        key = (m.get("key") or "")[:48]
        print(f"{m['digest'][:16]}  {m.get('kind') or '?':>16}  "
              f"{_fmt_bytes(int(m.get('size', 0))):>9}  "
              f"age {_fmt_age(float(m['age_s'])):>6}  "
              f"hits {int(m.get('hits', 0)):>4}  "
              f"{m.get('site') or '?'}  {key}")
    print(f"{len(rows)} entries, "
          f"{_fmt_bytes(cache.total_bytes())} of payloads")
    return 0


def cmd_verify(cache, args):
    rows = cache.entries()
    bad = []
    for m in rows:
        ok, reason = cache.verify_entry(m["digest"])
        if not ok:
            bad.append({"digest": m["digest"], "reason": reason})
    # manifest-less payloads are torn writes: report them too
    orphans = []
    try:
        known = {m["digest"] for m in rows}
        for n in os.listdir(cache.dir):
            if n.endswith(".pjrt") and n[:-5] not in known:
                orphans.append(n)
    except OSError:
        pass
    report = {"dir": cache.dir, "entries": len(rows),
              "corrupt": bad, "orphan_payloads": orphans,
              "ok": not bad and not orphans}
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for b in bad:
            print(f"CORRUPT {b['digest'][:16]}: {b['reason']}")
        for o in orphans:
            print(f"ORPHAN  {o} (payload with no manifest)")
        print(f"verify: {len(rows)} entries, {len(bad)} corrupt, "
              f"{len(orphans)} orphaned")
    return 0 if report["ok"] else 1


def cmd_gc(cache, args):
    max_bytes = int(args.max_gb * (1 << 30)) if args.max_gb else None
    max_age_s = args.max_age_days * 86400 if args.max_age_days else None
    before = cache.total_bytes()
    removed = cache.gc(max_bytes=max_bytes, max_age_s=max_age_s)
    report = {"dir": cache.dir, "removed": removed,
              "bytes_before": before, "bytes_after": cache.total_bytes()}
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        print(f"gc: evicted {len(removed)} entries "
              f"({_fmt_bytes(before)} -> "
              f"{_fmt_bytes(report['bytes_after'])})")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="exec_cache",
        description="inspect/verify/GC the persistent executable cache")
    ap.add_argument("command", choices=("list", "verify", "gc"))
    ap.add_argument("--dir", default=None,
                    help="cache directory (default: "
                         "PADDLE_TPU_EXEC_CACHE_DIR / "
                         "FLAGS_executable_cache_dir)")
    ap.add_argument("--max-gb", type=float, default=None,
                    help="gc: evict LRU entries until payloads fit")
    ap.add_argument("--max-age-days", type=float, default=None,
                    help="gc: evict entries unused for this many days")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    from paddle_tpu.jit import persistent_cache as pcache
    d = args.dir or pcache.cache_dir()
    if not d:
        ap.error("--dir is required (or set PADDLE_TPU_EXEC_CACHE_DIR)")
    if not os.path.isdir(d):
        print(f"exec_cache: no such directory: {d}", file=sys.stderr)
        return 2
    cache = pcache.cache_at(d)
    if args.command == "gc" and args.max_gb is None \
            and args.max_age_days is None:
        ap.error("gc needs --max-gb and/or --max-age-days")
    return {"list": cmd_list, "verify": cmd_verify,
            "gc": cmd_gc}[args.command](cache, args)


if __name__ == "__main__":
    sys.exit(main())
