"""Measured-MFU audit for the bench workloads (VERDICT r4 weak #1/next #2).

For each compiled train step: FLOPs/step and bytes/step from XLA's own
``compile().cost_analysis()`` via the HLO-audit extraction surface
(``paddle_tpu.analysis.hlo.extract_cost`` — the op-level accounting the
reference does in operators/benchmark/op_tester.cc; ISSUE 8 re-based the
last hand-maintained cost model, the static LeNet epoch, onto
``Executor.epoch_executable`` so every number here comes from the program
XLA actually compiled), and per-step time from an IN-GRAPH K-step
``lax.fori_loop`` dispatched once — two K values, delta method, so
tunnel RTT and fence cost cancel exactly (PERF.md round-4 methodology:
block_until_ready does not fence the tunnel; a scalar fetch does).

Bounds (measured on this chip, PERF.md round-5 corrected table — the
round-4 67 TFLOP/s / 200-290 GB/s figures were un-chained-loop
artifacts):
  compute: 171.7 TFLOP/s (8192^3 bf16 matmul, chained in-graph delta-of-K)
  memory:  ~630 GB/s streaming copy R+W (same methodology)

NB: bytes/step from cost_analysis is PRE-FUSION algorithmic traffic
(every HLO op's operands counted as HBM accesses) — an upper bound, not
achieved HBM traffic; the memory fraction is indicative only.

Usage: PYTHONPATH=/root/repo python tools/mfu_audit.py [--dry] [workload ...]
Prints one JSON line per workload: flops/step, bytes/step, ms/step,
achieved TFLOP/s + GB/s, fraction of each bound, and which bound binds.

``--dry``: run every workload at a tiny CPU-safe configuration (resnet18
@32px b4, BERT-tiny, 2-layer transformer, 5-step LeNet epoch) so the whole
harness — TrainStep build, AOT lower, cost_analysis, chained delta-of-K
loop, JSON emit — is exercised end-to-end on the 8-virtual-device CPU
mesh.  The numbers are meaningless as MFU; the run proves the harness
can't silently rot between perf rounds (tests/test_mfu_audit_smoke.py).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

PEAK_TFLOPS = 171.7
BW_HI_GBS = 630.0

K_SMALL, K_LARGE = 3, 9


def _cost(compiled):
    """(flops, bytes_accessed) through the shared HLO-audit extraction
    (one implementation serves mfu_audit, hlo_audit and the dryrun
    scaling table)."""
    from paddle_tpu.analysis.hlo import extract_cost
    c = extract_cost(compiled)
    return c["flops"], c["bytes_accessed"]


def _loop_time(body, state, args, k_small=K_SMALL, k_large=K_LARGE,
               reps=3):
    """Per-step seconds via the DELTA of two in-graph loop lengths (the
    dispatch + fence overhead cancels exactly).  The chained-loss loop
    itself is shared with bench.py (_chained_step_loop): the loss rides
    the carry so XLA cannot dead-code-eliminate any step — returning only
    the step counter measured 6.6 ms for a 47 ms BERT step."""
    from bench import _chained_step_loop, _time_loop_once
    f = _chained_step_loop(body, args)
    times = {k: _time_loop_once(f, state, k, reps)
             for k in (k_small, k_large)}
    return (times[k_large] - times[k_small]) / (k_large - k_small)


def _emit(name, flops, bytes_, sec, units_per_step, unit, extra=None):
    tf = flops / sec / 1e12
    gbs = bytes_ / sec / 1e9
    frac_c = tf / PEAK_TFLOPS
    frac_m = gbs / BW_HI_GBS
    rec = {
        "workload": name,
        "flops_per_step": flops, "bytes_per_step": bytes_,
        "ms_per_step": round(sec * 1e3, 3),
        "throughput": round(units_per_step / sec, 1), "unit": unit,
        "achieved_tflops": round(tf, 2), "achieved_gbs": round(gbs, 1),
        "frac_of_peak_tflops": round(frac_c, 3),
        "frac_of_peak_gbs": round(frac_m, 3),
        "binding_bound": "compute" if frac_c >= frac_m else "memory",
    }
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)


def audit_resnet50(dry=False):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.ops.pallas import fused_conv
    from paddle_tpu.parallel import init_mesh, TrainStep
    from paddle_tpu.vision.models import resnet50, resnet18

    if dry:
        model, batch, hw = resnet18(data_format="NHWC"), 4, 32
    else:
        model, batch, hw = resnet50(data_format="NHWC"), 256, 224
    mesh = init_mesh({"dp": -1})
    opt = paddle.optimizer.Momentum(parameters=model.parameters(),
                                    learning_rate=0.1, momentum=0.9)
    step = TrainStep(model, opt, loss_fn=paddle.nn.CrossEntropyLoss(),
                     mesh=mesh, compute_dtype=None if dry else jnp.bfloat16,
                     donate=False)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, hw, hw, 3).astype("float32"))
    y = jnp.asarray(rng.randint(0, 1000, (batch,)))
    float(step((x,), y))          # build state + compile the plain step
    import jax
    body = step._build_step()
    lowered = jax.jit(body).lower(step.state, (x,), y, np.float32(0.1))
    flops, bytes_ = _cost(lowered.compile())
    ks = (1, 2) if dry else (K_SMALL, K_LARGE)
    sec = _loop_time(body, step.state, ((x,), y, np.float32(0.1)),
                     k_small=ks[0], k_large=ks[1], reps=1 if dry else 3)
    # record which conv path produced the number — a fused-conv
    # measurement must never be mistaken for an XLA-path one
    _emit("resnet50_dygraph", flops, bytes_, sec, batch, "img/s",
          extra={"pallas_conv": fused_conv.enabled(), "dry": dry})


def audit_bert(dry=False):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.parallel import init_mesh, TrainStep
    from paddle_tpu.text.models.bert import BertConfig, BertForPretraining

    if dry:
        cfg, batch, seq = BertConfig.tiny(seq=32), 8, 32
    else:
        cfg, batch, seq = BertConfig.base(), 64, 128
    mesh = init_mesh({"dp": -1})
    model = BertForPretraining(cfg)
    opt = paddle.optimizer.AdamW(parameters=model.parameters(),
                                 learning_rate=1e-4, weight_decay=0.01)
    step = TrainStep(model, opt, mesh=mesh,
                     compute_dtype=None if dry else jnp.bfloat16,
                     donate=False)
    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    n_pred = max(2, int(seq * 0.15))
    pos = np.stack([rng.choice(seq, size=n_pred, replace=False)
                    for _ in range(batch)]).astype("int64")
    labels = jnp.asarray(np.take_along_axis(np.asarray(ids), pos, 1))
    positions = jnp.asarray(pos)
    args = (ids, None, None, labels, None, positions)
    float(step(args))
    body = step._build_step()
    inputs = tuple(None if a is None else jnp.asarray(a) for a in args)
    lowered = __import__("jax").jit(body).lower(
        step.state, inputs, None, np.float32(1e-4))
    flops, bytes_ = _cost(lowered.compile())
    ks = (1, 2) if dry else (K_SMALL, K_LARGE)
    sec = _loop_time(body, step.state, (inputs, None, np.float32(1e-4)),
                     k_small=ks[0], k_large=ks[1], reps=1 if dry else 3)
    _emit("bert_base_pretrain", flops, bytes_, sec, batch, "seq/s",
          extra={"dry": dry})


def audit_transformer_big(dry=False):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.parallel import init_mesh, TrainStep
    from bench import bench_transformer_big  # noqa: F401  (same model class)
    import paddle_tpu.nn as nn

    if dry:
        vocab, dm, nh, nl, ffn, batch, seq = 128, 32, 2, 2, 64, 2, 16
    else:
        vocab, dm, nh, nl, ffn, batch, seq = 32768, 1024, 16, 6, 4096, 64, 64

    class Seq2SeqLM(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(vocab, dm)
            self.pos = nn.Embedding(seq, dm)
            self.core = nn.Transformer(
                d_model=dm, nhead=nh, num_encoder_layers=nl,
                num_decoder_layers=nl, dim_feedforward=ffn, dropout=0.0)
            self.proj = nn.Linear(dm, vocab)
            self.loss = nn.CrossEntropyLoss()

        def forward(self, src, tgt, labels):
            p = paddle.arange(src.shape[1])
            s = self.embed(src) + self.pos(p)
            t = self.embed(tgt) + self.pos(p)
            h = self.core(s, t)
            logits = self.proj(h)
            return self.loss(logits.reshape([-1, logits.shape[-1]]),
                             labels.reshape([-1]))

    mesh = init_mesh({"dp": -1})
    model = Seq2SeqLM()
    opt = paddle.optimizer.Adam(parameters=model.parameters(),
                                learning_rate=1e-4)
    step = TrainStep(model, opt, mesh=mesh,
                     compute_dtype=None if dry else jnp.bfloat16,
                     donate=False)
    rng = np.random.RandomState(0)
    src = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    tgt = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    lbl = jnp.asarray(rng.randint(0, vocab, (batch, seq)))
    float(step((src, tgt, lbl)))
    body = step._build_step()
    lowered = __import__("jax").jit(body).lower(
        step.state, (src, tgt, lbl), None, np.float32(1e-4))
    flops, bytes_ = _cost(lowered.compile())
    ks = (1, 2) if dry else (K_SMALL, K_LARGE)
    sec = _loop_time(body, step.state, ((src, tgt, lbl), None,
                                        np.float32(1e-4)),
                     k_small=ks[0], k_large=ks[1], reps=1 if dry else 3)
    _emit("transformer_big", flops, bytes_, sec, batch * seq, "tok/s",
          extra={"dry": dry})


def audit_lenet(dry=False):
    """LeNet's scanned epoch is ONE dispatch; FLOPs/bytes from
    cost_analysis of the SAME scanned program via
    ``Executor.epoch_executable`` (ISSUE 8: the hand-maintained per-layer
    FLOP count is gone — it could silently drift from the compiled
    program), per-step time from epoch time / steps."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    import paddle_tpu.static as static

    batch, steps = (8, 5) if dry else (128, 200)
    paddle.enable_static()
    try:
        main, startup = static.Program(), static.Program()
        with static.program_guard(main, startup):
            img = static.data("img", [None, 1, 28, 28], "float32")
            label = static.data("label", [None], "int64")
            h = static.nn.conv2d(img, 6, 5, padding=2, act="relu")
            h = paddle.nn.functional.max_pool2d(h, 2, 2)
            h = static.nn.conv2d(h, 16, 5, act="relu")
            h = paddle.nn.functional.max_pool2d(h, 2, 2)
            h = paddle.flatten(h, start_axis=1)
            h = static.nn.fc(h, 120, activation="relu")
            h = static.nn.fc(h, 84, activation="relu")
            logits = static.nn.fc(h, 10)
            loss = paddle.nn.functional.cross_entropy(logits, label)
            paddle.optimizer.SGD(learning_rate=0.01).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        stacks = {"img": jnp.asarray(rng.randn(steps, batch, 1, 28, 28)
                                     .astype("float32")),
                  "label": jnp.asarray(rng.randint(0, 10, (steps, batch))
                                       .astype("int64"))}
        exe.train_from_dataset(main, dataset=stacks, fetch_list=[loss])
        best = None
        for _ in range(1 if dry else 3):
            t0 = time.perf_counter()
            out = exe.train_from_dataset(main, dataset=stacks,
                                         fetch_list=[loss])
            float(np.asarray(out[loss.name]).sum())
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        # FLOPs/bytes of the scanned epoch program itself (the executor's
        # lowered-executable surface): per-step = epoch totals / steps
        epoch_exe = exe.epoch_executable(main, dataset=stacks,
                                         fetch_list=[loss])
        ep_flops, ep_bytes = _cost(epoch_exe)
        sec = best / steps
        _emit("mnist_lenet_static", ep_flops / steps, ep_bytes / steps,
              sec, batch, "img/s", extra={"dry": dry})
    finally:
        paddle.disable_static()


AUDITS = {
    "resnet50_dygraph": audit_resnet50,
    "bert_base_pretrain": audit_bert,
    "transformer_big": audit_transformer_big,
    "mnist_lenet_static": audit_lenet,
}


if __name__ == "__main__":
    argv = sys.argv[1:]
    dry = "--dry" in argv
    names = [a for a in argv if a != "--dry"] or list(AUDITS)
    for n in names:
        print(f"[mfu] {n} ...", file=sys.stderr, flush=True)
        AUDITS[n](dry=dry)
