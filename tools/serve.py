#!/usr/bin/env python
"""serve — export zoo models, warm a serving engine, drive traffic, check SLOs.

The CLI face of ``paddle_tpu.serving``: the whole deploy walkthrough
(export → warm-up → serve → SLO check) in one command, runnable on any
backend (defaults to CPU, like tools/graph_lint.py).

    python tools/serve.py --model lenet --duration 2 --clients 4
    python tools/serve.py --model lenet --model bert --int8 --json
    python tools/serve.py --model resnet_block --p99-slo-ms 250 --json

Exit code is non-zero when any request errored, any steady-state XLA
compile was recorded after warm-up (the bucketed-batching invariant), or
a ``--p99-slo-ms`` bound was violated — so a CI lane can gate on it.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# serving smoke runs anywhere the framework imports; explicit env wins
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def build_lenet():
    from paddle_tpu.vision.models import LeNet
    return LeNet(), [([None, 1, 28, 28], "float32")]


def build_resnet_block(ch=8, hw=8):
    import paddle_tpu.nn as nn

    class Block(nn.Layer):
        """One residual conv-BN-ReLU pair (bench.py's high-res stage)."""

        def __init__(self):
            super().__init__()
            self.c1 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
            self.b1 = nn.BatchNorm2D(ch)
            self.c2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
            self.b2 = nn.BatchNorm2D(ch)
            self.relu = nn.ReLU()

        def forward(self, x):
            h = self.relu(self.b1(self.c1(x)))
            return self.relu(self.b2(self.c2(h)) + x)

    return Block(), [([None, ch, hw, hw], "float32")]


def build_bert(seq=32):
    from paddle_tpu.text.models.bert import BertConfig, BertModel
    cfg = BertConfig.tiny(seq=seq)
    m = BertModel(cfg)
    m._serve_vocab = cfg.vocab_size
    return m, [([None, seq], "int32")]


ZOO = {
    "lenet": build_lenet,
    "resnet_block": build_resnet_block,
    "bert": build_bert,
}


def build_gpt_decode(vocab=128, seq=128):
    from paddle_tpu.text.models.gpt import GPTConfig, GPTModel
    m = GPTModel(GPTConfig.tiny(vocab_size=vocab, hidden_size=32,
                                layers=2, heads=2, seq=seq))
    m.eval()
    m._serve_vocab = vocab
    return m


def _decode_traffic(server, name, duration_s, clients, max_rows,
                    max_prompt, max_new, vocab, seed):
    """Concurrent mixed prefill/decode traffic: each client submits
    random-row requests of random-length prompts (spanning the prefill
    bucket ladder) with random generation budgets, and checks the result
    shape; per-client error capture."""
    errors = []
    deadline = time.perf_counter() + duration_s

    def client(i):
        rng = np.random.RandomState(seed + i)
        while time.perf_counter() < deadline:
            rows = int(rng.randint(1, max_rows + 1))
            prompts = [rng.randint(1, vocab,
                                   int(rng.randint(1, max_prompt + 1)))
                       for _ in range(rows)]
            mn = int(rng.randint(1, max_new + 1))
            try:
                out = server.submit_decode(
                    name, prompts, max_new_tokens=mn).result(timeout=60)
                if out[0].shape != (rows, mn):
                    raise AssertionError(
                        f"decode shape {out[0].shape} != ({rows}, {mn})")
            except Exception as e:   # noqa: BLE001 — reported per client
                errors.append(f"client{i}: {type(e).__name__}: {e}")
                return
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


def _random_inputs(rng, specs, rows, vocab=None):
    out = []
    for shape, dtype in specs:
        s = (rows,) + tuple(shape[1:])
        if np.issubdtype(np.dtype(dtype), np.integer):
            out.append(rng.randint(0, vocab or 100, s).astype(dtype))
        else:
            out.append(rng.randn(*s).astype(dtype))
    return out


def _traffic(server, name, specs, duration_s, clients, max_rows, vocab,
             seed):
    """Concurrent mixed-shape traffic: each client submits random-row
    requests until the deadline; per-client error capture."""
    errors = []
    deadline = time.perf_counter() + duration_s

    def client(i):
        rng = np.random.RandomState(seed + i)
        while time.perf_counter() < deadline:
            rows = int(rng.randint(1, max_rows + 1))
            try:
                fut = server.submit(
                    name, _random_inputs(rng, specs, rows, vocab))
                outs = fut.result(timeout=60)
                if outs[0].shape[0] != rows:
                    raise AssertionError(
                        f"padding leaked: {outs[0].shape[0]} != {rows}")
            except Exception as e:   # noqa: BLE001 — reported per client
                errors.append(f"client{i}: {type(e).__name__}: {e}")
                return
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return errors


def _replica_child(cfg_path):
    """Replica-process entry (spawned by --router): build the configured
    models, start a Server, and serve RPC until killed.  Deterministic
    by construction — every replica seeds identically, so all replicas
    hold bit-identical weights and the router's answers do not depend
    on which replica served them."""
    with open(cfg_path) as f:
        cfg = json.load(f)
    import paddle_tpu as paddle
    from paddle_tpu import serving
    from paddle_tpu.framework.flags import set_flags
    from paddle_tpu.serving.cluster import replica_main
    set_flags({"FLAGS_serving_role": cfg.get("role", "both"),
               "FLAGS_router_heartbeat_s": float(cfg["heartbeat_s"])})
    if cfg.get("session_store"):
        # stateful replica: slot-loop decode + prefix cache + parked-
        # session store.  The spill dir is SHARED across the fleet —
        # that is what makes the SIGKILL drill stateful: a survivor
        # restores the victim's parked conversations from disk.
        from paddle_tpu.framework.flags import flag as _flag
        sess = {"FLAGS_session_store": True,
                "FLAGS_session_store_dir":
                    cfg.get("session_store_dir") or "",
                "FLAGS_prefix_cache": True}
        if not int(_flag("decode_slots")):
            sess["FLAGS_decode_slots"] = 4
        set_flags(sess)
    if cfg.get("cache_dir"):
        set_flags({"FLAGS_executable_cache": "readwrite",
                   "FLAGS_executable_cache_dir": cfg["cache_dir"]})
    if cfg.get("trace") and cfg["trace"] != "off":
        # spans ship to the router through the scrape op's export
        # buffer — no per-replica trace dir needed
        set_flags({"FLAGS_trace": cfg["trace"]})
    if cfg.get("flight_dir"):
        set_flags({"FLAGS_flight_dir": cfg["flight_dir"],
                   "FLAGS_flight_interval_s":
                       float(cfg.get("flight_interval_s", 0.5))})
    paddle.seed(cfg["seed"])
    buckets = tuple(cfg["buckets"])
    server = serving.Server(serving.ServingConfig(
        workers=cfg.get("workers"), buckets=buckets,
        version=cfg.get("version")))
    for tenant, pol in (cfg.get("tenant_policies") or {}).items():
        server.set_tenant_policy(tenant, **pol)
    with tempfile.TemporaryDirectory() as d:
        for name in cfg["models"]:
            layer, specs = ZOO[name]()
            layer.eval()
            prefix = os.path.join(d, name)
            serving.export_for_serving(layer, prefix, specs,
                                       buckets=buckets)
            server.register(name, prefix, buckets=buckets)
        if cfg.get("decode"):
            seq_buckets = tuple(cfg["seq_buckets"])
            gpt = build_gpt_decode()
            server.register_decode(
                "gpt_decode", gpt, batch_buckets=buckets,
                seq_buckets=seq_buckets, max_new_tokens=cfg["max_new"],
                max_len=max(seq_buckets) + cfg["max_new"])
        replica_main(server, replica_id=cfg["id"],
                     store_host=cfg["store_host"],
                     store_port=cfg["store_port"],
                     port=int(cfg.get("port", 0)), block=True,
                     heldout=bool(cfg.get("heldout")))
    return 0


def _router_main(args):
    """--router mode: spawn FLAGS_serving_replicas replica subprocesses,
    rendezvous them through a TCPStore, route sustained traffic through
    the front-end Router, optionally SIGKILL one replica mid-traffic
    (--kill-one: the heartbeat evict + redistribution drill), and gate
    the exit code on traffic errors, per-replica steady-state compiles,
    SLOs, and the eviction actually happening."""
    import signal
    import subprocess

    from paddle_tpu.distributed.fleet.base.tcp_store import TCPStore
    from paddle_tpu.framework.flags import flag as _flag, set_flags
    from paddle_tpu.serving.cluster import ClusterObserver, Router, \
        serve_cluster_metrics

    n = args.replicas if args.replicas is not None \
        else int(_flag("serving_replicas"))
    if args.disaggregate and (not args.decode or n < 2):
        print("--disaggregate needs --decode and --replicas >= 2",
              file=sys.stderr)
        return 2
    names = list(dict.fromkeys(
        args.model or ([] if args.decode else ["lenet"])))
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    seq_buckets = tuple(int(b) for b in args.seq_buckets.split(",")
                        if b.strip())
    report = {"router": True, "replicas": n,
              "disaggregate": bool(args.disaggregate),
              "duration_s": args.duration, "clients": args.clients,
              "models": {}, "replica_stats": {}}
    rc = 0
    trace_mode = "off"
    if args.trace_dir:
        # the router's own route/dispatch spans need tracing ON; they
        # reach the merged JSONL through the observer's export-buffer
        # drain, NOT a per-process trace dir (that would double-write)
        if str(_flag("trace")).lower() == "off":
            set_flags({"FLAGS_trace": "full"})
        trace_mode = str(_flag("trace")).lower()
        report["trace_dir"] = args.trace_dir
        report["trace_mode"] = trace_mode
    if args.flight_dir:
        os.makedirs(args.flight_dir, exist_ok=True)
        report["flight_dir"] = args.flight_dir
    store = TCPStore("127.0.0.1", 0, is_master=True)
    children, router = [], None
    obs = cluster_metrics_srv = sess_traffic = None
    cfg_dir = tempfile.mkdtemp(prefix="serve_router_")
    sess_dir = ""
    if args.sessions:
        sess_dir = os.path.join(cfg_dir, "sessions")
        os.makedirs(sess_dir, exist_ok=True)
        report["sessions_dir"] = sess_dir
    try:
        for i in range(n):
            role = "both"
            if args.disaggregate:
                # alternate so both pools exist at every cluster size
                role = "prefill" if i % 2 == 0 else "decode"
            cfg = {"id": f"replica{i}", "role": role, "seed": args.seed,
                   "session_store": bool(args.sessions),
                   "session_store_dir": sess_dir,
                   "models": names, "decode": bool(args.decode),
                   "buckets": list(buckets),
                   "seq_buckets": list(seq_buckets),
                   "max_new": args.max_new, "workers": args.workers,
                   "store_host": "127.0.0.1", "store_port": store.port,
                   "heartbeat_s": float(_flag("router_heartbeat_s")),
                   "cache_dir": args.cache_dir,
                   "trace": trace_mode,
                   "flight_dir": args.flight_dir,
                   "flight_interval_s": 0.5}
            path = os.path.join(cfg_dir, f"replica{i}.json")
            with open(path, "w") as f:
                json.dump(cfg, f)
            children.append(subprocess.Popen(
                [sys.executable, os.path.abspath(__file__),
                 "--replica-config", path],
                stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT))
        router = Router(store=store)
        # the cluster observability plane: federation + trace assembly
        # + ClusterSignals, driven by the router's watch loop
        obs = ClusterObserver(router, trace_dir=args.trace_dir)
        router.attach_observer(obs)
        if args.metrics_port is not None:
            cluster_metrics_srv = serve_cluster_metrics(
                obs, port=args.metrics_port)
            report["metrics_port"] = cluster_metrics_srv.port
        t0 = time.perf_counter()
        deadline = t0 + 300
        while router.replicas_live() < n:
            if time.perf_counter() > deadline:
                report["error"] = (f"only {router.replicas_live()}/{n} "
                                   "replicas joined within 300s")
                return _router_report(report, args, 1)
            for p in children:
                if p.poll() not in (None, 0):
                    report["error"] = \
                        f"replica exited rc={p.returncode} during warm-up"
                    return _router_report(report, args, 1)
            time.sleep(0.2)
        report["warmup_s"] = round(time.perf_counter() - t0, 3)

        killed = {"id": None}
        if args.kill_one:
            # kill mid-traffic from a side thread: the drill is traffic
            # REDISTRIBUTING, not a clean restart
            def killer():
                time.sleep(max(0.2, args.duration / 3))
                victim = children[-1]
                killed["id"] = f"replica{n - 1}"
                victim.send_signal(signal.SIGKILL)
            threading.Thread(target=killer, daemon=True).start()

        model_meta = {name: ZOO[name]() for name in names}
        errors = []
        if args.decode and args.sessions:
            # stateful leg rides ALONGSIDE the one-shot traffic (mixed
            # workload); with --kill-one the SIGKILL lands mid-turn and
            # the gate below demands zero lost sessions anyway
            sess_traffic = _SessionTraffic(
                router, "gpt_decode", seq_buckets, args.max_new,
                clients=max(2, args.clients // 2),
                seed=args.seed + 31).start()
        if args.decode:
            errors += _decode_traffic(
                router, "gpt_decode", args.duration, args.clients,
                args.max_request_rows, max(seq_buckets), args.max_new,
                128, args.seed)
        for name in names:
            layer, specs = model_meta[name]
            errors += _traffic(router, name, specs, args.duration,
                               args.clients, args.max_request_rows,
                               getattr(layer, "_serve_vocab", None),
                               args.seed)
        report["traffic_errors"] = errors
        if errors:
            rc = 1
        if sess_traffic is not None:
            sess_traffic.stop()
            report["sessions"] = sess_traffic.report()
            rc = _gate_sessions(report, args, rc)

        if args.kill_one:
            # the dead replica must be EVICTED by heartbeat, traffic
            # already redistributed (no errors above past the ack)
            stale = float(_flag("router_stale_after_s"))
            hb = float(_flag("router_heartbeat_s"))
            evict_deadline = time.perf_counter() + stale + 4 * hb + 10
            while router.replicas_live() > n - 1:
                if time.perf_counter() > evict_deadline:
                    break
                time.sleep(0.2)
            report["kill_one"] = {
                "victim": killed["id"],
                "evicted": router.replicas_live() == n - 1}
            if not report["kill_one"]["evicted"]:
                rc = 1
            if args.flight_dir and killed["id"]:
                # SIGKILL leaves no exit path — the victim's evidence is
                # whatever its flight recorder last persisted atomically
                pm = os.path.join(args.flight_dir,
                                  f"postmortem_{killed['id']}.json")
                report["kill_one"]["postmortem"] = pm
                report["kill_one"]["postmortem_exists"] = \
                    os.path.exists(pm)

        steady_total = 0
        for h in router.handles():
            if not h.alive:
                continue
            try:
                st = h.model_stats()
                hl = h.health()
            except Exception as e:   # noqa: BLE001 — reported, gated
                report["replica_stats"][h.id] = \
                    {"error": f"{type(e).__name__}: {e}"}
                rc = 1
                continue
            steady_total += int(hl.get("steady_compiles", 0))
            report["replica_stats"][h.id] = st
            if args.p99_slo_ms is not None:
                worst = max((m["p99_ms"] for m in st.values()
                             if m.get("completed")), default=0.0)
                if worst > args.p99_slo_ms:
                    rc = 1
        report["steady_compiles"] = steady_total
        if steady_total:
            rc = 1
        report["router_stats"] = router.stats()
        # final federation round on OUR clock: drain the last spans and
        # dumps so the merged trace / textfile include end-of-run state
        sig = obs.poll()
        report["cluster_signals"] = sig.to_dict()
        report["observer"] = obs.stats()
        if cluster_metrics_srv is not None:
            import urllib.request
            try:
                with urllib.request.urlopen(
                        "http://127.0.0.1:"
                        f"{cluster_metrics_srv.port}/metrics",
                        timeout=10) as resp:
                    body = resp.read().decode()
                report["metrics_scrape_ok"] = (
                    resp.status == 200
                    and "cluster_signals_replicas_live" in body)
            except Exception as e:   # noqa: BLE001 — reported, gated
                report["metrics_scrape_ok"] = False
                report["metrics_scrape_error"] = \
                    f"{type(e).__name__}: {e}"
            if not report["metrics_scrape_ok"]:
                rc = 1
        if args.metrics_textfile:
            report["metrics_textfile"] = \
                obs.write_textfile(args.metrics_textfile)
    finally:
        if sess_traffic is not None:
            sess_traffic.stop()
        if cluster_metrics_srv is not None:
            cluster_metrics_srv.close()
        if obs is not None:
            obs.close()
        if router is not None:
            router.close()
        for p in children:
            if p.poll() is None:
                p.terminate()
        for p in children:
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 — last resort
                p.kill()
        store.close()
    return _router_report(report, args, rc)


def _free_port():
    import socket
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _BgTraffic:
    """Open-loop background clients that run until told to stop — the
    ramp drill's phases (scale-up, drain-down, rollout legs) have no
    fixed traffic deadline, so the deadline-based _traffic helpers do
    not fit.  Each success is wall-stamped so the report can compute
    windowed p99s (the tenant-isolation control/burst comparison);
    quota rejections (UnavailableError with a retry_after hint, when
    ``count_rejections``) are tallied, not fatal — every other client
    exception is a drill-failing error."""

    def __init__(self, router, dense, decode, seq_buckets, max_new,
                 clients, seed, tenant="default", vocab=128, max_rows=2,
                 timeout=120.0, count_rejections=False):
        self._router = router
        self._dense = dense              # [(name, specs, vocab), ...]
        self._decode = bool(decode)
        self._max_prompt = max(seq_buckets)
        self._max_new = max_new
        self._clients = int(clients)
        self._seed = int(seed)
        self.tenant = str(tenant)
        self._vocab = int(vocab)
        self._max_rows = int(max_rows)
        self._timeout = float(timeout)
        self._count_rejections = bool(count_rejections)
        self._stop = threading.Event()
        self._threads = []
        self._lock = threading.Lock()
        self.errors = []
        self.rejections = 0
        self.latencies = []              # (wall_ts, seconds) per success

    def _client(self, i):
        from paddle_tpu.framework.enforce import UnavailableError
        rng = np.random.RandomState(self._seed + i)
        while not self._stop.is_set():
            rows = int(rng.randint(1, self._max_rows + 1))
            use_decode = self._decode and (not self._dense
                                           or rng.rand() < 0.5)
            t0 = time.perf_counter()
            try:
                if use_decode:
                    prompts = [rng.randint(
                        1, self._vocab,
                        int(rng.randint(1, self._max_prompt + 1)))
                        for _ in range(rows)]
                    mn = int(rng.randint(1, self._max_new + 1))
                    out = self._router.submit_decode(
                        "gpt_decode", prompts, max_new_tokens=mn,
                        timeout=self._timeout,
                        tenant=self.tenant).result(timeout=self._timeout)
                    if out[0].shape != (rows, mn):
                        raise AssertionError(
                            f"decode shape {out[0].shape} != ({rows},{mn})")
                else:
                    name, specs, vocab = \
                        self._dense[rng.randint(len(self._dense))]
                    outs = self._router.submit(
                        name, _random_inputs(rng, specs, rows, vocab),
                        timeout=self._timeout,
                        tenant=self.tenant).result(timeout=self._timeout)
                    if outs[0].shape[0] != rows:
                        raise AssertionError(
                            f"padding leaked: {outs[0].shape[0]} != {rows}")
                with self._lock:
                    self.latencies.append(
                        (time.time(), time.perf_counter() - t0))
            except UnavailableError as e:
                if self._count_rejections \
                        and getattr(e, "retry_after_s", None) is not None:
                    with self._lock:
                        self.rejections += 1
                    self._stop.wait(min(1.0, float(e.retry_after_s)))
                    continue
                with self._lock:
                    self.errors.append(
                        f"{self.tenant}/client{i}: "
                        f"{type(e).__name__}: {e}")
                return
            except Exception as e:   # noqa: BLE001 — reported, gated
                with self._lock:
                    self.errors.append(
                        f"{self.tenant}/client{i}: "
                        f"{type(e).__name__}: {e}")
                return

    def start(self):
        self._threads = [
            threading.Thread(target=self._client, args=(i,), daemon=True)
            for i in range(self._clients)]
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self._timeout + 30)

    def p99_ms(self, t0=None, t1=None):
        with self._lock:
            lats = [s for (ts, s) in self.latencies
                    if (t0 is None or ts >= t0)
                    and (t1 is None or ts <= t1)]
        if not lats:
            return None
        return round(float(np.percentile(
            np.asarray(lats) * 1e3, 99)), 3)


class _SessionTraffic:
    """Multi-turn conversation clients (the --sessions traffic mode).

    Each client keeps extending conversations: a turn submits the FULL
    transcript so far plus a fresh user suffix under a stable
    ``session_id``, appends whatever the server generated, and comes
    back for the next turn until the transcript no longer fits the
    prompt ladder (then that conversation ends and a new one starts).
    Every ``verify_every``-th follow-up turn the same transcript is
    ALSO submitted WITHOUT a session_id — the stateless prefill is the
    bit-exactness oracle: a session restore that is not bit-identical
    to plain serving is an error, not a slowdown.

    A turn that bounces with a retryable UnavailableError (drain park,
    replica death mid-flight) retries until the turn deadline; a turn
    that never lands counts as a LOST session — the stateful drills
    gate rc on zero of those.  Works against a Server or a Router:
    both expose ``submit_decode(..., session_id=...) -> Future``.
    """

    def __init__(self, target, model, seq_buckets, max_new, clients,
                 seed, vocab=128, verify_every=4, turn_timeout=120.0):
        self._target = target
        self._model = model
        self._max_prompt = max(seq_buckets)
        self._max_new = int(max_new)
        self._clients = int(clients)
        self._seed = int(seed)
        self._vocab = int(vocab)
        self._verify_every = max(1, int(verify_every))
        self._timeout = float(turn_timeout)
        self._stop = threading.Event()
        self._threads = []
        self._lock = threading.Lock()
        self.errors = []
        self.lost = 0
        self.turns = 0
        self.follow_ups = 0
        self.conversations = 0
        self.verified = 0
        self.mismatches = 0
        self.latencies = []              # (wall_ts, seconds, turn_idx)

    def _decode(self, prompt, sid):
        fut = self._target.submit_decode(
            self._model, [prompt], max_new_tokens=self._max_new,
            timeout=self._timeout, session_id=sid)
        return np.asarray(fut.result(timeout=self._timeout)[0])[0]

    def _turn(self, prompt, sid):
        from paddle_tpu.framework.enforce import UnavailableError
        deadline = time.monotonic() + self._timeout
        while True:
            try:
                return self._decode(prompt, sid)
            except UnavailableError as e:
                # drain bounce / parked mid-flight: the transcript is
                # client-held state, so the turn is safely retryable
                if time.monotonic() > deadline or self._stop.is_set():
                    raise
                time.sleep(min(1.0,
                               float(getattr(e, "retry_after_s", None)
                                     or 0.05)))

    def _client(self, i):
        rng = np.random.RandomState(self._seed + 7919 * (i + 1))
        transcript, sid, turn, conv = None, None, 0, 0
        while not self._stop.is_set():
            if transcript is None:
                conv += 1
                sid = f"client{i}-conv{conv}"
                turn = 0
                transcript = rng.randint(
                    1, self._vocab,
                    int(rng.randint(2, max(3, self._max_prompt // 4)))
                ).astype(np.int32)
                with self._lock:
                    self.conversations += 1
            else:
                transcript = np.concatenate(
                    [transcript, rng.randint(1, self._vocab,
                                             int(rng.randint(1, 5))
                                             ).astype(np.int32)])
            if transcript.size > self._max_prompt:
                transcript = None        # conversation outgrew the
                continue                 # ladder — retire it
            t0 = time.perf_counter()
            try:
                got = self._turn(transcript, sid)
            except Exception as e:   # noqa: BLE001 — a lost session
                with self._lock:
                    self.errors.append(f"{sid} turn{turn}: "
                                       f"{type(e).__name__}: {e}")
                    self.lost += 1
                transcript = None
                continue
            with self._lock:
                self.turns += 1
                self.follow_ups += bool(turn)
                self.latencies.append(
                    (time.time(), time.perf_counter() - t0, turn))
                check = turn and self.follow_ups % self._verify_every == 0
            if check:
                try:
                    want = self._turn(transcript, None)
                except Exception:   # noqa: BLE001 — the oracle leg
                    pass            # bounced; it only counts when run
                else:
                    with self._lock:
                        self.verified += 1
                        if not np.array_equal(got, want):
                            self.mismatches += 1
                            self.errors.append(
                                f"{sid} turn{turn}: session continuation"
                                " != stateless prefill")
            transcript = np.concatenate(
                [transcript, np.asarray(got, np.int32)])
            turn += 1

    def start(self):
        self._threads = [
            threading.Thread(target=self._client, args=(i,), daemon=True)
            for i in range(self._clients)]
        for t in self._threads:
            t.start()
        return self

    def stop(self):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self._timeout + 30)

    @staticmethod
    def _p99(lats):
        if not lats:
            return None
        return round(float(np.percentile(np.asarray(lats) * 1e3, 99)), 3)

    def report(self):
        with self._lock:
            return {"turns": self.turns, "follow_ups": self.follow_ups,
                    "conversations": self.conversations,
                    "lost_sessions": self.lost,
                    "verified_turns": self.verified,
                    "bit_mismatches": self.mismatches,
                    "p99_ms": self._p99(
                        [s for (_, s, _) in self.latencies]),
                    "follow_up_p99_ms": self._p99(
                        [s for (_, s, t) in self.latencies if t]),
                    "errors": list(self.errors)}


def _gate_sessions(report, args, rc):
    """Shared rc gate of the stateful traffic modes: zero lost
    sessions, zero bit-exactness mismatches, zero client errors, at
    least one follow-up turn actually exercised, and (when set) the
    p99 SLO over whole turns."""
    sess = report["sessions"]
    if sess["errors"] or sess["lost_sessions"] or sess["bit_mismatches"] \
            or not sess["follow_ups"]:
        rc = 1
    if args.p99_slo_ms is not None and sess["p99_ms"] is not None \
            and sess["p99_ms"] > args.p99_slo_ms:
        sess["p99_slo_violated"] = True
        rc = 1
    return rc


def _ramp_main(args):
    """--ramp N: the elastic-lifecycle drill.  One seed replica boots,
    sustained mixed traffic starts and NEVER stops; the cluster then
    scales 1 -> N -> 1 through the AutoscaleController (scale-down is
    graceful drain — rc gates on every retirement reporting drained,
    zero heartbeat evictions, zero client errors, zero steady-state
    compiles).  A tenant-burst window measures per-tenant admission
    isolation, and --rollout adds zero-downtime rolling-update legs:
    happy path behind the canary bit-match gate, an optional mid-rollout
    SIGKILL (--rollout-kill, journal-resume + postmortem gates), and a
    fault-forced canary rollback that must leave the old version
    serving."""
    import signal
    import subprocess

    from paddle_tpu.distributed.fleet.base.tcp_store import TCPStore
    from paddle_tpu.framework.flags import flag as _flag
    from paddle_tpu.profiler.metrics import default_registry
    from paddle_tpu.serving.cluster import (AutoscaleController,
                                            ClusterObserver, RemoteReplica,
                                            RollingUpdate, Router, RpcClient)
    from paddle_tpu.testing import faults as _faults

    n_top = int(args.ramp)
    if n_top < 2:
        print("--ramp needs N >= 2", file=sys.stderr)
        return 2
    names = list(dict.fromkeys(
        args.model or ([] if args.decode else ["lenet"])))
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    seq_buckets = tuple(int(b) for b in args.seq_buckets.split(",")
                        if b.strip())
    report = {"ramp": n_top, "duration_s": args.duration,
              "clients": args.clients, "models": names,
              "decode": bool(args.decode), "replica_stats": {}}
    rc = 0
    if args.flight_dir:
        os.makedirs(args.flight_dir, exist_ok=True)
        report["flight_dir"] = args.flight_dir
    store = TCPStore("127.0.0.1", 0, is_master=True)
    cfg_dir = tempfile.mkdtemp(prefix="serve_ramp_")
    # a shared executable cache is what makes elastic scale-up viable:
    # the seed replica compiles once, every later spawn boots O(load)
    cache_dir = args.cache_dir or os.path.join(cfg_dir, "exec_cache")
    os.makedirs(cache_dir, exist_ok=True)
    sess_dir = ""
    if args.sessions:
        # one spill dir for the WHOLE fleet: every spawn (including
        # rollout canaries) sees the same parked sessions, so a
        # SIGKILLed replica's conversations outlive it on disk
        sess_dir = os.path.join(cfg_dir, "sessions")
        os.makedirs(sess_dir, exist_ok=True)
        report["sessions_dir"] = sess_dir
    children = {}                        # replica id -> Popen
    router = obs = traffic = burst_router = sess_traffic = None

    def _cfg_for(rid, version=None, store_on=True, port=0,
                 heldout=False):
        return {"id": rid, "role": "both", "seed": args.seed,
                "heldout": heldout,
                "session_store": bool(args.sessions),
                "session_store_dir": sess_dir,
                "models": names, "decode": bool(args.decode),
                "buckets": list(buckets),
                "seq_buckets": list(seq_buckets),
                "max_new": args.max_new, "workers": args.workers,
                "store_host": "127.0.0.1" if store_on else None,
                "store_port": store.port, "port": port,
                "heartbeat_s": float(_flag("router_heartbeat_s")),
                "cache_dir": cache_dir, "trace": "off",
                "flight_dir": args.flight_dir,
                "flight_interval_s": 0.5, "version": version,
                # per-tenant admission for the burst drill: the bursty
                # tenant gets a tight pending quota + bottom priority,
                # the steady tenant a high priority class
                "tenant_policies": {
                    "burst": {"max_pending": 2, "priority": 0},
                    "steady": {"priority": 5}}}

    def _spawn_child(cfg):
        path = os.path.join(cfg_dir, f"{cfg['id']}.json")
        with open(path, "w") as f:
            json.dump(cfg, f)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--replica-config", path],
            stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        children[cfg["id"]] = p
        return p

    def spawn(rid, version):
        # ElasticLaunch-style: the controller holds the Popen token and
        # the replica joins through the rendezvous store
        return _spawn_child(_cfg_for(rid, version=version))

    def spawn_heldout(rid, version):
        # canary: NO rendezvous record (held out of rotation — discovery
        # can't find it), but it DOES heartbeat, so once RollingUpdate
        # promotes it via add_replica the router's liveness verdict
        # holds; fixed RPC port, dialed directly once it answers ping
        port = _free_port()
        _spawn_child(_cfg_for(rid, version=version, port=port,
                              heldout=True))
        deadline = time.monotonic() + 600
        while True:
            try:
                c = RpcClient("127.0.0.1", port, timeout=5.0)
                c.request("ping", {})
                c.close()
                break
            except Exception:   # noqa: BLE001 — still booting
                if children[rid].poll() is not None:
                    raise RuntimeError(
                        f"held-out replica {rid} exited "
                        f"rc={children[rid].returncode}")
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.2)
        return RemoteReplica(rid, "127.0.0.1", port, role="both",
                             version=version)

    def _evictions():
        m = default_registry().get("router_evictions_total")
        return float(m.value) if m is not None else 0.0

    try:
        router = Router(store=store)
        obs = ClusterObserver(router, trace_dir=args.trace_dir)
        router.attach_observer(obs)
        ctrl = AutoscaleController(router, spawn, min_replicas=1,
                                   max_replicas=max(n_top, 4),
                                   version="v1")
        t0 = time.perf_counter()
        ctrl.spawn_replica("r0", version="v1")
        if not ctrl.wait_live(1, timeout_s=600):
            report["error"] = "seed replica never joined"
            return _router_report(report, args, 1)
        report["boot_s"] = round(time.perf_counter() - t0, 3)

        model_meta = {name: ZOO[name]() for name in names}
        dense = [(name, model_meta[name][1],
                  getattr(model_meta[name][0], "_serve_vocab", None))
                 for name in names]
        traffic = _BgTraffic(router, dense, args.decode, seq_buckets,
                             args.max_new, clients=args.clients,
                             seed=args.seed, tenant="steady").start()
        if args.sessions:
            # conversations run through EVERY leg — ramp, drain-down,
            # rollout, the mid-rollout SIGKILL — and the exit gate
            # demands none of them were lost or answered differently
            sess_traffic = _SessionTraffic(
                router, "gpt_decode", seq_buckets, args.max_new,
                clients=max(2, args.clients // 2),
                seed=args.seed + 31).start()

        # -- tenant admission: control window, then a burst window ------
        tc0 = time.time()
        time.sleep(args.duration)
        tc1 = time.time()
        burst_router = Router(store=store)   # the burst tenant's own
        burst = _BgTraffic(burst_router, dense, args.decode, seq_buckets,
                           args.max_new, clients=max(4, args.clients),
                           seed=args.seed + 1000, tenant="burst",
                           timeout=max(8.0, args.duration),
                           count_rejections=True).start()
        tb0 = time.time()
        time.sleep(args.duration)
        tb1 = time.time()
        burst.stop()
        burst_router.close()
        burst_router = None
        p99_ctrl = traffic.p99_ms(tc0, tc1)
        p99_burst = traffic.p99_ms(tb0, tb1)
        report["tenant"] = {
            "steady_p99_ms_control": p99_ctrl,
            "steady_p99_ms_under_burst": p99_burst,
            "burst_p99_ms": burst.p99_ms(tb0, tb1),
            "burst_rejections": burst.rejections,
            "burst_completed": len(burst.latencies),
            "burst_errors": burst.errors}
        if burst.errors:
            rc = 1
        if p99_ctrl is not None and p99_burst is not None \
                and p99_burst > max(10.0 * p99_ctrl, p99_ctrl + 2000.0):
            report["tenant"]["isolation_violated"] = True
            rc = 1

        # -- ramp 1 -> N -> 1 under traffic ------------------------------
        ev0 = _evictions()
        up0 = time.perf_counter()
        ctrl.scale_to(n_top, version="v1")
        if not ctrl.wait_live(n_top, timeout_s=600):
            report["error"] = f"never reached {n_top} live replicas"
            return _router_report(report, args, 1)
        report["ramp_up_s"] = round(time.perf_counter() - up0, 3)
        time.sleep(args.duration)        # sustain at N
        down0 = time.perf_counter()
        ctrl.scale_to(1)
        report["ramp_down_s"] = round(time.perf_counter() - down0, 3)
        retires = [d for d in ctrl.decisions
                   if d.get("action") == "retire"]
        report["scale_down"] = [
            {"replica": d.get("replica"),
             "drained": d.get("drained"),
             "duration_s": d.get("duration_s"),
             "escalated": d.get("escalated")} for d in retires]
        report["scale_down_evictions"] = _evictions() - ev0
        if len(retires) != n_top - 1 \
                or not all(d.get("drained") for d in retires) \
                or report["scale_down_evictions"]:
            rc = 1

        # -- rolling update legs -----------------------------------------
        if args.rollout:
            ctrl.scale_to(2, version="v1")
            ctrl.wait_live(2, timeout_s=600)
            rng = np.random.RandomState(12345)
            canary_reqs = []
            for name, specs, vocab in dense:
                canary_reqs.append(
                    {"op": "infer", "model": name,
                     "inputs": _random_inputs(rng, specs, 1, vocab)})
            if args.decode:
                canary_reqs.append(
                    {"op": "decode", "model": "gpt_decode",
                     "prompts": [rng.randint(1, 128, 6)],
                     "max_new": args.max_new})
            journal = os.path.join(cfg_dir, "rollout.json")
            ru = RollingUpdate(ctrl, spawn_heldout, canary_reqs,
                               journal_path=journal)
            out = ru.run("v2", wait_live_s=600)
            out["versions"] = sorted(h.version for h in router.handles()
                                     if h.alive)
            report["rollout"] = out
            if out.get("rolled_back") \
                    or out["versions"] != ["v2"] * len(out["versions"]):
                rc = 1

            if args.rollout_kill:
                # mid-rollout SIGKILL: once the v3 canary is promoted
                # (journal says so), the old replica that would be
                # replaced LAST dies hard; the rollout must finish, the
                # journal must stay consistent, traffic must not error
                victim = max(h.id for h in router.handles() if h.alive)
                def _killer():
                    deadline = time.monotonic() + 600
                    while time.monotonic() < deadline:
                        try:
                            with open(journal) as f:
                                if json.load(f).get("promoted"):
                                    break
                        except (OSError, ValueError):
                            pass
                        time.sleep(0.05)
                    p = children.get(victim)
                    if p is not None and p.poll() is None:
                        p.send_signal(signal.SIGKILL)
                kt = threading.Thread(target=_killer, daemon=True)
                kt.start()
                out = RollingUpdate(ctrl, spawn_heldout, canary_reqs,
                                    journal_path=journal).run(
                                        "v3", wait_live_s=600)
                kt.join(timeout=30)
                with open(journal) as f:
                    jstate = json.load(f)
                out["victim"] = victim
                out["journal"] = jstate
                out["versions"] = sorted(
                    h.version for h in router.handles() if h.alive)
                if args.flight_dir:
                    pm = os.path.join(args.flight_dir,
                                      f"postmortem_{victim}.json")
                    out["postmortem_exists"] = os.path.exists(pm)
                    if not out["postmortem_exists"]:
                        rc = 1
                report["rollout_kill"] = out
                if out.get("rolled_back") or not jstate.get("done") \
                        or victim not in jstate.get("replaced", ()) \
                        or set(out["versions"]) != {"v3"}:
                    rc = 1

            # forced rollback: the canary_mismatch fault clause fires in
            # THIS process (the comparison runs router-side), the canary
            # must die before rotation and the old version keep serving
            prev = sorted(h.version for h in router.handles() if h.alive)
            _faults.install_plan(_faults.FaultPlan.parse("canary_mismatch:"))
            try:
                out = RollingUpdate(ctrl, spawn_heldout, canary_reqs,
                                    journal_path=journal).run(
                                        "v9", wait_live_s=600)
            finally:
                _faults.clear_plan()
            out["versions"] = sorted(h.version for h in router.handles()
                                     if h.alive)
            report["rollback"] = out
            if not out.get("rolled_back") or out["versions"] != prev:
                rc = 1
            ctrl.scale_to(1)

        if sess_traffic is not None:
            sess_traffic.stop()
            report["sessions"] = sess_traffic.report()
            rc = _gate_sessions(report, args, rc)
            if args.rollout_kill and report["sessions"]["lost_sessions"]:
                report["sessions"]["kill_lost_sessions"] = True
        traffic.stop()
        report["traffic_errors"] = traffic.errors
        report["traffic_completed"] = len(traffic.latencies)
        if traffic.errors or not traffic.latencies:
            rc = 1

        steady_total = 0
        for h in router.handles():
            if not h.alive:
                continue
            try:
                hl = h.health()
                report["replica_stats"][h.id] = h.model_stats()
            except Exception as e:   # noqa: BLE001 — reported, gated
                report["replica_stats"][h.id] = \
                    {"error": f"{type(e).__name__}: {e}"}
                rc = 1
                continue
            steady_total += int(hl.get("steady_compiles", 0))
        report["steady_compiles"] = steady_total
        if steady_total:
            rc = 1
        report["decisions"] = ctrl.decisions
        report["router_stats"] = router.stats()
        sig = obs.poll()
        report["cluster_signals"] = sig.to_dict()
    finally:
        if sess_traffic is not None:
            sess_traffic.stop()
        if traffic is not None:
            traffic.stop()
        if burst_router is not None:
            burst_router.close()
        if obs is not None:
            obs.close()
        if router is not None:
            router.close()
        for p in children.values():
            if p.poll() is None:
                p.terminate()
        for p in children.values():
            try:
                p.wait(timeout=10)
            except Exception:   # noqa: BLE001 — last resort
                p.kill()
        store.close()
    return _router_report(report, args, rc)


def _router_report(report, args, rc):
    report["rc"] = rc
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for rid, st in report.get("replica_stats", {}).items():
            if "error" in st:
                print(f"{rid:>10}: ERROR {st['error']}")
                continue
            for name, m in st.items():
                print(f"{rid:>10} {name:>12}: {m['qps']:>8.1f} qps  "
                      f"p50 {m['p50_ms']:>8.2f} ms  "
                      f"p99 {m['p99_ms']:>8.2f} ms  "
                      f"completed {m['completed']}")
        if "sessions" in report:
            s = report["sessions"]
            print(f"sessions: {s['turns']} turns "
                  f"({s['follow_ups']} follow-ups / "
                  f"{s['conversations']} conversations), "
                  f"lost {s['lost_sessions']}, verified "
                  f"{s['verified_turns']} (mismatches "
                  f"{s['bit_mismatches']}), p99 {s['p99_ms']} ms")
        print(f"router: {report.get('router_stats', {}).get('replicas_live')}"
              f" live, steady compiles {report.get('steady_compiles')} "
              f"(must be 0), rc={rc}")
    return rc


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="serve",
        description="export zoo models, warm the serving engine, drive "
                    "sustained traffic, report QPS/p50/p99 + the "
                    "zero-steady-state-recompile check")
    ap.add_argument("--model", action="append", choices=sorted(ZOO),
                    help="serve one zoo model (repeatable; default: all "
                         "dense models, or none under --decode)")
    ap.add_argument("--decode", action="store_true",
                    help="additionally serve a GPT autoregressive-decode "
                         "model (KV-cache generate through the bucketed "
                         "prefill/decode executables) and drive mixed "
                         "prompt-length decode traffic at it")
    ap.add_argument("--max-new", type=int, default=4,
                    help="decode model: max generated tokens per request")
    ap.add_argument("--seq-buckets", default="8,16",
                    help="decode model: prompt-length bucket ladder")
    ap.add_argument("--int8", action="store_true",
                    help="serve frozen int8 exports (PTQ + freeze)")
    ap.add_argument("--duration", type=float, default=2.0,
                    help="seconds of sustained traffic per run")
    ap.add_argument("--clients", type=int, default=4,
                    help="concurrent client threads")
    ap.add_argument("--workers", type=int, default=None,
                    help="serving worker threads (default: flag)")
    ap.add_argument("--buckets", default="1,2,4",
                    help="batch bucket ladder, e.g. '1,2,4,8'")
    ap.add_argument("--max-request-rows", type=int, default=2,
                    help="clients submit 1..N rows per request")
    ap.add_argument("--p99-slo-ms", type=float, default=None,
                    help="fail (rc!=0) when any model's p99 exceeds this")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="serve GET /metrics (Prometheus text) from a "
                         "stdlib http endpoint on this port while "
                         "traffic runs (0 = ephemeral; the bound port "
                         "lands in the report).  The report records a "
                         "self-scrape so CI can gate on exposition "
                         "health without its own scraper.  Under "
                         "--router this is the FEDERATED cluster "
                         "endpoint: replica-labeled families + "
                         "cluster_* rollups")
    ap.add_argument("--metrics-textfile", default=None, metavar="PATH",
                    help="atomically write the final Prometheus "
                         "exposition to PATH (textfile-collector "
                         "convention — scrape-less CI; the federated "
                         "cluster exposition under --router)")
    ap.add_argument("--trace-dir", default=None, metavar="DIR",
                    help="stream request spans as LogWriter JSONL into "
                         "DIR (sets FLAGS_trace=full unless FLAGS_trace "
                         "/ PADDLE_TPU_TRACE already enabled a mode); "
                         "join with tools/obs_report.py.  Under "
                         "--router the replicas ship their spans to the "
                         "router over the scrape RPC and DIR holds ONE "
                         "merged skew-corrected cluster trace "
                         "(obs_report.py --cluster)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="under --router: arm every replica's flight "
                         "recorder (FLAGS_flight_dir) so each process "
                         "keeps an atomically-rewritten "
                         "postmortem_<id>.json of its recent spans / "
                         "compile ledger / metrics; with --kill-one the "
                         "report records the SIGKILL victim's artifact "
                         "(read it with obs_report.py --postmortem)")
    ap.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="persistent executable cache: warm-up loads "
                         "serialized executables from DIR instead of "
                         "compiling, and stores what it compiles "
                         "(FLAGS_executable_cache=readwrite + "
                         "FLAGS_executable_cache_dir).  The report "
                         "gains exec_cache hit/miss tallies and a "
                         "warm-up compile-kind census — a warm boot "
                         "shows warmup_fresh_compiles == 0")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON report instead of text")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--router", action="store_true",
                    help="cluster mode: spawn --replicas serving "
                         "subprocesses behind the front-end Router "
                         "(TCPStore rendezvous + heartbeat eviction) "
                         "and drive the traffic through it; rc gates "
                         "additionally on per-replica steady compiles "
                         "and (with --kill-one) the eviction drill")
    ap.add_argument("--replicas", type=int, default=None,
                    help="replica subprocess count under --router "
                         "(default: FLAGS_serving_replicas)")
    ap.add_argument("--disaggregate", action="store_true",
                    help="under --router --decode: split replicas into "
                         "prefill/decode worker pools; decode requests "
                         "route prefill-pool → KV handoff → decode-pool")
    ap.add_argument("--kill-one", action="store_true", dest="kill_one",
                    help="under --router: SIGKILL one replica "
                         "mid-traffic and require heartbeat eviction + "
                         "traffic redistribution (rc!=0 otherwise)")
    ap.add_argument("--ramp", type=int, default=None, metavar="N",
                    help="elastic-lifecycle drill: boot ONE replica, "
                         "start sustained traffic that never stops, "
                         "scale 1 -> N -> 1 through the autoscaling "
                         "controller (scale-down is graceful drain), "
                         "and run a tenant-burst admission window; rc "
                         "gates on zero client errors, zero steady "
                         "compiles, every retirement drained (no "
                         "eviction), and tenant isolation")
    ap.add_argument("--rollout", action="store_true",
                    help="under --ramp: add zero-downtime rolling-"
                         "update legs at scale 2 — canary bit-match "
                         "gate then replica-by-replica replacement, "
                         "plus a fault-forced canary rollback that "
                         "must leave the old version serving")
    ap.add_argument("--rollout-kill", action="store_true",
                    dest="rollout_kill",
                    help="under --ramp --rollout: SIGKILL one old "
                         "replica mid-rollout (after canary "
                         "promotion); the rollout must still converge, "
                         "the journal stay consistent, and the victim "
                         "leave a flight-recorder postmortem")
    ap.add_argument("--sessions", action="store_true",
                    help="stateful multi-turn traffic (needs --decode): "
                         "clients grow conversations under stable "
                         "session_ids through the prefix/session KV "
                         "cache (FLAGS_session_store + "
                         "FLAGS_prefix_cache + the slot decode loop), "
                         "and a sampled oracle re-submits each "
                         "transcript statelessly, demanding "
                         "bit-identical output.  rc additionally "
                         "gates on zero lost sessions / mismatches; "
                         "under --ramp --rollout-kill this is the "
                         "stateful SIGKILL drill — parked sessions "
                         "spill to a fleet-shared dir and must "
                         "survive the victim")
    ap.add_argument("--replica-config", default=None,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.replica_config:
        return _replica_child(args.replica_config)
    if args.sessions and not args.decode:
        print("--sessions needs --decode", file=sys.stderr)
        return 2
    if args.sessions and args.disaggregate:
        print("--sessions needs unified replicas (the slot decode loop "
              "is per-replica); drop --disaggregate", file=sys.stderr)
        return 2
    if args.ramp is not None:
        return _ramp_main(args)
    if args.router:
        return _router_main(args)

    from paddle_tpu import serving
    from paddle_tpu.framework.flags import flags_restore, flags_snapshot, \
        set_flags

    names = list(dict.fromkeys(
        args.model or ([] if args.decode else sorted(ZOO))))
    buckets = tuple(int(b) for b in args.buckets.split(",") if b.strip())
    seq_buckets = tuple(int(b) for b in args.seq_buckets.split(",")
                        if b.strip())
    snap = flags_snapshot()
    report = {"int8": args.int8, "buckets": list(buckets),
              "duration_s": args.duration, "clients": args.clients,
              "models": {}}
    rc = 0
    metrics_srv = None
    try:
        if args.int8:
            set_flags({"FLAGS_use_int8_inference": True})
        if args.sessions:
            from paddle_tpu.framework.flags import flag as _flag
            sess_dir = tempfile.mkdtemp(prefix="serve_sessions_")
            report["sessions_dir"] = sess_dir
            sess_flags = {"FLAGS_session_store": True,
                          "FLAGS_session_store_dir": sess_dir,
                          "FLAGS_prefix_cache": True}
            if not int(_flag("decode_slots")):
                sess_flags["FLAGS_decode_slots"] = 4
            set_flags(sess_flags)
        if args.trace_dir:
            from paddle_tpu.framework.flags import flag as _flag
            from paddle_tpu.profiler import tracing as _tracing
            if str(_flag("trace")).lower() == "off":
                set_flags({"FLAGS_trace": "full"})
            _tracing.set_trace_dir(args.trace_dir)
            report["trace_dir"] = args.trace_dir
            report["trace_mode"] = str(_flag("trace")).lower()
        if args.metrics_port is not None:
            from paddle_tpu.profiler.metrics import serve_metrics
            metrics_srv = serve_metrics(port=args.metrics_port)
            report["metrics_port"] = metrics_srv.port
        if args.cache_dir:
            os.makedirs(args.cache_dir, exist_ok=True)
            set_flags({"FLAGS_executable_cache": "readwrite",
                       "FLAGS_executable_cache_dir": args.cache_dir})
            report["cache_dir"] = args.cache_dir
        with tempfile.TemporaryDirectory() as d:
            # deterministic builds: the exported program (and so the
            # cache identity and the served outputs) must match across
            # cold/warm runs of this CLI
            import paddle_tpu as _paddle
            _paddle.seed(args.seed)
            server = serving.Server(serving.ServingConfig(
                workers=args.workers, buckets=buckets))
            model_meta = {}
            for name in names:
                layer, specs = ZOO[name]()
                layer.eval()
                if args.int8:
                    import paddle_tpu as paddle
                    from paddle_tpu.quantization import \
                        PostTrainingQuantization
                    rng = np.random.RandomState(args.seed)
                    cal = _random_inputs(rng, specs, buckets[0],
                                         getattr(layer, "_serve_vocab",
                                                 None))

                    def loader():
                        for _ in range(4):
                            yield tuple(paddle.to_tensor(a) for a in cal)

                    PostTrainingQuantization(model=layer,
                                             data_loader=loader(),
                                             batch_nums=4).quantize()
                prefix = os.path.join(d, name)
                manifest = serving.export_for_serving(
                    layer, prefix, specs, buckets=buckets, int8=args.int8)
                server.register(name, prefix, buckets=buckets)
                model_meta[name] = (specs,
                                    getattr(layer, "_serve_vocab", None),
                                    manifest["mode"])
            if args.decode:
                gpt = build_gpt_decode()
                server.register_decode(
                    "gpt_decode", gpt, batch_buckets=buckets,
                    seq_buckets=seq_buckets, max_new_tokens=args.max_new,
                    max_len=max(seq_buckets) + args.max_new)
            t0 = time.perf_counter()
            server.start()
            warmup_s = round(time.perf_counter() - t0, 3)
            if args.cache_dir:
                # warm-up compile census: a warm boot over a filled
                # cache dir must show ONLY cache_load events (zero
                # fresh XLA compiles) at the server-owned sites
                from collections import Counter
                from paddle_tpu.jit import persistent_cache as _pcache
                from paddle_tpu.profiler import ledger as _pledger
                kinds = Counter()
                for site, mark in server._warmup_marks.items():
                    for e in _pledger.compile_events(site)[:mark]:
                        kinds[e.get("kind", "?")] += 1
                report["exec_cache"] = _pcache.stats()
                report["warmup_compile_kinds"] = dict(kinds)
                report["warmup_fresh_compiles"] = sum(
                    n for k, n in kinds.items() if k != "cache_load")
            if args.decode:
                strf = None
                if args.sessions:
                    # the stateful clients run ALONGSIDE the one-shot
                    # traffic: restores and plain prefills share slots
                    strf = _SessionTraffic(
                        server, "gpt_decode", seq_buckets, args.max_new,
                        clients=args.clients, seed=args.seed + 31,
                        vocab=gpt._serve_vocab).start()
                errors = _decode_traffic(
                    server, "gpt_decode", args.duration, args.clients,
                    args.max_request_rows, max(seq_buckets),
                    args.max_new, gpt._serve_vocab, args.seed)
                st = server.stats("gpt_decode")
                st["export_mode"] = "live_layer"
                st["traffic_errors"] = errors
                if errors or st["errors"]:
                    rc = 1
                if strf is not None:
                    strf.stop()
                    sess = strf.report()
                    sl = server.stats("gpt_decode").get("slot_loop") or {}
                    for k in ("restored", "parked", "prefix_hit_tokens"):
                        sess[k] = sl.get(k)
                    report["sessions"] = sess
                    rc = _gate_sessions(report, args, rc)
                    if not sess.get("restored"):
                        # mixed-mode without a single KV restore means
                        # the session plane silently never engaged
                        sess["restore_never_engaged"] = True
                        rc = 1
                if args.p99_slo_ms is not None:
                    st["p99_slo_ms"] = args.p99_slo_ms
                    st["slo_met"] = st["p99_ms"] <= args.p99_slo_ms
                    if not st["slo_met"]:
                        rc = 1
                report["models"]["gpt_decode"] = st
            for name in names:
                specs, vocab, mode = model_meta[name]
                errors = _traffic(server, name, specs, args.duration,
                                  args.clients, args.max_request_rows,
                                  vocab, args.seed)
                st = server.stats(name)
                st["export_mode"] = mode
                st["traffic_errors"] = errors
                if errors or st["errors"]:
                    rc = 1
                if args.p99_slo_ms is not None:
                    st["p99_slo_ms"] = args.p99_slo_ms
                    st["slo_met"] = st["p99_ms"] <= args.p99_slo_ms
                    if not st["slo_met"]:
                        rc = 1
                report["models"][name] = st
            server.stop()
            steady = server.compile_events_since_warmup()
            report["warmup_s"] = warmup_s
            report["steady_compiles"] = len(steady)
            if steady:
                rc = 1
                report["steady_compile_events"] = [
                    {"site": e["site"], "kind": e.get("kind"),
                     "diff": e["diff"]} for e in steady[:8]]
            if metrics_srv is not None:
                # self-scrape: the endpoint must serve parseable
                # Prometheus text while the process is still up
                import urllib.request
                try:
                    with urllib.request.urlopen(
                            f"http://127.0.0.1:{metrics_srv.port}/metrics",
                            timeout=10) as resp:
                        body = resp.read().decode()
                    report["metrics_scrape_ok"] = (
                        resp.status == 200
                        and "serving_queue_wait_seconds_bucket" in body)
                except Exception as e:   # noqa: BLE001 — reported, gated
                    report["metrics_scrape_ok"] = False
                    report["metrics_scrape_error"] = \
                        f"{type(e).__name__}: {e}"
                if not report["metrics_scrape_ok"]:
                    rc = 1
            if args.metrics_textfile:
                from paddle_tpu.profiler.metrics import write_textfile
                report["metrics_textfile"] = \
                    write_textfile(args.metrics_textfile)
    finally:
        if metrics_srv is not None:
            metrics_srv.close()
        if args.trace_dir:
            from paddle_tpu.profiler import tracing as _tracing
            _tracing.set_trace_dir(None)
        flags_restore(snap)

    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        for name, st in report["models"].items():
            print(f"{name:>14}: {st['qps']:>8.1f} qps  "
                  f"p50 {st['p50_ms']:>8.2f} ms  "
                  f"p99 {st['p99_ms']:>8.2f} ms  "
                  f"batches {st['batches']}  "
                  f"avg rows {st['avg_batch_rows']}  "
                  f"[{st['backend']}/{st['export_mode']}]")
        if "sessions" in report:
            s = report["sessions"]
            print(f"      sessions: {s['turns']} turns "
                  f"({s['follow_ups']} follow-ups), restored "
                  f"{s.get('restored')}, parked {s.get('parked')}, "
                  f"prefix-hit tokens {s.get('prefix_hit_tokens')}, "
                  f"lost {s['lost_sessions']}, mismatches "
                  f"{s['bit_mismatches']}")
        print(f"serve: warm-up {report['warmup_s']}s, steady-state "
              f"compiles {report['steady_compiles']} (must be 0), rc={rc}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
