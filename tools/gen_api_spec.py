"""Generate API.spec: a frozen signature inventory of the public surface.

Reference parity: paddle/fluid/API.spec + tools/check_api_compatible.py —
the reference pins every public API's signature so accidental breaks fail CI.
Run ``python tools/gen_api_spec.py > API.spec`` to (re)freeze deliberately;
tests/test_api_spec.py diffs the live surface against the committed file.
"""
from __future__ import annotations

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


NAMESPACES = [
    ("paddle_tpu", None),
    ("paddle_tpu.nn", None),
    ("paddle_tpu.nn.functional", None),
    ("paddle_tpu.nn.initializer", None),
    ("paddle_tpu.nn.layer.moe", None),
    ("paddle_tpu.tensor", None),
    ("paddle_tpu.optimizer", None),
    ("paddle_tpu.optimizer.lr", None),
    ("paddle_tpu.static", None),
    ("paddle_tpu.static.nn", None),
    ("paddle_tpu.io", None),
    ("paddle_tpu.metric", None),
    ("paddle_tpu.amp", None),
    ("paddle_tpu.jit", None),
    ("paddle_tpu.jit.persistent_cache", None),
    ("paddle_tpu.distributed", None),
    ("paddle_tpu.distributed.fleet", None),
    ("paddle_tpu.vision.models", None),
    ("paddle_tpu.text", None),
    ("paddle_tpu.text.models", None),
    ("paddle_tpu.text.speculative", None),
    ("paddle_tpu.inference", None),
    ("paddle_tpu.serving", None),
    ("paddle_tpu.serving.cluster", None),
    ("paddle_tpu.quantization", None),
    ("paddle_tpu.regularizer", None),
    ("paddle_tpu.incubate", None),
    ("paddle_tpu.profiler", None),
    ("paddle_tpu.profiler.metrics", None),
    ("paddle_tpu.profiler.tracing", None),
    ("paddle_tpu.rec", None),
    ("paddle_tpu.checkpoint", None),
    ("paddle_tpu.testing", None),
    ("paddle_tpu.analysis", None),
    ("paddle_tpu.analysis.hlo", None),
    ("paddle_tpu.analysis.autoshard", None),
]


def _sig(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(*)"


def iter_spec():
    import importlib
    for modname, _ in NAMESPACES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        for name in sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            if inspect.ismodule(obj):
                continue
            if inspect.isclass(obj):
                if getattr(obj, "__module__", "").startswith(
                        ("paddle_tpu",)):
                    yield f"{modname}.{name} class{_sig(obj)}"
            elif callable(obj):
                mod_of = getattr(obj, "__module__", "") or ""
                if mod_of.startswith("paddle_tpu") or mod_of == modname:
                    yield f"{modname}.{name} {_sig(obj)}"


def main():
    for line in iter_spec():
        sys.stdout.write(line + "\n")


if __name__ == "__main__":
    main()
