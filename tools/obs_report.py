#!/usr/bin/env python
"""obs_report — join trace JSONL + metrics snapshots into a per-request
waterfall and SLO report.

The read side of the observability plane: ``FLAGS_trace_dir`` (or
``tools/serve.py --trace-dir``) streams finished spans as LogWriter
JSONL; ``--metrics`` points at a Prometheus textfile written by
``profiler.metrics.write_textfile`` (or scraped from ``--metrics-port``).
This tool joins them:

    python tools/obs_report.py --trace-dir /tmp/traces
    python tools/obs_report.py --trace-dir /tmp/traces --waterfall 3
    python tools/obs_report.py --trace-dir /tmp/traces \
        --metrics /tmp/metrics.prom --slo-p99-ms 250 --json

Cluster mode (``--cluster``) reads the Router's MERGED trace JSONL
(serving.cluster.obs.ClusterObserver's sink): spans from N processes,
already re-stamped onto the router wall timeline with their origin
under ``process``.  A trace is judged as a CROSS-PROCESS chain — one
``route`` root, per-process subroots joined by trace_id, and for
disaggregated decode the full route→prefill→handoff→decode shape.

Postmortem mode (``--postmortem postmortem_<id>.json``) reads a flight-
recorder artifact (profiler.flight) and reports what the dead process
knew: recent spans, recompile-ledger tail, metric families, dump
reason.

Per trace it checks the span chain is COMPLETE (every phase its request
kind requires) and WELL-NESTED (children inside the root window, in
order); across traces it aggregates per-phase p50/p99 and total-latency
percentiles.  Exit code is non-zero when any chain is incomplete or
mis-nested, or a ``--slo-p99-ms`` bound is violated — the smoke test's
assertion surface.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# phases a complete request chain must carry, by root-span kind.  h2d /
# d2h are pipeline-path extras (the synchronous executor backend fences
# internally and legitimately lacks them).
REQUIRED_PHASES = {
    "dense": {"queue_wait", "pack", "execute", "reply"},
    "decode": {"queue_wait", "pack", "prefill", "decode", "reply"},
}
# cross-process chains: what a cluster trace must carry beyond the
# route root.  Unified routing proxies the whole request to one replica
# (request subroot + its in-process phases); disaggregated decode
# splits prefill and decode across pools with an explicit handoff.
REQUIRED_CLUSTER_PHASES = {
    "unified": {"dispatch", "request"},
    "disaggregated": {"dispatch", "prefill", "handoff", "decode"},
}
# tolerance for cross-thread monotonic stamping at span edges
_EDGE_EPS_S = 0.005
# cross-process nesting tolerance: clock-skew correction is RTT-midpoint
# accurate, so allow a wider (but still tight) edge epsilon
_CLUSTER_EPS_S = 0.05


def load_traces(trace_dir):
    """Read every trace/span JSONL record under ``trace_dir`` (rotated
    generations included) -> {trace_id: [span dicts, oldest first]}."""
    from paddle_tpu.utils.monitor import LogWriter
    spans = LogWriter.read_events(trace_dir).get("trace/span", [])
    out = {}
    for s in spans:
        out.setdefault(s["trace_id"], []).append(s)
    return out


def check_chain(spans):
    """Validate one trace: returns (ok, problems list).  Complete =
    every phase the root's kind requires is present; well-nested = every
    child span lies inside the root window (±edge epsilon) and the root
    was finished."""
    problems = []
    roots = [s for s in spans if s.get("parent_id") is None]
    if len(roots) != 1:
        return False, [f"expected exactly one root span, got {len(roots)}"]
    root = roots[0]
    kind = root.get("attrs", {}).get("kind", "dense")
    names = {s["name"] for s in spans if s is not root}
    missing = REQUIRED_PHASES.get(kind, set()) - names
    if missing:
        problems.append(f"incomplete chain (kind={kind}): missing "
                        f"{sorted(missing)}")
    r0 = root["t0"]
    r1 = root["t0"] + root["dur_ms"] / 1e3
    for s in spans:
        if s is root:
            continue
        s0, s1 = s["t0"], s["t0"] + s["dur_ms"] / 1e3
        if s0 < r0 - _EDGE_EPS_S or s1 > r1 + _EDGE_EPS_S:
            problems.append(
                f"span {s['name']!r} [{s0:.6f}, {s1:.6f}] escapes the "
                f"root window [{r0:.6f}, {r1:.6f}]")
    return not problems, problems


def _root_span(spans):
    """The trace's display root: the ``route`` span when present (a
    cluster trace has per-process subroots too), else the first
    parentless span."""
    roots = [s for s in spans if s.get("parent_id") is None]
    for s in roots:
        if s["name"] == "route":
            return s
    return roots[0]


def check_cluster_chain(spans, eps=_CLUSTER_EPS_S):
    """Validate one CROSS-PROCESS trace assembled by the Router:

    * exactly one ``route`` root (the router's);
    * other parentless spans are per-process subroots joined by
      trace_id — legal, but every span must still lie inside the route
      window after clock-skew correction (±``eps``);
    * complete = the unified shape (dispatch + the replica's request
      chain) or, when a ``handoff`` span is present, the disaggregated
      route→prefill→handoff→decode shape."""
    problems = []
    routes = [s for s in spans
              if s.get("parent_id") is None and s["name"] == "route"]
    if len(routes) != 1:
        return False, [f"expected exactly one route root, "
                       f"got {len(routes)}"]
    root = routes[0]
    kind = root.get("attrs", {}).get("kind", "dense")
    names = {s["name"] for s in spans if s is not root}
    shape = "disaggregated" if "handoff" in names else "unified"
    required = set(REQUIRED_CLUSTER_PHASES[shape])
    if shape == "unified":
        required |= REQUIRED_PHASES.get(kind, set())
    missing = required - names
    if missing:
        problems.append(f"incomplete cluster chain (kind={kind}, "
                        f"{shape}): missing {sorted(missing)}")
    r0 = root["t0"]
    r1 = root["t0"] + root["dur_ms"] / 1e3
    for s in spans:
        if s is root:
            continue
        s0, s1 = s["t0"], s["t0"] + s["dur_ms"] / 1e3
        if s0 < r0 - eps or s1 > r1 + eps:
            problems.append(
                f"span {s['name']!r} "
                f"(process {s.get('process', '?')}) "
                f"[{s0:.6f}, {s1:.6f}] escapes the route window "
                f"[{r0:.6f}, {r1:.6f}] after skew correction")
    return not problems, problems


def waterfall(spans, width=48):
    """Text waterfall for one trace: spans as offset bars under the
    root, phase order preserved."""
    root = _root_span(spans)
    total = max(root["dur_ms"], 1e-6)
    lines = [f"trace {root['trace_id']}  {root['name']} "
             f"{root['dur_ms']:.2f} ms  {root.get('attrs', {})}"]
    for s in sorted((s for s in spans if s is not root),
                    key=lambda s: s["t0"]):
        off_ms = (s["t0"] - root["t0"]) * 1e3
        a = int(max(0.0, off_ms) / total * width)
        b = max(a + 1, int((max(0.0, off_ms) + s["dur_ms"]) / total
                           * width))
        bar = " " * a + "#" * min(b - a, width - a)
        extra = ""
        n_tok = sum(1 for e in s.get("events", [])
                    if e.get("name") == "token")
        if n_tok:
            extra = f"  [{n_tok} tokens]"
        n_compiles = sum(1 for e in s.get("events", [])
                         if e.get("name") == "compile")
        if n_compiles:
            extra += f"  [{n_compiles} COMPILE]"
        nm = s["name"]
        if s.get("process"):
            nm = f"{nm}@{s['process']}"
        lines.append(f"  {nm:<12} {off_ms:>9.2f} ms "
                     f"+{s['dur_ms']:>9.2f} ms |{bar:<{width}}|{extra}")
    return "\n".join(lines)


def _pctl(sorted_vals, p):
    if not sorted_vals:
        return None
    rank = max(0, min(len(sorted_vals) - 1,
                      int(round(p / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[rank]


def parse_prometheus_text(text):
    """Minimal (and strict) Prometheus 0.0.4 text parser -> {metric:
    {labels-string: float}}.  Raises ValueError on a malformed line —
    the smoke test runs it over a live scrape as the format gate."""
    import re
    sample = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
        r" ([0-9.eE+-]+|NaN|[+-]Inf)$")
    out = {}
    for i, line in enumerate(text.splitlines()):
        if not line.strip():
            continue
        if line.startswith("#"):
            if not (line.startswith("# HELP ")
                    or line.startswith("# TYPE ")):
                raise ValueError(f"line {i + 1}: bad comment {line!r}")
            continue
        m = sample.match(line)
        if m is None:
            raise ValueError(f"line {i + 1}: bad sample {line!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        out.setdefault(name, {})[labels] = float(value)
    return out


def build_report(traces, slo_p99_ms=None, metrics_path=None,
                 cluster=False):
    """Aggregate check + percentile report over every trace.  With
    ``cluster`` the judge is :func:`check_cluster_chain` and the report
    additionally counts distinct processes and chain shapes."""
    per_phase = {}
    totals = []
    bad = {}
    kinds = {}
    shapes = {}
    max_procs = 0
    checker = check_cluster_chain if cluster else check_chain
    for tid, spans in sorted(traces.items()):
        ok, problems = checker(spans)
        if not ok:
            bad[tid] = problems
            continue
        root = _root_span(spans)
        totals.append(root["dur_ms"])
        kinds[root.get("attrs", {}).get("kind", "dense")] = \
            kinds.get(root.get("attrs", {}).get("kind", "dense"), 0) + 1
        if cluster:
            names = {s["name"] for s in spans}
            shape = "disaggregated" if "handoff" in names else "unified"
            shapes[shape] = shapes.get(shape, 0) + 1
            max_procs = max(max_procs, len(
                {s.get("process") for s in spans if s.get("process")}))
        for s in spans:
            if s is not root:
                per_phase.setdefault(s["name"], []).append(s["dur_ms"])
    totals.sort()
    report = {
        "traces": len(traces),
        "complete": len(totals),
        "incomplete": {k: v for k, v in sorted(bad.items())[:8]},
        "kinds": kinds,
        "total_ms": {"p50": _pctl(totals, 50), "p99": _pctl(totals, 99),
                     "max": totals[-1] if totals else None},
        "phases_ms": {
            name: {"count": len(vs), "p50": _pctl(sorted(vs), 50),
                   "p99": _pctl(sorted(vs), 99)}
            for name, vs in sorted(per_phase.items())},
    }
    if cluster:
        report["shapes"] = shapes
        report["max_processes"] = max_procs
    if slo_p99_ms is not None and totals:
        report["slo_p99_ms"] = slo_p99_ms
        report["slo_met"] = report["total_ms"]["p99"] <= slo_p99_ms
    if metrics_path:
        with open(metrics_path) as f:
            fams = parse_prometheus_text(f.read())
        report["metrics"] = {
            name: fams[name] for name in sorted(fams)
            if name.split("_bucket")[0].startswith(
                ("serving_", "train_step_", "wide_deep_", "cluster_",
                 "router_"))}
    rc = 1 if bad else 0
    if report.get("slo_met") is False:
        rc = 1
    return report, rc


def postmortem_report(path):
    """Read + validate a flight-recorder artifact -> (report, rc)."""
    with open(path) as f:
        rec = json.load(f)
    problems = []
    if not str(rec.get("schema", "")).startswith(
            "paddle_tpu/flight-recorder/"):
        problems.append(f"unrecognized schema {rec.get('schema')!r}")
    for key in ("reason", "wall", "spans", "ledger", "metrics"):
        if key not in rec:
            problems.append(f"missing key {key!r}")
    spans = rec.get("spans") or []
    report = {
        "path": path,
        "schema": rec.get("schema"),
        "id": rec.get("id"),
        "pid": rec.get("pid"),
        "reason": rec.get("reason"),
        "age_s": round(time.time() - float(rec["wall"]), 3)
        if "wall" in rec else None,
        "dumps": rec.get("dumps"),
        "trace_mode": rec.get("trace_mode"),
        "spans": len(spans),
        "ledger_events": len(rec.get("ledger") or []),
        "metric_families": len((rec.get("metrics") or {})
                               .get("families") or []),
        "last_spans": [{"name": s.get("name"),
                        "trace_id": s.get("trace_id"),
                        "dur_ms": s.get("dur_ms")}
                       for s in spans[-5:]],
        "problems": problems,
    }
    return report, 1 if problems else 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="obs_report",
        description="join trace JSONL + metrics snapshots into "
                    "per-request waterfalls and an SLO report")
    ap.add_argument("--trace-dir", default=None,
                    help="directory of LogWriter trace JSONL "
                         "(FLAGS_trace_dir / serve.py --trace-dir)")
    ap.add_argument("--cluster", action="store_true",
                    help="judge traces as cross-process chains (the "
                         "Router's merged trace sink: route root + "
                         "per-process subroots, disaggregated "
                         "prefill/handoff/decode shapes)")
    ap.add_argument("--postmortem", default=None, metavar="PATH",
                    help="read one flight-recorder artifact "
                         "(postmortem_<id>.json) instead of a trace "
                         "dir; rc!=0 when unreadable/malformed")
    ap.add_argument("--metrics", default=None,
                    help="Prometheus textfile to validate + embed "
                         "(profiler.metrics.write_textfile output)")
    ap.add_argument("--slo-p99-ms", type=float, default=None,
                    help="fail (rc!=0) when total p99 exceeds this")
    ap.add_argument("--waterfall", type=int, default=0, metavar="N",
                    help="print text waterfalls of the N slowest "
                         "complete requests")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.postmortem:
        report, rc = postmortem_report(args.postmortem)
        if args.as_json:
            print(json.dumps(report, indent=1))
        else:
            print(f"postmortem {report['path']}: id={report['id']} "
                  f"pid={report['pid']} reason={report['reason']!r} "
                  f"age={report['age_s']}s")
            print(f"  {report['spans']} spans, "
                  f"{report['ledger_events']} ledger events, "
                  f"{report['metric_families']} metric families, "
                  f"{report['dumps']} prior dumps, "
                  f"trace={report['trace_mode']}")
            for s in report["last_spans"]:
                print(f"  span {s['name']:<12} {s['dur_ms']:>9.3f} ms "
                      f"trace {s['trace_id']}")
            for p in report["problems"]:
                print(f"  PROBLEM: {p}")
        return rc

    if not args.trace_dir:
        ap.error("--trace-dir is required (or use --postmortem)")

    traces = load_traces(args.trace_dir)
    report, rc = build_report(traces, slo_p99_ms=args.slo_p99_ms,
                              metrics_path=args.metrics,
                              cluster=args.cluster)
    if args.as_json:
        print(json.dumps(report, indent=1))
    else:
        print(f"obs_report: {report['complete']}/{report['traces']} "
              f"complete span chains  kinds={report['kinds']}"
              + (f"  shapes={report['shapes']}  "
                 f"max_processes={report['max_processes']}"
                 if args.cluster else ""))
        t = report["total_ms"]
        if t["p50"] is not None:
            print(f"  total: p50 {t['p50']:.2f} ms  p99 {t['p99']:.2f} ms"
                  f"  max {t['max']:.2f} ms")
        for name, st in report["phases_ms"].items():
            print(f"  {name:<12} n={st['count']:<6} p50 "
                  f"{st['p50']:>9.3f} ms  p99 {st['p99']:>9.3f} ms")
        for tid, problems in report["incomplete"].items():
            print(f"  BAD {tid}: {'; '.join(problems)}")
        if "slo_met" in report:
            print(f"  SLO p99<={report['slo_p99_ms']} ms: "
                  f"{'met' if report['slo_met'] else 'VIOLATED'}")
    if args.waterfall:
        checker = check_cluster_chain if args.cluster else check_chain
        complete = []
        for tid, spans in traces.items():
            ok, _ = checker(spans)
            if ok:
                complete.append((_root_span(spans)["dur_ms"], tid))
        for _, tid in sorted(complete, reverse=True)[:args.waterfall]:
            print()
            print(waterfall(traces[tid]))
    return rc


if __name__ == "__main__":
    sys.exit(main())
