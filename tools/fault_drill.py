#!/usr/bin/env python
"""Fault drill: exercise the fault-tolerant runtime end to end on a CPU mesh.

Runs the four fault kinds the deterministic harness
(``paddle_tpu.testing.faults``) can inject — rank kill, NaN gradients,
store connection drops, slow ranks — against the subsystems built to
survive them, and emits one JSON line per scenario::

    python tools/fault_drill.py --dry                # all scenarios
    python tools/fault_drill.py --dry nan_sentinel   # one scenario

Scenarios:

``torn_checkpoint``  interrupt/corrupt saves; the loader must fall back
                     to the previous complete step and an uncommitted
                     save must stay invisible (manifest = atomicity).
``nan_sentinel``     inject a NaN gradient in-graph; the numerics
                     sentinel must skip the step (params untouched),
                     back off the GradScaler, and keep training.
``store_drop``       sever the TCPStore connection mid-traffic; client
                     ops must retry/reconnect and ``add`` must not
                     double-count.
``slow_step``        a ``slow`` clause must stall the step hook
                     deterministically (the straggler the heartbeat
                     watchdog exists for).
``kill_resume``      SIGKILL a worker mid-run under ElasticLaunch; the
                     restarted gang must resume from the newest complete
                     checkpoint and finish with params identical to an
                     uninterrupted run.

``--dry`` keeps every scenario at toy scale (tier-1 CPU semantics, the
shape ``tools/mfu_audit.py --dry`` set); there is currently no chip-scale
wet mode, the flag exists for CLI symmetry and future growth.
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _emit(record):
    sys.stdout.write(json.dumps(record) + "\n")
    sys.stdout.flush()


# ---------------------------------------------------------------------------
def drill_torn_checkpoint(work):
    from paddle_tpu.checkpoint import CheckpointManager, complete_steps
    import numpy as np
    root = os.path.join(work, "ckpt_torn")
    m = CheckpointManager(root, keep=0)
    for s in (1, 2, 3):
        m.save(s, {"params": {"w": np.full((4,), float(s), np.float32)}})
    # tear the newest: corrupt its payload in place (manifest + size kept,
    # so only the checksum can catch it)
    step3 = os.path.join(root, "step_00000003")
    payload = [f for f in os.listdir(step3) if f.endswith(".pdparams")][0]
    with open(os.path.join(step3, payload), "r+b") as f:
        f.seek(10)
        f.write(b"\xde\xad\xbe\xef")
    step, state = m.load()
    fell_back = step == 2 and float(state["params"]["w"][0]) == 2.0
    # an interrupted save (payload written, manifest never committed)
    # must not be visible at all
    m4 = CheckpointManager(os.path.join(work, "ckpt_partial"), keep=0)
    m4.save(7, {"params": {"w": np.zeros(2, np.float32)}})
    os.remove(os.path.join(m4.root, "step_00000007", "MANIFEST.json"))
    invisible = complete_steps(m4.root) == []
    return {"ok": bool(fell_back and invisible), "fallback_step": step,
            "torn_visible": not fell_back, "partial_visible": not invisible}


# ---------------------------------------------------------------------------
def drill_nan_sentinel(work):
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.amp import GradScaler
    from paddle_tpu.parallel import TrainStep
    from paddle_tpu.testing.faults import FaultPlan, install_plan, clear_plan
    from paddle_tpu.utils.monitor import stat_get
    paddle.seed(0)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    scaler = GradScaler(enable=True, init_loss_scaling=1024.0,
                        decr_every_n_nan_or_inf=1)
    step = TrainStep(net, opt, loss_fn=nn.MSELoss(), sentinel=True,
                     grad_scaler=scaler)
    install_plan(FaultPlan.parse("nan_grad:step=2"))
    try:
        rng = np.random.RandomState(0)
        x = rng.randn(16, 8).astype("float32")
        y = rng.randn(16, 4).astype("float32")
        skipped0 = stat_get("train_skipped_steps")
        losses, snaps = [], []
        for _ in range(4):
            snaps.append(
                np.asarray(step.state["params"][
                    sorted(step.state["params"])[0]]).copy())
            losses.append(float(step((x,), y)))
        skipped = stat_get("train_skipped_steps") - skipped0
        p_name = sorted(step.state["params"])[0]
        # step 2 (the injected one) must commit nothing: the param value
        # before step 3 equals the value before step 2
        frozen = bool(np.array_equal(snaps[2], snaps[1]))
        moved_after = not np.array_equal(
            np.asarray(step.state["params"][p_name]), snaps[2])
        return {"ok": bool(skipped == 1 and frozen and moved_after
                           and scaler.get_loss_scaling() == 512.0
                           and np.isfinite(losses[3])),
                "skipped_steps": skipped, "params_frozen_on_bad_step": frozen,
                "scale_after": scaler.get_loss_scaling(),
                "trained_through": bool(moved_after)}
    finally:
        clear_plan()


# ---------------------------------------------------------------------------
def drill_store_drop(work):
    from paddle_tpu.distributed.fleet.base.tcp_store import TCPStore
    from paddle_tpu.testing.faults import FaultPlan, install_plan, clear_plan
    store = TCPStore("127.0.0.1", 0, is_master=True)
    install_plan(FaultPlan.parse(
        "store_drop:op=set,at=1; store_drop:op=add,at=2,count=2"))
    try:
        store.set("k", b"v1")               # drop #1: retried, must land
        ok_set = store.get("k", wait=False) == b"v1"
        total = 0
        for _ in range(4):                  # drops #2,#3 on the add path
            total = store.add("ctr", 1)
        ok_add = total == 4                 # retries must not double-count
        return {"ok": bool(ok_set and ok_add), "set_survived": ok_set,
                "add_total": total}
    finally:
        clear_plan()
        store.close()


# ---------------------------------------------------------------------------
def drill_slow_step(work):
    from paddle_tpu.testing.faults import (FaultPlan, install_plan,
                                           clear_plan, step_hook)
    install_plan(FaultPlan.parse("slow:rank=0,step=1,seconds=0.4"))
    try:
        t0 = time.perf_counter()
        step_hook(0, rank=0)
        fast = time.perf_counter() - t0
        t0 = time.perf_counter()
        step_hook(1, rank=0)
        slow = time.perf_counter() - t0
        return {"ok": bool(slow >= 0.4 and fast < 0.2),
                "stall_s": round(slow, 3)}
    finally:
        clear_plan()


# ---------------------------------------------------------------------------
_KILL_WORKER = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, @REPO@)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.checkpoint import CheckpointManager
from paddle_tpu.parallel import TrainStep

work = sys.argv[1]
total_steps = int(sys.argv[2])
paddle.seed(0)
net = nn.Linear(8, 4)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
step = TrainStep(net, opt, loss_fn=nn.MSELoss())
step.attach_checkpoint_manager(
    CheckpointManager(os.path.join(work, "ckpt"), rank=0, world_size=1))
try:
    step.restore_from_checkpoint()
except FileNotFoundError:
    pass
while int(step.state["step"]) < total_steps:
    s = int(step.state["step"])          # deterministic per-step batch
    rng = np.random.RandomState(1000 + s)
    x = rng.randn(16, 8).astype("float32")
    y = rng.randn(16, 4).astype("float32")
    step((x,), y)                        # fault step_hook fires in here
    step.save_checkpoint(wait=True)
out = {n: np.asarray(v).tolist() for n, v in step.state["params"].items()}
with open(os.path.join(work, "final.json"), "w") as f:
    json.dump({"step": int(step.state["step"]), "params": out}, f)
"""


def drill_kill_resume(work):
    import numpy as np
    from paddle_tpu.distributed.fleet.elastic import ElasticLaunch
    total_steps, kill_at = 6, 3
    script = os.path.join(work, "kill_worker.py")
    with open(script, "w") as f:
        f.write(_KILL_WORKER.replace("@REPO@", repr(REPO)))

    def run(tag, plan):
        wdir = os.path.join(work, tag)
        os.makedirs(wdir, exist_ok=True)
        supervisor = []

        def spawn(local):
            env = dict(os.environ, PADDLE_TRAINER_ID="0",
                       PADDLE_TRAINERS_NUM="1", JAX_PLATFORMS="cpu")
            gen = supervisor[0].generation if supervisor else 0
            if plan and gen == 0:
                # the fault lives in the FIRST incarnation only — the
                # restarted gang must run clean, like a real preemption
                env["PADDLE_TPU_FAULT_PLAN"] = plan
            else:
                env.pop("PADDLE_TPU_FAULT_PLAN", None)
            return subprocess.Popen(
                [sys.executable, script, wdir, str(total_steps)], env=env)

        el = ElasticLaunch(spawn, 1, max_restarts=2, poll_s=0.2, gang=True)
        supervisor.append(el)
        rc, restarts = el.run()
        with open(os.path.join(wdir, "final.json")) as f:
            return rc, restarts[0], json.load(f)

    rc_f, restarts, faulted = run(
        "faulted", f"kill:rank=0,step={kill_at}")
    rc_c, _, clean = run("clean", None)
    same = faulted["step"] == clean["step"] == total_steps and all(
        np.array_equal(np.asarray(faulted["params"][n]),
                       np.asarray(clean["params"][n]))
        for n in clean["params"])
    return {"ok": bool(rc_f == 0 and rc_c == 0 and restarts >= 1 and same),
            "restarts": restarts, "resumed_step": faulted["step"],
            "params_match_uninterrupted": bool(same)}


# ---------------------------------------------------------------------------
SCENARIOS = {
    "torn_checkpoint": drill_torn_checkpoint,
    "nan_sentinel": drill_nan_sentinel,
    "store_drop": drill_store_drop,
    "slow_step": drill_slow_step,
    "kill_resume": drill_kill_resume,
}


def main(argv=None):
    p = argparse.ArgumentParser("fault_drill")
    p.add_argument("--dry", action="store_true",
                   help="toy-scale CPU run (the only mode today)")
    p.add_argument("scenarios", nargs="*", choices=list(SCENARIOS) + [[]],
                   help="subset to run (default: all)")
    args = p.parse_args(argv)
    names = args.scenarios or list(SCENARIOS)
    work = tempfile.mkdtemp(prefix="fault_drill_")
    failed = 0
    try:
        for name in names:
            t0 = time.perf_counter()
            try:
                rec = SCENARIOS[name](work)
            except Exception as e:  # a drill crash is a failed drill
                rec = {"ok": False, "error": repr(e)}
            rec.update(scenario=name, dry=bool(args.dry),
                       wall_s=round(time.perf_counter() - t0, 2))
            _emit(rec)
            failed += 0 if rec["ok"] else 1
    finally:
        shutil.rmtree(work, ignore_errors=True)
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
