"""paddle_tpu.testing: deterministic fault injection for recovery tests.

SURVEY.md's failure-detection gap note: the reference ships liveness
monitoring but *no fault injection framework* — recovery paths rot
because nothing exercises them.  :mod:`.faults` closes that gap: an
env-driven (``PADDLE_TPU_FAULT_PLAN``) plan of rank kills, store
connection drops, NaN gradients and slow ranks, deterministic per seed,
consumed by the TrainStep / TCPStore hooks and runnable standalone via
``tools/fault_drill.py``.
"""
from .faults import (  # noqa: F401
    Fault, FaultPlan, active_plan, clear_plan, install_plan, step_hook)

__all__ = ["Fault", "FaultPlan", "active_plan", "install_plan",
           "clear_plan", "step_hook"]
