"""Deterministic fault-injection plans (``PADDLE_TPU_FAULT_PLAN``).

A plan is a semicolon-separated list of fault clauses::

    kill:rank=1,step=5; nan_grad:step=3; store_drop:op=set,at=2,count=3;
    slow:rank=0,step=4,seconds=2; seed=7

Clause kinds and their knobs:

``kill``        SIGKILL this process when the step hook runs at
                ``step`` on ``rank`` (rank omitted = every rank).
``nan_grad``    the TrainStep injects NaN into every gradient leaf at
                ``step`` — IN-GRAPH, so the numerics sentinel is
                exercised exactly the way a real blow-up reaches it.
``store_drop``  the TCPStore client hard-drops its connection right
                before the ``at``-th matching op (1-based over ops of
                kind ``op``; ``op=any`` matches all), ``count`` times
                in a row — exercising the retry/reconnect path.
``slow``        the step hook sleeps ``seconds`` at ``step`` on
                ``rank`` — a straggler for the heartbeat watchdog.
``spawn_fail``  the autoscaling controller's next replica spawn raises
                instead of launching — the ``at``-th query (1-based)
                matches, ``count`` times in a row — exercising the
                retry-next-poll path and the spawn-failure postmortem.
``drain_hang``  a drain order wedges: the replica stops accepting but
                never reports drained, forcing the controller's
                drain-timeout escalation (evict + postmortem).
``canary_mismatch``  the rolling-update canary comparison reports a
                bit-mismatch regardless of the real outputs, forcing
                the instant-rollback path.  Same ``at``/``count``
                occurrence knobs as ``spawn_fail``.
``seed=N``      scopes probabilistic triggers: a clause with ``p=0.3``
                fires iff a hash of (seed, kind, occurrence-counter)
                lands under p — deterministic across reruns and ranks,
                no global RNG state touched.

The plan is installed from the env at first use (or programmatically
via :func:`install_plan`); every trigger decision is pure in
(plan string, seed, call counters), so a drill reproduces bit-for-bit.
"""
from __future__ import annotations

import hashlib
import os
import signal
import threading
import time
from typing import Dict, List, Optional

_ENV = "PADDLE_TPU_FAULT_PLAN"


def _hash01(seed: int, *parts) -> float:
    """Deterministic uniform in [0,1) from (seed, parts)."""
    h = hashlib.sha256(
        ("/".join([str(seed)] + [str(p) for p in parts])).encode()).digest()
    return int.from_bytes(h[:8], "little") / 2.0 ** 64


class Fault:
    """One parsed clause: ``kind`` + keyword fields."""

    __slots__ = ("kind", "fields", "fired", "index")

    def __init__(self, kind: str, fields: Dict[str, str], index: int = 0):
        self.kind = kind
        self.fields = fields
        self.fired = 0
        self.index = index      # clause position: the stable counter key

    def get_int(self, key, default=None):
        v = self.fields.get(key)
        return default if v is None else int(v)

    def get_float(self, key, default=None):
        v = self.fields.get(key)
        return default if v is None else float(v)

    def matches_rank_step(self, rank: int, step: int) -> bool:
        frank = self.get_int("rank")
        if frank is not None and frank != rank:
            return False
        return self.get_int("step") == step

    def __repr__(self):
        kv = ",".join(f"{k}={v}" for k, v in sorted(self.fields.items()))
        return f"Fault({self.kind}:{kv})"


class FaultPlan:
    """Parsed plan + the mutable occurrence counters trigger decisions
    consume.  Thread-safe: store ops arrive from many threads."""

    def __init__(self, faults: List[Fault], seed: int = 0, spec: str = ""):
        self.faults = faults
        self.seed = seed
        self.spec = spec
        self._counters: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- parsing ------------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        faults, seed = [], 0
        for clause in spec.split(";"):
            clause = clause.strip()
            if not clause:
                continue
            if clause.startswith("seed="):
                seed = int(clause[5:])
                continue
            kind, _, rest = clause.partition(":")
            kind = kind.strip()
            if kind not in ("kill", "nan_grad", "store_drop", "slow",
                            "spawn_fail", "drain_hang", "canary_mismatch"):
                raise ValueError(f"unknown fault kind {kind!r} in plan "
                                 f"{spec!r}")
            fields = {}
            for kv in rest.split(","):
                kv = kv.strip()
                if not kv:
                    continue
                k, _, v = kv.partition("=")
                fields[k.strip()] = v.strip()
            faults.append(Fault(kind, fields, index=len(faults)))
        return cls(faults, seed=seed, spec=spec)

    def of_kind(self, kind: str) -> List[Fault]:
        return [f for f in self.faults if f.kind == kind]

    def _sampled(self, f: Fault, counter_key: str) -> bool:
        """Apply the optional p= gate deterministically."""
        p = f.get_float("p")
        if p is None:
            return True
        with self._lock:
            n = self._counters[counter_key] = \
                self._counters.get(counter_key, 0) + 1
        return _hash01(self.seed, f.kind, counter_key, n) < p

    # -- trigger queries ----------------------------------------------------
    def should_kill(self, rank: int, step: int) -> bool:
        return any(f.matches_rank_step(rank, step)
                   and self._sampled(f, f"kill/{rank}")
                   for f in self.of_kind("kill"))

    def nan_grad_steps(self) -> List[int]:
        """Steps at which the TrainStep injects NaN gradients (consumed
        at trace time: the injection is part of the compiled graph)."""
        return [f.get_int("step") for f in self.of_kind("nan_grad")
                if f.get_int("step") is not None]

    def slow_delay(self, rank: int, step: int) -> float:
        return sum(f.get_float("seconds", 1.0)
                   for f in self.of_kind("slow")
                   if f.matches_rank_step(rank, step))

    def should_drop_store_op(self, op: str) -> bool:
        """True when the TCPStore client must sever its connection before
        sending this op.  ``at`` counts 1-based occurrences of the
        matching op kind; ``count`` drops that many consecutive
        occurrences (default 1)."""
        hit = False
        for f in self.of_kind("store_drop"):
            fop = f.fields.get("op", "any")
            if fop not in ("any", op):
                continue
            key = f"store/{fop}/{f.index}"
            with self._lock:
                n = self._counters[key] = self._counters.get(key, 0) + 1
            at = f.get_int("at", 1)
            if at <= n < at + f.get_int("count", 1) and \
                    self._sampled(f, key + "/p"):
                f.fired += 1
                hit = True
        return hit

    def _counted(self, kind: str) -> bool:
        """Occurrence-counted trigger shared by the lifecycle drills:
        the ``at``-th query (1-based) of this kind matches, ``count``
        consecutive times (default 1), subject to the ``p=`` gate."""
        hit = False
        for f in self.of_kind(kind):
            key = f"{kind}/{f.index}"
            with self._lock:
                n = self._counters[key] = self._counters.get(key, 0) + 1
            at = f.get_int("at", 1)
            if at <= n < at + f.get_int("count", 1) and \
                    self._sampled(f, key + "/p"):
                f.fired += 1
                hit = True
        return hit

    def should_fail_spawn(self) -> bool:
        """True when the controller's next replica spawn must fail."""
        return self._counted("spawn_fail")

    def should_hang_drain(self) -> bool:
        """True when this drain order must wedge (stop accepting but
        never report drained), forcing the caller's timeout path."""
        return self._counted("drain_hang")

    def should_mismatch_canary(self) -> bool:
        """True when the canary bit-compare must report a mismatch."""
        return self._counted("canary_mismatch")

    def __repr__(self):
        return f"FaultPlan(seed={self.seed}, {self.faults})"


# -- process-wide active plan ------------------------------------------------
_state = {"plan": None, "env": None, "installed": False}


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Programmatically set the active plan (overrides the env until
    :func:`clear_plan`); ``install_plan(None)`` suppresses any env plan."""
    _state["plan"] = plan
    _state["installed"] = True
    return plan


def clear_plan() -> None:
    """Drop any plan (installed or env-parsed); the env is re-read on the
    next :func:`active_plan` call."""
    _state["plan"] = None
    _state["installed"] = False
    _state["env"] = None


def active_plan() -> Optional[FaultPlan]:
    """The active plan: programmatically installed, else parsed from
    ``PADDLE_TPU_FAULT_PLAN``.  Re-parses when the env var CHANGES (so
    monkeypatched tests get fresh counters) but keeps the same instance
    — and its counters — while it is stable."""
    if _state["installed"]:
        return _state["plan"]
    env = os.environ.get(_ENV, "")
    if env != _state["env"]:
        _state["env"] = env
        _state["plan"] = FaultPlan.parse(env) if env.strip() else None
    return _state["plan"]


def step_hook(step: int, rank: Optional[int] = None) -> None:
    """Host-side per-step injection point (TrainStep calls this; a custom
    loop or drill script can too): applies ``slow`` then ``kill``.

    SIGKILL — not sys.exit — because the scenario under test is a
    preempted/OOM-killed worker: no atexit handlers, no flushes, no
    chance for a half-written checkpoint to be 'cleaned up' into looking
    valid.
    """
    plan = active_plan()
    if plan is None:
        return
    if rank is None:
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    delay = plan.slow_delay(rank, step)
    if delay > 0:
        time.sleep(delay)
    if plan.should_kill(rank, step):
        os.kill(os.getpid(), signal.SIGKILL)
