"""Scope + Executor: static-program execution.

Reference parity: Scope ≙ paddle/fluid/framework/scope.h (name→Variable map);
Executor.run ≙ python/paddle/fluid/executor.py:916 → C++ Executor::Run
(executor.cc:179) whose hot loop interprets ops one-by-one (executor.cc:473).

TPU-first: instead of op-by-op interpretation, ``run`` compiles the WHOLE
block into one XLA computation (jax.jit of the sequential replay) cached by
(program version, feed signature) — the analogue of the reference's program
cache (executor.py:1277) but yielding a single fused device program, which is
the idiomatic (and only fast) way to execute a graph on TPU.  Startup
programs (initializers) run eagerly, matching their one-shot nature.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..profiler import ledger as _ledger
from ..profiler import profiling_enabled as _prof_on
from ..profiler import span as _span
from .program import Program, Variable, default_main_program


class Scope:
    """scope.h parity: name → array, with parent chain."""

    def __init__(self, parent: Optional["Scope"] = None):
        self._vars: Dict[str, jnp.ndarray] = {}
        self._parent = parent
        self._kids: List["Scope"] = []

    def new_scope(self):
        s = Scope(self)
        self._kids.append(s)
        return s

    def drop_kids(self):
        self._kids.clear()

    def find_var(self, name):
        if name in self._vars:
            return self._vars[name]
        if self._parent is not None:
            return self._parent.find_var(name)
        return None

    def set_var(self, name, value):
        self._vars[name] = value

    def var_names(self):
        return list(self._vars)

    def __contains__(self, name):
        return self.find_var(name) is not None


_global_scope = Scope()


def global_scope() -> Scope:
    return _global_scope


def scope_guard(scope):
    import contextlib

    @contextlib.contextmanager
    def guard():
        global _global_scope
        prev = _global_scope
        _global_scope = scope
        try:
            yield
        finally:
            _global_scope = prev
    return guard()



def _collect_persistables(program, scope, persist_names):
    """Resolve persistable values, seeding RNG-key vars (key_advance
    inputs) from the framework generator when a scope never saw them — a
    deserialized program or a fresh Scope carries no record-time seeding,
    and a missing KEY is not a user error the way a missing weight is."""
    rng_keys = {op.input_names[0]
                for op in program.global_block().ops
                if op.prim == "key_advance"}
    vals = []
    for n in persist_names:
        v = scope.find_var(n)
        if v is None:
            if n in rng_keys:
                from ..framework.random import key_raw, default_generator
                v = key_raw(default_generator.next_key())
                scope.set_var(n, v)
            else:
                raise RuntimeError(
                    f"persistable {n!r} not initialized — run the startup "
                    f"program first (exe.run(paddle.static."
                    f"default_startup_program()))")
        vals.append(v)
    return vals


class Executor:
    """executor.py:475 parity."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}
        self._aot_dir = None
        self._cache_extra_key = None
        # train_from_dataset replays, keyed per (program, feeds, fetches):
        # re-jitting the epoch scan every call would pay a full XLA
        # recompile per epoch (jit caching lives on the jitted callable)
        self._epoch_fn_cache = {}

    # -- AOT executable cache (inference/api SetOptimCacheDir parity) --------
    def set_aot_cache_dir(self, path):
        """Persist compiled PJRT executables under ``path`` so a process
        restart replays them instead of recompiling — the TPU seat of the
        reference's optimization-cache dir (analysis_config SetOptimCacheDir)
        and TensorRT engine serialization.  Entries go through
        ``jit.persistent_cache`` (atomic writes + sha256 manifests, the
        checkpoint discipline), so a torn write can never poison a load."""
        import os
        os.makedirs(path, exist_ok=True)
        self._aot_dir = path

    def _exec_cache(self):
        """(cache, writable): the legacy per-predictor optim-cache dir
        (always readwrite — the caller asked for it explicitly) or the
        FLAGS_executable_cache global dir; (None, False) when neither is
        configured — the one off-path branch."""
        from ..jit import persistent_cache as _pcache
        if self._aot_dir is not None:
            return _pcache.cache_at(self._aot_dir), True
        c = _pcache.get_cache()
        if c is not None:
            return c, _pcache.mode() == "readwrite"
        return None, False

    def set_cache_extra_key(self, key):
        """Fold an extra token into the AOT executable digest — the
        Predictor passes the model's quantization signature here so int8
        and float programs sharing one optim-cache dir never collide onto
        each other's serialized executables."""
        self._cache_extra_key = None if key is None else str(key)

    def _aot_digest(self, program, feed_names, feed_vals, union,
                    persist_names, persist_vals):
        """Restart-stable executable key: program structure + IO signature
        (program._uid is per-process, useless across restarts)."""
        import hashlib
        h = hashlib.sha1()

        def attr_bytes(v):
            # arrays hash by VALUE (repr elides large arrays, and any
            # truncation lets distinct programs collide onto a stale
            # executable); everything else hashes its full repr
            if hasattr(v, "dtype") and hasattr(v, "shape"):
                a = np.asarray(v)
                return f"{a.shape}:{a.dtype}:".encode() + a.tobytes()
            return repr(v).encode()

        for op in program.global_block().ops:
            h.update(repr((op.prim, tuple(op.input_names),
                           tuple(op.output_names))).encode())
            for k in sorted(op.attrs or {}):
                h.update(k.encode())
                h.update(attr_bytes(op.attrs[k]))
        for n, v in zip(feed_names, feed_vals):
            h.update(f"{n}:{v.shape}:{v.dtype}".encode())
        for n, v in zip(persist_names, persist_vals):
            h.update(f"{n}:{getattr(v, 'shape', ())}:"
                     f"{getattr(v, 'dtype', '')}".encode())
        h.update(repr(tuple(union)).encode())
        if self._cache_extra_key is not None:
            h.update(self._cache_extra_key.encode())
        return h.hexdigest()


    # -- eager interpretation (startup programs / debugging) -----------------
    def _run_eager(self, program: Program, scope: Scope):
        env = {}
        for op in program.global_block().ops:
            ins = [self._lookup(n, env, scope, program) for n in op.input_names]
            outs = op.run_fn()(*ins)
            for name, val in zip(op.output_names, outs):
                env[name] = val
        self._writeback(program, env, scope)
        return env

    @staticmethod
    def _lookup(name, env, scope, program):
        if name in env:
            return env[name]
        v = scope.find_var(name)
        if v is None:
            raise RuntimeError(f"variable {name!r} has no value (not fed, "
                               f"not initialized in scope)")
        return v

    @staticmethod
    def _writeback(program, env, scope):
        for b in program.blocks:
            for name, var in b.vars.items():
                if var.persistable and name in env:
                    scope.set_var(name, env[name])

    # -- compiled run --------------------------------------------------------
    def _persistable_names(self, program):
        names = []
        for b in program.blocks:
            for name, var in b.vars.items():
                if var.persistable and name not in names:
                    names.append(name)
        return names

    def _build_replay(self, program, feed_names, fetch_names, persist_names,
                      written):
        ops = program.global_block().ops

        def replay(feed_vals, persist_vals):
            env = dict(zip(feed_names, feed_vals))
            env.update(zip(persist_names, persist_vals))
            for op in ops:
                ins = [env[n] for n in op.input_names]
                outs = op.run_fn()(*ins)
                for name, val in zip(op.output_names, outs):
                    env[name] = val
            fetches = tuple(env[n] for n in fetch_names)
            updates = tuple(env[n] for n in written)
            return fetches, updates

        return replay

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program or default_main_program()
        compiled = getattr(program, "_compiled_program", None)
        if compiled is None and type(program).__name__ == "CompiledProgram":
            compiled = program
            program = compiled._program
        scope = scope or global_scope()
        feed = feed or {}
        fetch_list = fetch_list or []

        # startup / init programs: run once, eagerly
        if any(op.prim == "@init" for op in program.global_block().ops):
            self._run_eager(program, scope)
            return []

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        with _span("executor::data_feed"):
            feed_items = sorted(feed.items())
            feed_names = [k for k, _ in feed_items]
            feed_vals = [v._value if isinstance(v, Tensor)
                         else jnp.asarray(v) for _, v in feed_items]

        persist_names = self._persistable_names(program)
        written = [n for n in persist_names
                   if any(n in op.output_names
                          for op in program.global_block().ops)]

        # cache per (program, feed signature); the compiled replay returns
        # the UNION of all fetch sets seen so far, so alternating fetch
        # lists (loss-only vs loss+acc) share one compiled program instead
        # of one per distinct fetch tuple. A new fetch name recompiles
        # once, then the union is stable.
        key = (program._uid, program._version,
               tuple((n, v.shape, str(v.dtype))
                     for n, v in zip(feed_names, feed_vals)))
        entry = self._cache.get(key) if use_program_cache else None
        fresh = entry is None or not set(fetch_names) <= set(entry[0])
        aot_loaded = False
        if fresh:
            t_compile = time.perf_counter()
            union = list(entry[0]) if entry else []
            union += [n for n in fetch_names if n not in union]
            replay = self._build_replay(program, feed_names, union,
                                        persist_names, written)
            jitted = None
            pcache, pc_writable = (self._exec_cache() if compiled is None
                                   else (None, False))
            if pcache is not None:
                # AOT executable cache: lowering needs the persist values,
                # so gather them here (run() re-gathers below — cheap dict
                # reads)
                pv = [scope.find_var(n) for n in persist_names]
                if all(v is not None for v in pv):
                    from ..jit import persistent_cache as _pcache
                    digest = _pcache.digest_for(
                        ("executor",),
                        extra_key=self._aot_digest(program, feed_names,
                                                   feed_vals, union,
                                                   persist_names, pv))
                    t_load = time.perf_counter()
                    jitted = pcache.load(digest)
                    aot_loaded = jitted is not None
                    if aot_loaded:
                        _pcache.note_hit("executor_aot",
                                         time.perf_counter() - t_load)
                    else:
                        _pcache.note_miss("executor_aot")
                        with _span("executor::compile"):
                            compiled_exe = jax.jit(replay).lower(
                                feed_vals, pv).compile()
                        if pc_writable:
                            pcache.store(
                                digest, compiled_exe,
                                key=key + (tuple(union),),
                                site=f"executor:{program._uid}",
                                kind="executor_aot")
                        jitted = compiled_exe
                        from ..utils.monitor import stat_add
                        stat_add("STAT_executor_compiles")
            if jitted is None:
                jitted = jax.jit(replay)
                from ..utils.monitor import stat_add
                stat_add("STAT_executor_compiles")
            entry = (union, jitted, persist_names, written)
            self._cache[key] = entry
        union, jitted, persist_names, written = entry
        fetch_pos = [union.index(n) for n in fetch_names]

        for hook in getattr(program, "_pre_run_hooks", []):
            hook(scope)

        persist_vals = _collect_persistables(program, scope,
                                             persist_names)
        site = f"executor:{program._uid}"

        if fresh:
            from ..analysis import lint_enabled as _lint_on
            if _lint_on():
                # graph lint over the fresh program (abstract eval only,
                # amortized per compile): the jaxpr passes see the whole
                # replay; program_info adds the op-level fetch view so
                # dead-fetch names the op, not a jaxpr equation
                from ..analysis import lint_traced
                _ops = [(getattr(op, "type", op.prim),
                         tuple(op.input_names), tuple(op.output_names))
                        for op in program.global_block().ops]
                lint_traced(
                    replay, (feed_vals, persist_vals),
                    site=site, kind="executor",
                    cache_key=key + (tuple(union),),
                    prev_key=_ledger.last_key(site),
                    program_info={"ops": _ops, "fetches": union,
                                  "written": written,
                                  "persistable": persist_names,
                                  "feeds": feed_names})

        if compiled is not None and compiled._data_parallel:
            from ..parallel.api import batch_sharding
            from ..parallel.mesh import get_mesh
            mesh = get_mesh()
            with _span("executor::data_feed"):
                feed_vals = [jax.device_put(
                    v, batch_sharding(mesh, ndim=max(v.ndim, 1)))
                    for v in feed_vals]

        if fresh:
            # trace + XLA compile happen inside this first dispatch (the
            # AOT path compiled above; a deserialized executable skipped
            # it) — ledger the wall time and the cache-key diff.  A
            # persistent-cache load is ledgered as ``cache_load`` so warm
            # starts show zero fresh XLA compiles while the steady-state
            # checks keep counting events at this site unchanged.
            with _span("executor::compile"):
                fetches, updates = jitted(feed_vals, persist_vals)
            _ledger.record_compile(
                site, "cache_load" if aot_loaded else "executor",
                key + (tuple(union),),
                (time.perf_counter() - t_compile) * 1e3,
                extra={"orig_kind": "executor_aot"} if aot_loaded
                else None)
        else:
            _ledger.record_cache_hit(site)
            with _span("executor::device_execute"):
                fetches, updates = jitted(feed_vals, persist_vals)
                if _prof_on():
                    # fence so the span reflects device time, not just
                    # async dispatch
                    jax.block_until_ready((fetches, updates))
        for n, val in zip(written, updates):
            scope.set_var(n, val)
        picked = [fetches[i] for i in fetch_pos]
        if return_numpy:
            with _span("executor::fetch"):
                return [np.asarray(f) for f in picked]
        return [Tensor(f) for f in picked]

    def _epoch_entry(self, program, feed_names, fetch_names):
        """The jitted scanned-epoch function for ``program`` — one per
        (program, feed/fetch set): later calls (and later EPOCHS through
        them) hit jax.jit's executable cache instead of retracing +
        recompiling the epoch program every time.  Keyed like exe.run's
        compile cache (program _uid + _version: rewrite passes bump
        _version, compiler.py:110); FIFO-bounded so a long-lived Executor
        over many programs cannot grow unboundedly.  Returns
        ``(jitted_epoch_fn, persist_names)``."""
        persist_names = self._persistable_names(program)
        ck = (program._uid, program._version,
              tuple(op.type for op in program.global_block().ops),
              tuple(feed_names), tuple(fetch_names), tuple(persist_names))
        cached = self._epoch_fn_cache.get(ck)
        if cached is None and len(self._epoch_fn_cache) >= 8:
            self._epoch_fn_cache.pop(next(iter(self._epoch_fn_cache)))
        if cached is None:
            written = [n for n in persist_names
                       if any(n in op.output_names
                              for op in program.global_block().ops)]
            replay = self._build_replay(program, feed_names, fetch_names,
                                        persist_names, written)
            w_pos = [persist_names.index(n) for n in written]

            def epoch_fn(persist_vals, feed_stacks, mask):
                def step(carry, xs):
                    feeds, m = xs[:-1], xs[-1]
                    fetches, updates = replay(list(feeds), list(carry))
                    carry = list(carry)
                    for p, u in zip(w_pos, updates):
                        # masked tail steps keep the carry (padding must
                        # not apply optimizer updates)
                        carry[p] = jnp.where(m, u, carry[p])
                    return tuple(carry), fetches
                return jax.lax.scan(step, tuple(persist_vals),
                                    (*feed_stacks, mask))

            cached = (jax.jit(epoch_fn), program)
            self._epoch_fn_cache[ck] = cached
        return cached[0], persist_names

    def epoch_executable(self, program=None, dataset=None, fetch_list=None,
                         scope=None, chunk_steps=256):
        """AOT-lower the scanned epoch program for ``dataset`` and return
        the compiled executable WITHOUT running the epoch — the
        lowered-executable access surface for the dataset-training engine
        (the HLO audit and tools/mfu_audit.py read ``cost_analysis()`` /
        ``memory_analysis()`` / ``as_text()`` off it; the hand-maintained
        FLOP models this replaces could silently drift from the program).

        ``dataset`` must be a dict of pre-stacked arrays
        ``{var_name: [steps, ...]}`` (the bench/mfu shape); at most
        ``chunk_steps`` leading steps are lowered.
        """
        program = program or default_main_program()
        scope = scope or global_scope()
        if not isinstance(dataset, dict) or not dataset:
            raise TypeError("epoch_executable needs a dict of pre-stacked "
                            "arrays {var_name: [steps, ...]}")
        feed_names = sorted(dataset)
        fetch_list = fetch_list or []
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        jitted, persist_names = self._epoch_entry(program, feed_names,
                                                  fetch_names)
        k = min(int(chunk_steps),
                len(next(iter(dataset.values()))))
        feeds = tuple(jnp.asarray(dataset[n][:k]) for n in feed_names)
        mask = jnp.ones((k,), bool)
        persist_vals = tuple(_collect_persistables(program, scope,
                                                   persist_names))
        return jitted.lower(persist_vals, feeds, mask).compile()

    # -- dataset-driven training (Trainer/DeviceWorker runtime) -------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100, epochs=1,
                           chunk_steps=256):
        """trainer.h:51 / device_worker.h parity: stream the dataset
        through a compiled scan — no Python between steps, bounded HBM.

        The reference's DistMultiTrainer spins C++ DeviceWorkers that pull
        minibatches from a DataFeed CHANNEL (data_feed.h:305) and run the
        op graph per batch.  The TPU-shape of that channel: host-stack the
        feeds in chunks of ``chunk_steps``, double-buffer each chunk onto
        the device while the previous chunk's ``lax.scan`` runs, and carry
        the persistables across chunks inside one jitted scan per chunk
        shape.  Peak device memory holds ~2 chunks + parameters instead of
        the whole epoch; the tail chunk pads to an adaptive bucket with a
        per-step validity mask (masked steps keep the carry), so one
        compiled program serves every full chunk.

        ``dataset``: an iterable of feed dicts {var_name: ndarray}, an
        io.DataLoader yielding such dicts, or a dict of pre-stacked
        arrays {var_name: [steps, ...]}.
        Returns {fetch_name: [epochs*steps, ...] numpy} for fetch_list.
        """
        import itertools
        program = program or default_main_program()
        scope = scope or global_scope()
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        fetch_list = fetch_list or []
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        chunk_steps = max(1, int(chunk_steps))

        # -- the DataFeed channel: a re-iterable source of host chunks ----
        if isinstance(dataset, dict):
            if not dataset:
                raise ValueError("train_from_dataset: empty dataset")
            # values already on DEVICE stay there: chunk by device-side
            # slicing (pulling them to host and re-uploading per epoch
            # would cost two full-epoch tunnel transfers for nothing)
            host = {k: (v if isinstance(v, jax.Array) else np.asarray(v))
                    for k, v in dataset.items()}
            n_total = len(next(iter(host.values())))

            def raw_chunks():
                for s in range(0, n_total, chunk_steps):
                    yield {k: v[s:s + chunk_steps] for k, v in host.items()}
        else:
            if iter(dataset) is dataset:
                # one-shot iterator: materialize HOST-side once (epochs may
                # re-read); device memory stays chunk-bounded regardless
                dataset = list(dataset)

            def raw_chunks():
                buf, count = {}, 0
                for feed in dataset:
                    for k, v in feed.items():
                        buf.setdefault(k, []).append(np.asarray(
                            v.numpy() if isinstance(v, Tensor) else v))
                    count += 1
                    if count == chunk_steps:
                        yield {k: np.stack(vs) for k, vs in buf.items()}
                        buf, count = {}, 0
                if count:
                    yield {k: np.stack(vs) for k, vs in buf.items()}

        # epoch 0 fills a host-side chunk cache; later epochs replay it
        # instead of re-stacking every feed (the chunks ARE the host copy)
        _chunk_cache: list = []

        def chunk_iter():
            if _chunk_cache:
                yield from _chunk_cache
                return
            for ch in raw_chunks():
                _chunk_cache.append(ch)
                yield ch

        head_it = chunk_iter()
        first = next(head_it, None)
        if first is None:
            raise ValueError("train_from_dataset: empty dataset")
        feed_names = sorted(first)

        jitted, persist_names = self._epoch_entry(program, feed_names,
                                                  fetch_names)

        def upload(chunk):
            """Pad to a stable bucket, ship to device (async H2D)."""
            from ..distributed.ps.device_cache import pad_adaptive
            sp = _span("executor::dataset_upload")
            sp.begin()
            n = len(chunk[feed_names[0]])
            # tail buckets never exceed the full-chunk shape (the documented
            # device budget), and near-full tails reuse the full compile
            k = (chunk_steps if n == chunk_steps
                 else min(pad_adaptive(n), chunk_steps))
            mask = np.zeros(k, bool)
            mask[:n] = True
            feeds = []
            nbytes = 0
            for name in feed_names:
                v = chunk[name]
                if len(v) < k:
                    xp = jnp if isinstance(v, jax.Array) else np
                    v = xp.concatenate(
                        [v, xp.zeros((k - len(v),) + v.shape[1:],
                                     v.dtype)])
                nbytes += v.nbytes
                # device_put is a no-op for arrays already on device
                feeds.append(jax.device_put(v))
            self._train_stats["max_chunk_bytes"] = max(
                self._train_stats["max_chunk_bytes"], nbytes)
            sp.end()
            return tuple(feeds), jax.device_put(mask), n

        persist_vals = tuple(_collect_persistables(program, scope,
                                                   persist_names))

        self._train_stats = {"chunks": 0, "max_chunk_bytes": 0}
        all_fetches = {n: [] for n in fetch_names}
        for ep in range(epochs):
            chunks = (itertools.chain([first], head_it) if ep == 0
                      else chunk_iter())
            pending = upload(next(chunks))
            while pending is not None:
                feeds, mask, n_valid = pending
                nxt = next(chunks, None)
                with _span("executor::dataset_scan"):
                    persist_vals, fetches = jitted(persist_vals, feeds,
                                                   mask)
                # double buffer: ship chunk i+1 while chunk i scans
                pending = upload(nxt) if nxt is not None else None
                self._train_stats["chunks"] += 1
                for n, f in zip(fetch_names, fetches):
                    all_fetches[n].append(np.asarray(f)[:n_valid])
            if debug and fetch_names:
                head = fetch_names[0]
                _last = all_fetches[head][-1]
                print(f"[train_from_dataset] epoch {ep}: {head} "
                      f"mean={np.mean(_last):.6f}")
        for n, val in zip(persist_names, persist_vals):
            scope.set_var(n, val)
        return {n: np.concatenate(v) if v else np.array([])
                for n, v in all_fetches.items()}

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Inference twin of train_from_dataset (same scanned engine; the
        program simply has no optimizer ops, so nothing is written back)."""
        return self.train_from_dataset(program, dataset, scope, thread,
                                       debug, fetch_list, fetch_info,
                                       print_period, epochs=1)

    def close(self):
        self._cache.clear()
