"""paddle.static.nn: static-graph layer builders.

Reference parity: python/paddle/fluid/layers/nn.py (the 36K-LoC layers DSL,
SURVEY.md §2.4) — here each builder creates eager Parameters (registered into
the program as persistables by the primitive recorder) and invokes the same
nn.functional ops that dygraph uses, so the static DSL is a thin veneer
rather than a parallel implementation.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import ParamAttr
from ..framework.tensor import Parameter
from ..framework.dtype import convert_dtype


def _make_param(shape, dtype, attr, default_init, name_hint):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = attr.initializer or default_init
    value = init(shape, convert_dtype(dtype) or "float32")
    p = Parameter(value, name=attr.name)
    return p


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """fluid.layers.fc parity."""
    from .. import ops
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= d
    if len(x.shape) > num_flatten_dims + 1:
        lead = [-1 if (d is None or d < 0) else d
                for d in x.shape[:num_flatten_dims]]
        x = ops.reshape(x, lead + [in_dim])
    w = _make_param([in_dim, size], "float32", weight_attr,
                    I.XavierUniform(), "fc_w")
    b = _make_param([size], "float32", bias_attr, I.Constant(0.0), "fc_b")
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    w = _make_param([num_filters, in_ch // groups] + list(ks), "float32",
                    param_attr, I.XavierUniform(), "conv_w")
    b = _make_param([num_filters], "float32", bias_attr, I.Constant(0.0),
                    "conv_b")
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    w = _make_param(list(size), dtype, param_attr, I.XavierUniform(), "emb_w")
    return F.embedding(input, w, padding_idx=padding_idx)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from .. import ops
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = _make_param([c], "float32", param_attr, I.Constant(1.0), "bn_s")
    bias = _make_param([c], "float32", bias_attr, I.Constant(0.0), "bn_b")
    mean = Parameter(jnp.zeros([c], jnp.float32))
    var = Parameter(jnp.ones([c], jnp.float32))
    mean.stop_gradient = True
    var.stop_gradient = True
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    """fluid.layers.layer_norm parity (layer_norm_op.cc)."""
    norm_shape = [int(d) for d in input.shape[begin_norm_axis:]]
    w = _make_param(norm_shape, "float32", param_attr, I.Constant(1.0),
                    "ln_s") if scale else None
    b = _make_param(norm_shape, "float32", bias_attr, I.Constant(0.0),
                    "ln_b") if shift else None
    out = F.layer_norm(input, norm_shape, weight=w, bias=b, epsilon=epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False,
            dropout_implementation="downgrade_in_infer", seed=None,
            name=None):
    """fluid.layers.dropout parity (dropout_op.cc)."""
    mode = ("downscale_in_infer"
            if dropout_implementation == "downgrade_in_infer"
            else "upscale_in_train")
    return F.dropout(x, p=dropout_prob, training=not is_test, mode=mode)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    """fluid.layers.pool2d parity (pool_op.cc)."""
    if global_pooling:
        sp = input.shape[2:] if data_format == "NCHW" else input.shape[1:3]
        pool_size, pool_padding, pool_stride = list(sp), 0, 1
    if pool_type == "max":
        return F.max_pool2d(input, pool_size, pool_stride, pool_padding,
                            ceil_mode=ceil_mode, data_format=data_format)
    return F.avg_pool2d(input, pool_size, pool_stride, pool_padding,
                        ceil_mode=ceil_mode, exclusive=exclusive,
                        data_format=data_format)


def conv2d_transpose(input, num_filters, filter_size=None, output_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None,
                     data_format="NCHW", name=None):
    """fluid.layers.conv2d_transpose parity (conv_transpose_op.cc)."""
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    if filter_size is None:
        if output_size is None:
            raise ValueError("conv2d_transpose needs filter_size or "
                             "output_size")
        # derive the kernel from the requested output (conv_transpose_op.cc
        # InferShape inverted): k = out - (in-1)*stride + 2*pad
        os_ = [output_size, output_size] if isinstance(output_size, int) \
            else list(output_size)
        st = [stride, stride] if isinstance(stride, int) else list(stride)
        pd = [padding, padding] if isinstance(padding, int) else list(padding)
        sp = input.shape[2:4] if data_format == "NCHW" else input.shape[1:3]
        ks = [os_[i] - (sp[i] - 1) * st[i] + 2 * pd[i] for i in range(2)]
    else:
        ks = filter_size if isinstance(filter_size, (list, tuple)) \
            else [filter_size, filter_size]
    w = _make_param([in_ch, num_filters // groups] + list(ks), "float32",
                    param_attr, I.XavierUniform(), "convt_w")
    b = _make_param([num_filters], "float32", bias_attr, I.Constant(0.0),
                    "convt_b")
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups,
                             data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode="all", param_attr=None, name=None):
    """fluid.layers.prelu parity (prelu_op.cc): alpha shared over all
    elements / per channel / per element."""
    from .. import ops
    nd = len(x.shape)
    if mode == "all":
        shape, bshape = [1], [1] * nd
    elif mode == "channel":
        shape, bshape = [x.shape[1]], [1, x.shape[1]] + [1] * (nd - 2)
    else:
        shape = list(x.shape[1:])
        bshape = [1] + shape
    w = _make_param(shape, "float32", param_attr, I.Constant(0.25),
                    "prelu_a")
    alpha = ops.reshape(w, bshape)
    zero = x * 0
    return ops.maximum(x, zero) + alpha * ops.minimum(x, zero)


def lstm(input, init_h, init_c, max_len=None, hidden_size=None,
         num_layers=1, dropout_prob=0.0, is_bidirec=False, is_test=False,
         name=None, param_attr=None, bias_attr=None):
    """fluid.layers.lstm parity (cudnn_lstm_op.cc) over the framework's
    scan-based LSTM. input [B, T, D] (batch-first here; the recorder is
    shape-driven). Returns (out, last_h, last_c)."""
    from ..nn.layer.rnn import LSTM as _LSTM
    D = input.shape[-1]
    hidden_size = hidden_size or init_h.shape[-1]
    rnn = _LSTM(D, hidden_size, num_layers=num_layers,
                direction="bidirect" if is_bidirec else "forward")
    out, (h, c) = rnn(input, (init_h, init_c))
    return out, h, c


def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation="tanh", gate_activation="sigmoid", name=None):
    """fluid.layers.gru_unit parity (gru_unit_op.cc): one GRU step.
    ``size`` is 3*hidden_dim, matching the reference convention."""
    from ..nn.layer.rnn import GRUCell
    hidden_dim = size // 3
    cell = GRUCell(input.shape[-1], hidden_dim)
    h, _ = cell(input, hidden)
    return h, h, h   # (hidden, reset_hidden_prev, gate) API shape parity


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    """fluid.layers.spectral_norm parity (spectral_norm_op.cc): normalize
    the weight by its largest singular value via power iteration."""
    if power_iters < 1:
        raise ValueError("spectral_norm needs power_iters >= 1 (no "
                         "persisted u/v state to reuse)")
    from .. import ops
    import jax.numpy as jnp
    from ..framework.tensor import Tensor, unwrap
    w = weight
    mat = ops.reshape(ops.transpose(
        w, [dim] + [i for i in range(len(w.shape)) if i != dim]),
        [w.shape[dim], -1])
    u = Tensor(jnp.ones([mat.shape[0]], jnp.float32))
    v = None
    for _ in range(power_iters):
        v = F.normalize(ops.matmul(u, mat), axis=0, epsilon=eps)
        u = F.normalize(ops.matmul(mat, v), axis=0, epsilon=eps)
    sigma = ops.sum(u * ops.matmul(mat, v))
    return w / sigma


# -- control flow (layers/control_flow.py parity) ----------------------------
from ..ops.control_flow import while_loop, cond, case, switch_case  # noqa: F401,E402


def _fill_affine_pair(w, b, c):
    """param_attr=False with a live bias (or vice versa) still needs BOTH
    affine operands — the functionals dispatch to the no-affine primitive
    whenever weight is None, which would silently drop the other half."""
    from ..framework.tensor import Parameter
    import jax.numpy as jnp
    if w is None and b is not None:
        w = Parameter(jnp.ones([c], jnp.float32))
        w.stop_gradient = True
    if b is None and w is not None:
        b = Parameter(jnp.zeros([c], jnp.float32))
        b.stop_gradient = True
    return w, b


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, data_layout="NCHW", name=None):
    """fluid.layers.group_norm parity (group_norm_op.cc)."""
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    w = _make_param([c], "float32", param_attr, I.Constant(1.0), "gn_s")
    b = _make_param([c], "float32", bias_attr, I.Constant(0.0), "gn_b")
    w, b = _fill_affine_pair(w, b, c)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    """fluid.layers.instance_norm parity (instance_norm_op.cc)."""
    c = input.shape[1]
    w = _make_param([c], "float32", param_attr, I.Constant(1.0), "in_s")
    b = _make_param([c], "float32", bias_attr, I.Constant(0.0), "in_b")
    w, b = _fill_affine_pair(w, b, c)
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def conv3d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCDHW", name=None):
    """fluid.layers.conv3d parity (conv3d_op)."""
    in_ch = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size] * 3
    w = _make_param([num_filters, in_ch // groups] + list(ks), "float32",
                    param_attr, I.XavierUniform(), "conv3d_w")
    b = _make_param([num_filters], "float32", bias_attr, I.Constant(0.0),
                    "conv3d_b")
    out = F.conv3d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """fluid.layers.bilinear_tensor_product parity
    (bilinear_tensor_product_op.cc): out_k = x·W_k·yᵀ + b."""
    from .. import ops
    w = _make_param([size, x.shape[-1], y.shape[-1]], "float32", param_attr,
                    I.XavierUniform(), "blt_w")
    b = _make_param([size], "float32", bias_attr, I.Constant(0.0), "blt_b")
    out = ops.bilinear_tensor_product(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """fluid.layers.row_conv parity (row_conv_op.cc): lookahead conv."""
    from .. import ops
    w = _make_param([future_context_size + 1, input.shape[-1]], "float32",
                    param_attr, I.XavierUniform(), "rowconv_w")
    out = ops.row_conv(input, w)
    if act:
        out = getattr(F, act)(out)
    return out


def sequence_conv(input, num_filters, filter_size=3, param_attr=None,
                  bias_attr=None, act=None, name=None):
    """fluid.layers.sequence_conv parity (sequence_conv_op.cc) over the
    masked-dense sequence carrier."""
    from .. import ops
    w = _make_param([filter_size * input.shape[-1], num_filters], "float32",
                    param_attr, I.XavierUniform(), "seqconv_w")
    out = ops.sequence_conv(input, w, context_length=filter_size)
    if bias_attr is not False:
        b = _make_param([num_filters], "float32", bias_attr,
                        I.Constant(0.0), "seqconv_b")
        out = out + b
    if act:
        out = getattr(F, act)(out)
    return out


def nce(input, label, num_total_classes, num_neg_samples=10,
        param_attr=None, bias_attr=None, name=None, sampler="uniform",
        seed=None):
    """fluid.layers.nce parity (nce_op.h, uniform sampler): builds the
    class weight/bias params and returns the per-example NCE loss."""
    from .. import ops
    if sampler != "uniform":
        raise NotImplementedError(
            f"static.nn.nce sampler={sampler!r}: only the uniform sampler "
            f"is built (log_uniform/custom_dist need their own q "
            f"corrections)")
    w = _make_param([num_total_classes, input.shape[-1]], "float32",
                    param_attr, I.XavierUniform(), "nce_w")
    b = _make_param([num_total_classes], "float32", bias_attr,
                    I.Constant(0.0), "nce_b")
    return ops.nce_loss(input, label, w, b,
                        num_neg_samples=num_neg_samples,
                        num_total_classes=num_total_classes,
                        seed=seed)
