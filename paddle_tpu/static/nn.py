"""paddle.static.nn: static-graph layer builders.

Reference parity: python/paddle/fluid/layers/nn.py (the 36K-LoC layers DSL,
SURVEY.md §2.4) — here each builder creates eager Parameters (registered into
the program as persistables by the primitive recorder) and invokes the same
nn.functional ops that dygraph uses, so the static DSL is a thin veneer
rather than a parallel implementation.
"""
from __future__ import annotations

from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import ParamAttr
from ..framework.tensor import Parameter
from ..framework.dtype import convert_dtype


def _make_param(shape, dtype, attr, default_init, name_hint):
    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = attr.initializer or default_init
    value = init(shape, convert_dtype(dtype) or "float32")
    p = Parameter(value, name=attr.name)
    return p


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """fluid.layers.fc parity."""
    from .. import ops
    in_dim = 1
    for d in x.shape[num_flatten_dims:]:
        in_dim *= d
    if len(x.shape) > num_flatten_dims + 1:
        lead = [-1 if (d is None or d < 0) else d
                for d in x.shape[:num_flatten_dims]]
        x = ops.reshape(x, lead + [in_dim])
    w = _make_param([in_dim, size], "float32", weight_attr,
                    I.XavierUniform(), "fc_w")
    b = _make_param([size], "float32", bias_attr, I.Constant(0.0), "fc_b")
    out = F.linear(x, w, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else [filter_size, filter_size]
    w = _make_param([num_filters, in_ch // groups] + list(ks), "float32",
                    param_attr, I.XavierUniform(), "conv_w")
    b = _make_param([num_filters], "float32", bias_attr, I.Constant(0.0),
                    "conv_b")
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups, data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype="float32"):
    w = _make_param(list(size), dtype, param_attr, I.XavierUniform(), "emb_w")
    return F.embedding(input, w, padding_idx=padding_idx)


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout="NCHW", is_test=False, name=None):
    from .. import ops
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    scale = _make_param([c], "float32", param_attr, I.Constant(1.0), "bn_s")
    bias = _make_param([c], "float32", bias_attr, I.Constant(0.0), "bn_b")
    mean = Parameter(jnp.zeros([c], jnp.float32))
    var = Parameter(jnp.ones([c], jnp.float32))
    mean.stop_gradient = True
    var.stop_gradient = True
    out = F.batch_norm(input, mean, var, weight=scale, bias=bias,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


# -- control flow (layers/control_flow.py parity) ----------------------------
from ..ops.control_flow import while_loop, cond, case, switch_case  # noqa: F401,E402
