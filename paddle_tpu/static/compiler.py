"""CompiledProgram / BuildStrategy / ExecutionStrategy.

Reference parity: python/paddle/fluid/compiler.py:88 — with_data_parallel
(:164) builds a C++ ParallelExecutor with a pass pipeline
(build_strategy.cc:58).  TPU-native: "compiling with data parallelism" means
the Executor shards the feed batch over the mesh dp axis and lets GSPMD
replicate the (already whole-program-jitted) computation — the 103-pass IR
pipeline and SSA graph executors are the XLA compiler's job.  The strategy
objects keep their fields for API parity; most are advisory on TPU.
"""
from __future__ import annotations


class BuildStrategy:
    """details/build_strategy.h pybind parity (fields advisory on TPU —
    fusion/memory passes are XLA's)."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0


class ExecutionStrategy:
    """ExecutionStrategy pybind parity."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.use_thread_barrier = False


# every BuildStrategy field accounted for (the strategy-honesty rule of
# fleet/ledger.py applied to build_strategy.cc's pass pipeline): "n/a"
# fields are legitimately subsumed by XLA and may take any value; "raises"
# fields would change numerics/topology and are rejected when set to a
# non-default value instead of silently ignored.
BUILD_LEDGER = {
    "reduce_strategy": ("n/a", "GSPMD chooses reduction placement"),
    "gradient_scale_strategy": ("raises", "custom grad scaling must go "
                                          "through the optimizer/GradScaler"),
    "fuse_all_reduce_ops": ("n/a", "XLA all-reduce combiner"),
    "fuse_elewise_add_act_ops": ("n/a", "XLA elementwise fusion"),
    "fuse_bn_act_ops": ("n/a", "XLA fusion"),
    "enable_inplace": ("n/a", "buffer donation"),
    "memory_optimize": ("n/a", "XLA buffer assignment"),
    "sync_batch_norm": ("engine", "program rewrite: batch_norm_train ops "
                                  "swap to sync_batch_norm_train (global "
                                  "batch stats; explicit pmean under a "
                                  "manual dp axis, identical under GSPMD "
                                  "whole-array semantics) — "
                                  "apply_sync_batch_norm_pass"),
    "num_trainers": ("n/a", "cluster size comes from the launch env"),
    "trainer_id": ("n/a", "rank comes from the launch env"),
}

_BUILD_DEFAULTS = None


def check_build_strategy(bs):
    """Raise for non-default values of 'raises'-classified fields."""
    global _BUILD_DEFAULTS
    if _BUILD_DEFAULTS is None:
        _BUILD_DEFAULTS = vars(BuildStrategy())
    for field, (kind, note) in BUILD_LEDGER.items():
        if kind != "raises":
            continue
        val = getattr(bs, field, None)
        if val is not None and val != _BUILD_DEFAULTS.get(field):
            raise NotImplementedError(
                f"BuildStrategy.{field} is not supported by the TPU "
                f"engine: {note}")
    return True


def apply_sync_batch_norm_pass(program) -> int:
    """The build-strategy sync_batch_norm pass as a Program rewrite
    (reference wiring: framework/details/build_strategy.cc appends
    sync_batch_norm_pass, which swaps batch_norm ops for sync_batch_norm).
    Here each recorded ``batch_norm_train`` op re-points at the
    ``sync_batch_norm_train`` primitive — global batch statistics (an
    explicit dp-axis pmean under shard_map; identical math under GSPMD
    whole-array semantics).  Eval-mode ops are untouched: running stats
    are already replica-identical.  Returns the rewrite count."""
    n = 0
    for block in program.blocks:
        for op in block.ops:
            if op.prim == "batch_norm_train":
                op.prim = "sync_batch_norm_train"
                op.type = "sync_batch_norm"
                n += 1
    if n:
        program._version += 1       # invalidate compiled-replay caches
    return n


class CompiledProgram:
    """compiler.py:88 parity."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        check_build_strategy(self._build_strategy)
        self._exec_strategy = ExecutionStrategy()
        self._data_parallel = False
        self._loss_name = None
        self._maybe_sync_bn()

    def _maybe_sync_bn(self):
        if (getattr(self._build_strategy, "sync_batch_norm", False)
                and self._program is not None
                and hasattr(self._program, "blocks")):
            apply_sync_batch_norm_pass(self._program)

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            check_build_strategy(build_strategy)
            self._build_strategy = build_strategy
            self._maybe_sync_bn()
        if exec_strategy is not None:
            self._exec_strategy = exec_strategy
        return self
