"""Static autodiff: append_backward / gradients.

Reference parity: python/paddle/fluid/backward.py — append_backward (:1288)
walks ops in reverse generating grad-op descs from registered GradOpMakers,
deduping accumulation (:424) and pruning no-grad vars (:529).

TPU-first: backward is derived from the WHOLE recorded forward segment with
jax.grad over its replay — one macro grad op computes every parameter
gradient in a single traced computation (XLA then fuses/schedules it with
forward; re-used forward values are CSE'd, recomputed ones are effectively
rematerialized).  Per-op GradOpMakers are unnecessary because every recorded
primitive is jax-differentiable.  ``checkpoints`` mirrors
_append_backward_ops_with_checkpoints_ (backward.py:701) via jax.checkpoint
over the replay.
"""
from __future__ import annotations

from typing import List, Optional

import jax

from .program import Operator, Variable, default_main_program


def _segment_io(ops, block, param_names, loss_name):
    """External inputs of the op segment: names READ BEFORE any op in the
    segment wrote them (order-aware — a self-aliasing read-then-write op
    like the advancing RNG key or the BN running-stat update consumes its
    own name externally first) and not parameters."""
    produced = set()
    ext = []
    for op in ops:
        for n in op.input_names:
            if n not in produced and n not in param_names and n not in ext:
                ext.append(n)
        produced.update(op.output_names)
    return ext


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None,
                    checkpoints=None):
    """Returns [(param_var, grad_var)] like backward.py:1288."""
    block = loss.block
    program = block.program
    if parameter_list:
        param_names = [p if isinstance(p, str) else p.name
                       for p in parameter_list]
    else:
        param_names = [n for n in program._parameters
                       if block.has_var(n) and block.var(n).trainable]
    no_grad = {n if isinstance(n, str) else n.name
               for n in (no_grad_set or set())}
    param_names = [n for n in param_names if n not in no_grad]
    if not param_names:
        raise ValueError("append_backward: no trainable parameters found")

    fwd_ops = list(block.ops)
    ext_names = _segment_io(fwd_ops, block, set(param_names), loss.name)
    loss_name = loss.name
    grad_fn = make_backward_fn(fwd_ops, param_names, ext_names, loss_name,
                               bool(checkpoints))

    # declare grad vars + the macro op writing them. The attrs carry the
    # full recipe (which forward ops, which params, the loss), so a saved
    # TRAIN program deserializes and rebuilds this fn (io.py macro
    # builders) — the reference's serialized grad-op descs, one op here.
    grad_vars = []
    for n in param_names:
        pv = block.var(n)
        gv = block.create_var(name=n + "@GRAD", shape=pv.shape,
                              dtype=pv.dtype, stop_gradient=True)
        grad_vars.append(gv)
    op = Operator(block, prim="@backward",
                  inputs=param_names + ext_names,
                  outputs=[g.name for g in grad_vars],
                  attrs={"param_names": list(param_names),
                         "ext_names": list(ext_names),
                         "loss_name": loss_name,
                         "checkpoints": bool(checkpoints),
                         "n_fwd_ops": len(fwd_ops)},
                  fn=grad_fn, type_name="backward")
    block.ops.append(op)
    program._version += 1
    return [(block.var(n), g) for n, g in zip(param_names, grad_vars)]


def make_backward_fn(fwd_ops, param_names, ext_names, loss_name,
                     checkpoints=False):
    """The macro grad fn: jax.grad over the forward segment's replay."""
    def grad_fn(*arrs):
        pvals = arrs[:len(param_names)]
        evals = arrs[len(param_names):]
        base_env = dict(zip(ext_names, evals))

        def loss_of(pv):
            env = dict(base_env)
            env.update(zip(param_names, pv))
            for op in fwd_ops:
                ins = [env[n] for n in op.input_names]
                if op.prim == "key_advance":
                    # the gradient replay must see the SAME randomness the
                    # forward pass used: by @backward's execution the env
                    # already holds the post-advance key, so advancing
                    # again here would differentiate a different dropout
                    # mask / negative set than the fetched loss
                    env[op.output_names[0]] = ins[0]
                    continue
                outs = op.run_fn()(*ins)
                for name, val in zip(op.output_names, outs):
                    env[name] = val
            out = env[loss_name]
            return out.sum() if out.ndim else out

        f = loss_of
        if checkpoints:
            f = jax.checkpoint(f)
        grads = jax.grad(f)(tuple(pvals))
        return tuple(grads)

    return grad_fn


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """backward.py:1878 calc_gradient parity (first-order, static)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    loss = targets[0]
    pgs = append_backward(loss, parameter_list=[v.name for v in inputs],
                          no_grad_set=no_grad_set)
    return [g for _, g in pgs]
