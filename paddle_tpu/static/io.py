"""Static-graph persistence: save/load persistables + inference model export.

Reference parity: python/paddle/fluid/io.py — save_persistables (:620),
load_persistables (:994), save_inference_model (:1198),
load_inference_model (:1411), whole-program save/load (:1760,:1832).

Format: programs serialize as pickled op tuples (prim registry names +
attrs) — the primitive registry plays framework.proto's role; macro ops
(@backward/@optimize) are non-serializable and are excluded by inference
pruning, matching the reference where export prunes to the feed/fetch
forward subgraph.
"""
from __future__ import annotations

import os
import pickle
from typing import List

import jax.numpy as jnp
import numpy as np

from .program import Program, Block, Operator, Variable, default_main_program
from .executor import global_scope

_PROG_MAGIC = "paddle_tpu.program.v1"

# NOTE on macro ops: @backward and @optimize close over Python state but
# their attrs carry the full rebuild recipe, so TRAIN programs serialize
# (deserialization reconstructs the closures below). Other fn-bearing ops
# must still be pruned to the inference subgraph first.


_REBUILDABLE_MACROS = ("@backward", "@optimize")


def _program_to_dict(program: Program):
    ops = []
    for op in program.global_block().ops:
        if not op.serializable() and op.prim not in _REBUILDABLE_MACROS:
            raise ValueError(
                f"op {op.type} is a macro op; prune to the inference "
                f"subgraph before serializing (save_inference_model does)")
        ops.append({"prim": op.prim, "inputs": op.input_names,
                    "outputs": op.output_names, "attrs": op.attrs,
                    "type": op.type})
    vars_ = {
        name: {"shape": v.shape, "dtype": np.dtype(v.dtype).name,
               "persistable": v.persistable, "is_data": v.is_data,
               "stop_gradient": v.stop_gradient, "trainable": v.trainable}
        for name, v in program.global_block().vars.items()}
    return {"magic": _PROG_MAGIC, "ops": ops, "vars": vars_,
            "parameters": list(program._parameters),
            "feed_names": program._feed_names,
            "fetch_names": program._fetch_names}


def _program_from_dict(d) -> Program:
    p = Program()
    b = p.global_block()
    for name, meta in d["vars"].items():
        b.create_var(name=name, shape=meta["shape"], dtype=meta["dtype"],
                     persistable=meta["persistable"],
                     stop_gradient=meta["stop_gradient"],
                     is_data=meta["is_data"], trainable=meta["trainable"])
    for o in d["ops"]:
        fn = None
        attrs = o["attrs"]
        if o["prim"] == "@backward":
            # rebuild the macro grad fn from the ops appended so far
            from .backward import make_backward_fn
            fn = make_backward_fn(
                list(b.ops[:attrs["n_fwd_ops"]]), attrs["param_names"],
                attrs["ext_names"], attrs["loss_name"],
                attrs.get("checkpoints", False))
        elif o["prim"] == "@optimize":
            from ..optimizer.optimizer import (rebuild_optimizer,
                                               make_update_fn)
            opt = rebuild_optimizer(attrs["optimizer"], attrs["config"])
            fn = make_update_fn(opt, attrs["param_names"])
        op = Operator(b, prim=o["prim"], inputs=o["inputs"],
                      outputs=o["outputs"], attrs=attrs,
                      type_name=o["type"], fn=fn)
        b.ops.append(op)
    p._parameters = list(d["parameters"])
    p._feed_names = d.get("feed_names", [])
    p._fetch_names = d.get("fetch_names", [])
    return p


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """io.py:238 parity: dump a chosen subset of vars (by list or
    predicate) from the scope."""
    program = main_program or default_main_program()
    scope = global_scope()
    os.makedirs(dirname, exist_ok=True)
    if vars is None:
        vars = [v for v in program.list_vars()
                if predicate is None or predicate(v)]
    blob = {}
    for v in vars:
        name = v.name if hasattr(v, "name") else str(v)
        val = scope.find_var(name)
        if val is not None:
            blob[name] = np.asarray(val)
    path = os.path.join(dirname, filename or "__vars__")
    with open(path, "wb") as f:
        pickle.dump(blob, f, protocol=4)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None):
    """Restore only the requested subset (vars list / predicate), like the
    reference load_vars — a full-blob restore would clobber vars the
    caller changed since saving."""
    scope = global_scope()
    path = os.path.join(dirname, filename or "__vars__")
    with open(path, "rb") as f:
        blob = pickle.load(f)
    wanted = None
    if vars is not None:
        wanted = {v.name if hasattr(v, "name") else str(v) for v in vars}
    elif predicate is not None:
        program = main_program or default_main_program()
        wanted = {v.name for v in program.list_vars() if predicate(v)}
    for name, val in blob.items():
        if wanted is None or name in wanted:
            scope.set_var(name, jnp.asarray(val))


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    """io.py:620 parity: dump every persistable var's scope value."""
    save_vars(executor, dirname, main_program,
              predicate=lambda v: v.persistable,
              filename=filename or "__persistables__")


save_params = save_persistables


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None):
    load_vars(executor, dirname, main_program,
              filename=filename or "__persistables__")


load_params = load_persistables


def save_inference_model(dirname, feeded_var_names: List[str], target_vars,
                         executor=None, main_program=None,
                         model_filename=None, params_filename=None):
    """io.py:1198 parity: prune to feed→fetch subgraph, save program+params."""
    program = main_program or default_main_program()
    target_vars = target_vars if isinstance(target_vars, (list, tuple)) \
        else [target_vars]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in target_vars]
    pruned = program._prune(feeded_var_names, fetch_names)
    os.makedirs(dirname, exist_ok=True)
    with open(os.path.join(dirname, model_filename or "__model__"),
              "wb") as f:
        pickle.dump(_program_to_dict(pruned), f, protocol=4)
    scope = global_scope()
    blob = {}
    for v in pruned.list_vars():
        if v.persistable:
            val = scope.find_var(v.name)
            if val is not None:
                blob[v.name] = np.asarray(val)
    with open(os.path.join(dirname, params_filename or "__params__"),
              "wb") as f:
        pickle.dump(blob, f, protocol=4)
    return fetch_names


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None):
    """io.py:1411 parity → (program, feed_names, fetch_vars)."""
    with open(os.path.join(dirname, model_filename or "__model__"),
              "rb") as f:
        d = pickle.load(f)
    assert d.get("magic") == _PROG_MAGIC, "not a paddle_tpu inference model"
    program = _program_from_dict(d)
    with open(os.path.join(dirname, params_filename or "__params__"),
              "rb") as f:
        blob = pickle.load(f)
    scope = global_scope()
    for name, val in blob.items():
        scope.set_var(name, jnp.asarray(val))
    fetch_vars = [program.global_block().var(n) for n in d["fetch_names"]]
    return program, d["feed_names"], fetch_vars
