"""Stats/monitor registry + scalar logging (observability).

Reference parity: paddle/fluid/platform/monitor.h — StatRegistry<int64_t>
with the STAT_INT_ADD/SUB/SET macro family (gauges like
STAT_gpu0_mem_size) — plus a minimal VisualDL-style LogWriter for scalar
curves (the reference ecosystem's VisualDL writes protobuf event files;
here scalars land in JSONL, one file per run, trivially parseable and
plottable — no daemon, no proto dependency).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    """STAT_INT_ADD parity."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_sub(name: str, value: int = 1) -> int:
    """STAT_INT_SUB parity."""
    return stat_add(name, -int(value))


def stat_set(name: str, value: int) -> int:
    with _lock:
        _stats[name] = int(value)
        return _stats[name]


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def all_stats() -> Dict[str, int]:
    """StatRegistry::publish parity: snapshot of every registered stat."""
    with _lock:
        return dict(_stats)


def reset_stats(prefix: str = "") -> None:
    """Zero the registry (tests / run boundaries); with a prefix, only
    matching gauges are dropped."""
    with _lock:
        if not prefix:
            _stats.clear()
        else:
            for k in [k for k in _stats if k.startswith(prefix)]:
                del _stats[k]


class LogWriter:
    """Minimal VisualDL LogWriter: scalars/metadata to JSONL.

    with LogWriter(logdir="runs/exp1") as w:
        w.add_scalar("train/loss", loss_value, step)
    """

    def __init__(self, logdir: str, filename_suffix: str = ""):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.{int(time.time())}.{os.getpid()}" \
                f"{filename_suffix}.jsonl"
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "a", buffering=1)
        self._lock = threading.Lock()

    def add_scalar(self, tag: str, value, step: int = 0,
                   walltime: float = None):
        rec = {"tag": tag, "value": float(value), "step": int(step),
               "wall": walltime if walltime is not None else time.time()}
        with self._lock:
            self._f.write(json.dumps(rec) + "\n")

    def add_hparams(self, hparams: dict, metrics: dict = None):
        rec = {"hparams": {k: repr(v) for k, v in hparams.items()},
               "metrics": {k: float(v) for k, v in (metrics or {}).items()}}
        with self._lock:
            self._f.write(json.dumps(rec) + "\n")

    def add_event(self, tag: str, event: dict, walltime: float = None):
        """Structured (non-scalar) JSONL event — the recompile ledger and
        other telemetry ride this channel; read back with read_events."""
        rec = {"tag": tag, "event": event,
               "wall": walltime if walltime is not None else time.time()}
        with self._lock:
            self._f.write(json.dumps(rec, default=repr) + "\n")

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    @staticmethod
    def read_scalars(logdir: str):
        """Load all scalar records from a log dir -> {tag: [(step, value)]}."""
        out = {}
        for fn in sorted(os.listdir(logdir)):
            if not fn.endswith(".jsonl"):
                continue
            with open(os.path.join(logdir, fn)) as f:
                for line in f:
                    rec = json.loads(line)
                    if "tag" in rec and "value" in rec:
                        out.setdefault(rec["tag"], []).append(
                            (rec["step"], rec["value"]))
        return out

    @staticmethod
    def read_events(logdir: str):
        """Load structured events (add_event) -> {tag: [event dicts]}."""
        out = {}
        for fn in sorted(os.listdir(logdir)):
            if not fn.endswith(".jsonl"):
                continue
            with open(os.path.join(logdir, fn)) as f:
                for line in f:
                    rec = json.loads(line)
                    if "tag" in rec and "event" in rec:
                        out.setdefault(rec["tag"], []).append(rec["event"])
        return out
