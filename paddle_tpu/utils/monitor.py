"""Stats/monitor registry + scalar logging (observability).

Reference parity: paddle/fluid/platform/monitor.h — StatRegistry<int64_t>
with the STAT_INT_ADD/SUB/SET macro family (gauges like
STAT_gpu0_mem_size) — plus a minimal VisualDL-style LogWriter for scalar
curves (the reference ecosystem's VisualDL writes protobuf event files;
here scalars land in JSONL, one file per run, trivially parseable and
plottable — no daemon, no proto dependency).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict

_lock = threading.Lock()
_stats: Dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> int:
    """STAT_INT_ADD parity."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)
        return _stats[name]


def stat_sub(name: str, value: int = 1) -> int:
    """STAT_INT_SUB parity."""
    return stat_add(name, -int(value))


def stat_set(name: str, value: int) -> int:
    with _lock:
        _stats[name] = int(value)
        return _stats[name]


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def all_stats() -> Dict[str, int]:
    """StatRegistry::publish parity: snapshot of every registered stat."""
    with _lock:
        return dict(_stats)


def reset_stats(prefix: str = "") -> None:
    """Zero the registry (tests / run boundaries); with a prefix, only
    matching gauges are dropped."""
    with _lock:
        if not prefix:
            _stats.clear()
        else:
            for k in [k for k in _stats if k.startswith(prefix)]:
                del _stats[k]


class LogWriter:
    """Minimal VisualDL LogWriter: scalars/metadata to JSONL.

    with LogWriter(logdir="runs/exp1") as w:
        w.add_scalar("train/loss", loss_value, step)

    Sinks are size-capped (FLAGS_log_writer_max_mb, default 64 MiB):
    past the cap the file rotates — ``f.jsonl`` → ``f.jsonl.1`` →
    ``f.jsonl.2``, two rollovers kept — so a long-running serve process
    streaming ledger/lint/audit/trace events cannot grow any file
    without bound.  ``read_scalars``/``read_events`` read rotated files
    too (oldest first), so nothing recent is lost to a rollover.
    """

    _ROLLOVERS = 2

    def __init__(self, logdir: str, filename_suffix: str = ""):
        self.logdir = logdir
        os.makedirs(logdir, exist_ok=True)
        fname = f"events.{int(time.time())}.{os.getpid()}" \
                f"{filename_suffix}.jsonl"
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "a", buffering=1)
        self._bytes = self._f.tell()
        self._lock = threading.Lock()

    def _cap_bytes(self):
        try:
            from ..framework import flags as _flags
            return int(float(_flags.flag("log_writer_max_mb")) * 1048576)
        except Exception:
            return 0

    def _rotate_locked(self):
        """Shift f.jsonl.1 -> f.jsonl.2, f.jsonl -> f.jsonl.1, reopen
        fresh; must be called with _lock held."""
        self._f.close()
        for i in range(self._ROLLOVERS, 1, -1):
            src = f"{self._path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self._path}.{i}")
        os.replace(self._path, f"{self._path}.1")
        self._f = open(self._path, "a", buffering=1)
        self._bytes = 0

    def _write(self, rec: dict, default=None):
        line = json.dumps(rec, default=default) + "\n"
        cap = self._cap_bytes()
        with self._lock:
            if cap and self._bytes + len(line) > cap and self._bytes:
                self._rotate_locked()
            self._f.write(line)
            self._bytes += len(line)

    def add_scalar(self, tag: str, value, step: int = 0,
                   walltime: float = None):
        self._write({"tag": tag, "value": float(value), "step": int(step),
                     "wall": walltime if walltime is not None
                     else time.time()})

    def add_hparams(self, hparams: dict, metrics: dict = None):
        self._write({"hparams": {k: repr(v) for k, v in hparams.items()},
                     "metrics": {k: float(v)
                                 for k, v in (metrics or {}).items()}})

    def add_event(self, tag: str, event: dict, walltime: float = None):
        """Structured (non-scalar) JSONL event — the recompile ledger and
        other telemetry ride this channel; read back with read_events."""
        self._write({"tag": tag, "event": event,
                     "wall": walltime if walltime is not None
                     else time.time()}, default=repr)

    def flush(self):
        self._f.flush()

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()

    @staticmethod
    def _log_files(logdir: str):
        """Sink files oldest-first, rotated generations (.jsonl.2,
        .jsonl.1) before each live .jsonl so readers see event order."""

        def key(fn):
            if fn.endswith(".jsonl"):
                return (fn, 0)
            base, gen = fn.rsplit(".", 1)
            return (base, -int(gen))

        names = [fn for fn in os.listdir(logdir)
                 if fn.endswith(".jsonl")
                 or (fn.rsplit(".", 1)[-1].isdigit()
                     and ".jsonl." in fn)]
        return [os.path.join(logdir, fn) for fn in sorted(names, key=key)]

    @staticmethod
    def read_scalars(logdir: str):
        """Load all scalar records from a log dir -> {tag: [(step, value)]}."""
        out = {}
        for path in LogWriter._log_files(logdir):
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    if "tag" in rec and "value" in rec:
                        out.setdefault(rec["tag"], []).append(
                            (rec["step"], rec["value"]))
        return out

    @staticmethod
    def read_events(logdir: str):
        """Load structured events (add_event) -> {tag: [event dicts]}."""
        out = {}
        for path in LogWriter._log_files(logdir):
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    if "tag" in rec and "event" in rec:
                        out.setdefault(rec["tag"], []).append(rec["event"])
        return out
