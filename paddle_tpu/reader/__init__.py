"""paddle.reader parity: reader-creator combinators.

Reference: python/paddle/reader/decorator.py — a *reader creator* is a
zero-arg callable returning an iterable of samples; these combinators
compose them.  Pure host-side Python (identical role here); the
process-pool variants (xmap_readers, multiprocess_reader) use threads —
the heavy-parallel seat in this framework is io.DataLoader's worker
processes + shm ring, so the combinators stay simple and deadlock-free.
"""
from __future__ import annotations

import itertools
import queue as queue_mod
import random as random_mod
import threading

__all__ = ["cache", "map_readers", "buffered", "compose", "chain",
           "shuffle", "firstn", "xmap_readers", "multiprocess_reader"]


def cache(reader):
    """decorator.py:51 — materialize once, replay from memory."""
    all_data = tuple(reader())

    def __impl__():
        for item in all_data:
            yield item

    return __impl__


def map_readers(func, *readers):
    """decorator.py:91 — zip readers, map func over the sample tuples."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size):
    """decorator.py:133 — buffered shuffle window."""

    def data_reader():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random_mod.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            random_mod.shuffle(buf)
            for b in buf:
                yield b

    return data_reader


def chain(*readers):
    """decorator.py:182 — concatenate readers in order."""

    def reader():
        rs = [r() for r in readers]
        for e in itertools.chain(*rs):
            yield e

    return reader


class ComposeNotAligned(ValueError):
    pass


def compose(*readers, **kwargs):
    """decorator.py:247 — sample-wise tuple composition
    ((a,), (b, c)) → (a, b, c); check_alignment raises on ragged ends."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        if isinstance(x, tuple):
            return x
        return (x,)

    def reader():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum(list(map(make_tuple, outputs)), ())
        else:
            for outputs in itertools.zip_longest(*rs):
                if any(o is None for o in outputs):
                    raise ComposeNotAligned(
                        "outputs of readers are not aligned")
                yield sum(list(map(make_tuple, outputs)), ())

    return reader


def buffered(reader, size):
    """decorator.py:307 — a producer thread keeps ``size`` samples ahead.
    Producer exceptions RE-RAISE in the consumer (a swallowed error would
    read as a clean, truncated dataset)."""

    end = object()

    def read_worker(r, q):
        try:
            for d in r:
                q.put((None, d))
        except BaseException as e:   # noqa: BLE001 — re-raised by consumer
            q.put((e, None))
        else:
            q.put((None, end))

    def data_reader():
        r = reader()
        q = queue_mod.Queue(maxsize=size)
        t = threading.Thread(target=read_worker, args=(r, q), daemon=True)
        t.start()
        while True:
            err, e = q.get()
            if err is not None:
                raise err
            if e is end:
                return
            yield e

    return data_reader


def firstn(reader, n):
    """decorator.py:366 — first n samples."""

    def firstn_reader():
        for i, item in enumerate(reader()):
            if i == n:
                break
            yield item

    return firstn_reader


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """decorator.py:411 — map ``mapper`` over the reader with
    ``process_num`` worker THREADS and a ``buffer_size`` queue.  The
    reference uses threads here too; ``order=True`` preserves sample
    order."""

    end = object()

    def ordered_reader():
        # order=True degenerates to a buffered sequential map: a thread
        # pool reordering via sequence numbers buys nothing for the
        # GIL-bound mappers this API serves
        def r():
            for sample in reader():
                yield mapper(sample)
        return buffered(r, buffer_size)()

    def data_reader():
        if order:
            yield from ordered_reader()
            return
        in_q = queue_mod.Queue(buffer_size)
        out_q = queue_mod.Queue(buffer_size)

        def feed():
            try:
                for s in reader():
                    in_q.put(s)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            # NB: end marker posts from finally — a `return` inside try
            # would skip an `else` clause and strand the consumer
            err = None
            try:
                while True:
                    s = in_q.get()
                    if s is end:
                        break
                    out_q.put((None, mapper(s)))
            except BaseException as e:   # noqa: BLE001 — re-raised below
                err = e
            finally:
                out_q.put((err, end))

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        while finished < process_num:
            err, s = out_q.get()
            if err is not None:
                raise err
            if s is end:
                finished += 1
                continue
            yield s

    return data_reader


def multiprocess_reader(readers, use_pipe=True, queue_size=1000):
    """decorator.py:504 — interleave several readers concurrently.  One
    thread per reader feeding a shared queue (the pickle-free bulk
    transport seat belongs to io.DataLoader's shm ring; this combinator
    keeps the reference's interleaving contract)."""
    if len(readers) < 1:
        raise ValueError("multiprocess_reader must own at least one reader")

    end = object()

    def data_reader():
        q = queue_mod.Queue(queue_size)

        def work(r):
            try:
                for s in r():
                    q.put((None, s))
            except BaseException as e:   # noqa: BLE001 — re-raised below
                q.put((e, None))
            else:
                q.put((None, end))

        for r in readers:
            threading.Thread(target=work, args=(r,), daemon=True).start()
        finished = 0
        while finished < len(readers):
            err, s = q.get()
            if err is not None:
                raise err
            if s is end:
                finished += 1
                continue
            yield s

    return data_reader
