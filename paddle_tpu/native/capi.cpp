// C inference ABI over the paddle_tpu predictor.
//
// Reference parity: paddle/fluid/inference/capi/ (pd_config.cc/pd_predictor.cc)
// — a plain-C surface so C/Go/R programs can load a saved model and run it.
// The TPU build's predictor executes through PJRT from Python, so this shim
// embeds CPython and marshals through inference/capi_bridge.py; the caller
// links ONLY this C ABI (no Python headers needed on the consumer side —
// see tests/test_capi.py's demo program).
//
// Environment contract: PYTHONPATH must reach paddle_tpu and its deps
// (the embedding inherits the process env, like any CPython).
//
// Build (native/__init__.py build_capi):
//   g++ -O2 -shared -fPIC capi.cpp $(python3-config --includes) \
//       $(python3-config --ldflags --embed) -o libpt_capi.so

#include <Python.h>

#include <cstring>
#include <string>

namespace {

std::string g_err;
PyObject* g_bridge = nullptr;

void set_err_from_python() {
    PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
    PyErr_Fetch(&type, &value, &tb);
    if (value != nullptr) {
        PyObject* s = PyObject_Str(value);
        if (s != nullptr) {
            const char* c = PyUnicode_AsUTF8(s);
            g_err = c ? c : "unknown python error";
            Py_DECREF(s);
        }
    } else {
        g_err = "unknown python error";
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
}

bool ensure_init() {
    if (g_bridge != nullptr) return true;
    if (!Py_IsInitialized()) {
        Py_InitializeEx(0);
        // release the GIL so pd_* entry points can take it from any thread
        PyEval_SaveThread();
    }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* mod = PyImport_ImportModule("paddle_tpu.inference.capi_bridge");
    if (mod == nullptr) {
        set_err_from_python();
        PyGILState_Release(g);
        return false;
    }
    g_bridge = mod;  // keep the reference for process lifetime
    PyGILState_Release(g);
    return true;
}

}  // namespace

extern "C" {

const char* pd_last_error() { return g_err.c_str(); }

// Load a saved model (save_inference_model dir or jit.save prefix).
// Returns an opaque handle, or null (see pd_last_error()).
void* pd_predictor_create(const char* model_path) {
    if (!ensure_init()) return nullptr;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* pred = PyObject_CallMethod(g_bridge, "create", "s", model_path);
    if (pred == nullptr) set_err_from_python();
    PyGILState_Release(g);
    return pred;
}

// One float32 input (shape[ndim]) -> first float32 output, copied into
// out. Returns the TOTAL output element count (size discovery,
// snprintf-style; may exceed out_cap — writes are clamped to out_cap, so
// call with out_cap=0 to learn the size, then again to fill), or -1 on
// error (see pd_last_error()).
long long pd_predictor_run_f32(void* handle, const float* in,
                               const long long* shape, int ndim,
                               float* out, long long out_cap) {
    if (handle == nullptr) { g_err = "null predictor"; return -1; }
    long long n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* data = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(in), n * sizeof(float));
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
        PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* res = PyObject_CallMethod(g_bridge, "run_f32", "OOO",
                                        static_cast<PyObject*>(handle),
                                        data, shp);
    Py_DECREF(data);
    Py_DECREF(shp);
    long long count = -1;
    if (res == nullptr) {
        set_err_from_python();
    } else {
        PyObject* obytes = PyTuple_GetItem(res, 0);   // borrowed
        char* buf = nullptr;
        Py_ssize_t blen = 0;
        if (PyBytes_AsStringAndSize(obytes, &buf, &blen) == 0) {
            count = blen / static_cast<long long>(sizeof(float));
            long long ncopy = count < out_cap ? count : out_cap;
            if (out != nullptr && ncopy > 0)
                std::memcpy(out, buf, ncopy * sizeof(float));
        } else {
            set_err_from_python();
        }
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return count;
}

void pd_predictor_destroy(void* handle) {
    if (handle == nullptr) return;
    PyGILState_STATE g = PyGILState_Ensure();
    Py_DECREF(static_cast<PyObject*>(handle));
    PyGILState_Release(g);
}

// -- multi-input serving (capi PD_SetZeroCopyInput/GetZeroCopyOutput style) --

namespace {

int set_input_impl(void* handle, const char* name, const void* data,
                   long long nbytes, const long long* shape, int ndim,
                   const char* dtype) {
    if (handle == nullptr) { g_err = "null predictor"; return -1; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* bytes = PyBytes_FromStringAndSize(
        static_cast<const char*>(data), nbytes);
    PyObject* shp = PyTuple_New(ndim);
    for (int i = 0; i < ndim; ++i)
        PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
    PyObject* res = PyObject_CallMethod(
        g_bridge, "set_input", "OsOOs", static_cast<PyObject*>(handle),
        name, bytes, shp, dtype);
    Py_DECREF(bytes);
    Py_DECREF(shp);
    int rc = -1;
    if (res == nullptr) set_err_from_python(); else { rc = 0; Py_DECREF(res); }
    PyGILState_Release(g);
    return rc;
}

}  // namespace

extern "C" int pd_predictor_set_input_f32(void* h, const char* name,
                                          const float* data,
                                          const long long* shape, int ndim) {
    long long n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    return set_input_impl(h, name, data, n * sizeof(float), shape, ndim,
                          "float32");
}

extern "C" int pd_predictor_set_input_i64(void* h, const char* name,
                                          const long long* data,
                                          const long long* shape, int ndim) {
    long long n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    return set_input_impl(h, name, data, n * sizeof(long long), shape, ndim,
                          "int64");
}

// Run on staged inputs; returns the output count or -1.
extern "C" int pd_predictor_run2(void* handle) {
    if (handle == nullptr) { g_err = "null predictor"; return -1; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = PyObject_CallMethod(g_bridge, "run_staged", "O",
                                        static_cast<PyObject*>(handle));
    int rc = -1;
    if (res == nullptr) {
        set_err_from_python();
    } else {
        rc = static_cast<int>(PyLong_AsLong(res));
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return rc;
}

// Copy output #idx (float32) into out; returns element count (may exceed
// out_cap — call again with a larger buffer) or -1.
extern "C" long long pd_predictor_get_output_f32(void* handle, int idx,
                                                 float* out,
                                                 long long out_cap) {
    if (handle == nullptr) { g_err = "null predictor"; return -1; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = PyObject_CallMethod(g_bridge, "get_output_f32", "Oi",
                                        static_cast<PyObject*>(handle), idx);
    long long count = -1;
    if (res == nullptr) {
        set_err_from_python();
    } else {
        char* buf = nullptr;
        Py_ssize_t blen = 0;
        if (PyBytes_AsStringAndSize(PyTuple_GetItem(res, 0), &buf,
                                    &blen) == 0) {
            count = blen / static_cast<long long>(sizeof(float));
            long long ncopy = count < out_cap ? count : out_cap;
            if (out != nullptr && ncopy > 0)
                std::memcpy(out, buf, ncopy * sizeof(float));
        } else {
            set_err_from_python();
        }
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return count;
}

// "in1,in2|out1,out2" into buf; returns needed length or -1.
extern "C" long long pd_predictor_io_names(void* handle, char* buf,
                                           long long cap) {
    if (handle == nullptr) { g_err = "null predictor"; return -1; }
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* res = PyObject_CallMethod(g_bridge, "io_names", "O",
                                        static_cast<PyObject*>(handle));
    long long need = -1;
    if (res == nullptr) {
        set_err_from_python();
    } else {
        const char* s = PyUnicode_AsUTF8(res);
        if (s != nullptr) {
            need = static_cast<long long>(strlen(s)) + 1;
            if (buf != nullptr && cap > 0) {
                long long ncopy = need < cap ? need : cap;
                std::memcpy(buf, s, ncopy);
                buf[ncopy - 1] = '\0';
            }
        } else {
            set_err_from_python();
        }
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return need;
}

// -- Python-free TRAINING entry (train/demo/demo_trainer.cc parity) ---------

// Load a train program saved by paddle.static.save: model_prefix.pdmodel +
// persistables. feeds_csv names the feed variables in call order (e.g.
// "img,label"); fetch names the loss to return from each step.
void* pd_trainer_create(const char* model_prefix, const char* feeds_csv,
                        const char* fetch) {
    if (!ensure_init()) return nullptr;
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* t = PyObject_CallMethod(g_bridge, "train_create", "sss",
                                      model_prefix, feeds_csv, fetch);
    if (t == nullptr) set_err_from_python();
    PyGILState_Release(g);
    return t;
}

// One train step: float32 features + int64 labels in, fetched loss out.
// Returns 0, or -1 (see pd_last_error()).
int pd_trainer_step_f32(void* handle, const float* x,
                        const long long* x_shape, int x_ndim,
                        const long long* label, const long long* l_shape,
                        int l_ndim, float* loss_out) {
    if (handle == nullptr) { g_err = "null trainer"; return -1; }
    long long nx = 1, nl = 1;
    for (int i = 0; i < x_ndim; ++i) nx *= x_shape[i];
    for (int i = 0; i < l_ndim; ++i) nl *= l_shape[i];
    PyGILState_STATE g = PyGILState_Ensure();
    PyObject* xb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(x), nx * sizeof(float));
    PyObject* lb = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(label), nl * sizeof(long long));
    PyObject* xs = PyTuple_New(x_ndim);
    for (int i = 0; i < x_ndim; ++i)
        PyTuple_SET_ITEM(xs, i, PyLong_FromLongLong(x_shape[i]));
    PyObject* ls = PyTuple_New(l_ndim);
    for (int i = 0; i < l_ndim; ++i)
        PyTuple_SET_ITEM(ls, i, PyLong_FromLongLong(l_shape[i]));
    PyObject* res = PyObject_CallMethod(
        g_bridge, "train_step", "OOOOO", static_cast<PyObject*>(handle),
        xb, xs, lb, ls);
    Py_DECREF(xb);
    Py_DECREF(lb);
    Py_DECREF(xs);
    Py_DECREF(ls);
    int rc = -1;
    if (res == nullptr) {
        set_err_from_python();
    } else {
        double v = PyFloat_AsDouble(res);
        if (PyErr_Occurred()) {
            set_err_from_python();
        } else {
            if (loss_out != nullptr) *loss_out = static_cast<float>(v);
            rc = 0;
        }
        Py_DECREF(res);
    }
    PyGILState_Release(g);
    return rc;
}

void pd_trainer_destroy(void* handle) { pd_predictor_destroy(handle); }

}  // extern "C"
