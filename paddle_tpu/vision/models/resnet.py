"""ResNet family.

Reference parity: python/paddle/vision/models/resnet.py:151 (ResNet with
BasicBlock/BottleneckBlock, resnet18..152 constructors). Benchmark workload 2
of BASELINE.json (ResNet-50 dygraph).

TPU-first layout note: the public default stays NCHW (paddle-native), but the
whole tower accepts ``data_format="NHWC"`` — on TPU the MXU consumes
channels-minor (NHWC) conv operands directly, while NCHW forces XLA to insert
transposes around every conv in both fwd and bwd. Converting once at the model
boundary and running channels-last end-to-end is the idiomatic move (the
reference's cudnn layout search, conv_cudnn_helper.h, solved the same problem
per-op on GPU).
"""
from __future__ import annotations

from functools import partial

from ... import nn


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or partial(nn.BatchNorm2D,
                                           data_format=data_format)
        self.conv1 = nn.Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                               bias_attr=False, data_format=data_format)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None,
                 groups=1, base_width=64, dilation=1, norm_layer=None,
                 data_format="NCHW"):
        super().__init__()
        norm_layer = norm_layer or partial(nn.BatchNorm2D,
                                           data_format=data_format)
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=data_format)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups,
                               dilation=dilation, bias_attr=False,
                               data_format=data_format)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=data_format)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1, data_format="NCHW"):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.groups = groups
        self.base_width = width
        self.data_format = data_format
        self._norm_layer = partial(nn.BatchNorm2D, data_format=data_format)
        self.inplanes = 64
        self.dilation = 1

        self.conv1 = nn.Conv2D(3, self.inplanes, 7, stride=2, padding=3,
                               bias_attr=False, data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _stem(self, x):
        """7×7/s2 stem + maxpool.  With the fused-conv gate on (NHWC,
        training), the input reorganizes space-to-depth (C_in 3 → 12,
        lane utilization ~4×) and the equivalent 4×4/s1 conv+BN+ReLU runs
        through the Pallas pipeline — fed directly, so XLA's im2col can't
        undo the reorg the way it did the rejected r3 s2d-at-XLA attempt.
        Parameters stay on conv1/bn1 (state-dict compatible); off-path is
        one branch."""
        from ...nn import functional as NF
        if (self.data_format == "NHWC" and self.training
                and self.conv1._kernel_size == (7, 7)
                and NF.conv_bn_fusable(x, self.conv1.weight, 2, 3, 1, 1,
                                       "NHWC", s2d=True)):
            x = NF.conv_bn_act(
                x, self.conv1.weight, self.bn1.weight, self.bn1.bias,
                self.bn1._mean, self.bn1._variance,
                momentum=self.bn1._momentum, epsilon=self.bn1._epsilon,
                stride=2, padding=3, data_format="NHWC", act="relu",
                training=True, s2d=True)
        else:
            x = self.relu(self.bn1(self.conv1(x)))
        return self.maxpool(x)

    def _make_layer(self, block, planes, blocks, stride=1):
        norm_layer = self._norm_layer
        df = self.data_format
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                norm_layer(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample,
                        self.groups, self.base_width, norm_layer=norm_layer,
                        data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, groups=self.groups,
                                base_width=self.base_width,
                                norm_layer=norm_layer, data_format=df))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self._stem(x)
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            from ...ops import manipulation as M
            x = M.flatten(x, 1)
            x = self.fc(x)
        return x


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)
