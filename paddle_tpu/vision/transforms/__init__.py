"""paddle.vision.transforms parity (numpy host-side preprocessing).

Reference: python/paddle/vision/transforms/ — Compose + functional image ops.
Host-side numpy keeps the TPU input pipeline simple; heavy augmentation
belongs in the DataLoader workers.
"""
from __future__ import annotations

import numbers

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    """HWC uint8 [0,255] -> CHW float32 [0,1]."""

    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[:, :, None]
        if img.dtype == np.uint8:
            img = img.astype("float32") / 255.0
        else:
            img = img.astype("float32")
        if self.data_format == "CHW":
            img = np.transpose(img, (2, 0, 1))
        return img


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = np.asarray(mean, dtype="float32")
        self.std = np.asarray(std, dtype="float32")
        self.data_format = data_format

    def _apply_image(self, img):
        img = np.asarray(img, dtype="float32")
        if self.data_format == "CHW":
            m = self.mean.reshape(-1, 1, 1)[: img.shape[0]]
            s = self.std.reshape(-1, 1, 1)[: img.shape[0]]
        else:
            m = self.mean[: img.shape[-1]]
            s = self.std[: img.shape[-1]]
        return (img - m) / s


class Resize(BaseTransform):
    """Nearest/bilinear resize via numpy (no PIL dependency)."""

    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and \
            img.shape[0] < img.shape[-1]
        h_axis = 1 if chw else 0
        oh, ow = self.size
        ih, iw = img.shape[h_axis], img.shape[h_axis + 1]
        ys = np.clip((np.arange(oh) + 0.5) * ih / oh - 0.5, 0, ih - 1)
        xs = np.clip((np.arange(ow) + 0.5) * iw / ow - 0.5, 0, iw - 1)
        if self.interpolation == "nearest":
            yi = np.round(ys).astype(int)
            xi = np.round(xs).astype(int)
            return (img[:, yi][:, :, xi] if chw else img[yi][:, xi])
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, ih - 1)
        x1 = np.minimum(x0 + 1, iw - 1)
        wy = (ys - y0)[:, None]
        wx = (xs - x0)[None, :]
        def gather(a, yi, xi):
            return a[:, yi][:, :, xi] if chw else a[yi][:, xi]
        if chw:
            wy, wx = wy[None], wx[None]
        elif img.ndim == 3:
            wy, wx = wy[..., None], wx[..., None]
        out = (gather(img, y0, x0) * (1 - wy) * (1 - wx)
               + gather(img, y1, x0) * wy * (1 - wx)
               + gather(img, y0, x1) * (1 - wy) * wx
               + gather(img, y1, x1) * wy * wx)
        return out.astype(img.dtype if img.dtype != np.uint8 else "float32")


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(img[..., ::-1] if img.ndim == 3
                                        and img.shape[0] in (1, 3)
                                        else img[:, ::-1])
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and \
            img.shape[0] < img.shape[-1]
        if self.padding:
            pad = [(0, 0)] * img.ndim
            ax = 1 if chw else 0
            pad[ax] = pad[ax + 1] = (self.padding, self.padding)
            img = np.pad(img, pad)
        h_axis = 1 if chw else 0
        ih, iw = img.shape[h_axis], img.shape[h_axis + 1]
        th, tw = self.size
        i = np.random.randint(0, ih - th + 1)
        j = np.random.randint(0, iw - tw + 1)
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def _apply_image(self, img):
        img = np.asarray(img)
        chw = img.ndim == 3 and img.shape[0] in (1, 3) and \
            img.shape[0] < img.shape[-1]
        h_axis = 1 if chw else 0
        ih, iw = img.shape[h_axis], img.shape[h_axis + 1]
        th, tw = self.size
        i, j = (ih - th) // 2, (iw - tw) // 2
        return img[:, i:i + th, j:j + tw] if chw else img[i:i + th, j:j + tw]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def _apply_image(self, img):
        return np.transpose(np.asarray(img), self.order)


def _hwc_view(img):
    """(array, chw_flag): normalize access to HWC coordinates."""
    img = np.asarray(img)
    chw = img.ndim == 3 and img.shape[0] in (1, 3) and \
        img.shape[0] < img.shape[-1]
    return img, chw


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5):
        self.prob = prob

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        if np.random.rand() < self.prob:
            ax = 1 if chw else 0
            return np.ascontiguousarray(np.flip(img, axis=ax))
        return img


class Pad(BaseTransform):
    """transforms.Pad parity: constant/edge/reflect padding of the
    spatial dims; padding int, (pad_x, pad_y) or (l, t, r, b)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, numbers.Number):
            padding = (padding,) * 4
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        elif len(padding) != 4:
            raise ValueError(
                "padding must be an int, a 2-tuple (pad_x, pad_y) or a "
                f"4-tuple (l, t, r, b); got {padding!r}")
        self.padding = tuple(int(p) for p in padding)   # l, t, r, b
        self.fill = fill
        if padding_mode not in ("constant", "edge", "reflect",
                                "symmetric"):
            raise ValueError(f"unknown padding_mode {padding_mode!r}")
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        l, t, r, b = self.padding
        pad = [(0, 0)] * img.ndim
        ax = 1 if chw else 0
        pad[ax] = (t, b)
        pad[ax + 1] = (l, r)
        if self.padding_mode == "constant":
            return np.pad(img, pad, constant_values=self.fill)
        return np.pad(img, pad, mode=self.padding_mode)


class Grayscale(BaseTransform):
    """ITU-R 601-2 luma (the reference's to_grayscale)."""

    def __init__(self, num_output_channels=1):
        self.num_output_channels = int(num_output_channels)

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        w = np.asarray([0.299, 0.587, 0.114], "float32")
        if chw:
            g = np.tensordot(w, img.astype("float32"), axes=([0], [0]))
            g = g[None]
            reps = (self.num_output_channels, 1, 1)
        else:
            g = img.astype("float32") @ w
            g = g[..., None]
            reps = (1, 1, self.num_output_channels)
        out = np.tile(g, reps)
        return out.astype(img.dtype) if img.dtype == np.uint8 else out


class BrightnessTransform(BaseTransform):
    """value v: factor drawn from [max(0, 1-v), 1+v] (reference jitter)."""

    def __init__(self, value):
        if value < 0:
            raise ValueError("brightness value should be non-negative")
        self.value = float(value)

    def _factor(self):
        return np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)

    def _apply_image(self, img):
        img = np.asarray(img)
        out = img.astype("float32") * self._factor()
        if img.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class ContrastTransform(BrightnessTransform):
    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        f = self._factor()
        # pivot on the GRAYSCALE mean (adjust_contrast reference: the
        # ITU-R 601-2 luma), not the flat RGB mean
        w = np.asarray([0.299, 0.587, 0.114], "float32")
        x = img.astype("float32")
        gray_mean = (np.tensordot(w, x, axes=([0], [0])).mean() if chw
                     else (x @ w).mean() if x.ndim == 3 and
                     x.shape[-1] == 3 else x.mean())
        out = x * f + gray_mean * (1 - f)
        if img.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class SaturationTransform(BrightnessTransform):
    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        f = self._factor()
        w = np.asarray([0.299, 0.587, 0.114], "float32")
        gray = (np.tensordot(w, img.astype("float32"), axes=([0], [0]))[None]
                if chw else (img.astype("float32") @ w)[..., None])
        out = img.astype("float32") * f + gray * (1 - f)
        if img.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class HueTransform(BaseTransform):
    """Hue shift by a fraction of the color wheel in [-value, value],
    value <= 0.5 (reference contract); HSV round-trip in numpy."""

    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = float(value)

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        shift = np.random.uniform(-self.value, self.value)
        x = img.astype("float32")
        if img.dtype == np.uint8:
            x = x / 255.0
        if chw:
            x = np.transpose(x, (1, 2, 0))
        r, g, b = x[..., 0], x[..., 1], x[..., 2]
        mx, mn = x.max(-1), x.min(-1)
        d = mx - mn + 1e-12
        h = np.where(mx == r, ((g - b) / d) % 6,
                     np.where(mx == g, (b - r) / d + 2, (r - g) / d + 4))
        h = (h / 6.0 + shift) % 1.0
        s = np.where(mx > 0, d / (mx + 1e-12), 0.0)
        v = mx
        # hsv -> rgb
        i = np.floor(h * 6).astype(int) % 6
        f = h * 6 - np.floor(h * 6)
        p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
        choices = [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
                   np.stack([p, v, t], -1), np.stack([p, q, v], -1),
                   np.stack([t, p, v], -1), np.stack([v, p, q], -1)]
        out = np.select([i[..., None] == k for k in range(6)], choices)
        if chw:
            out = np.transpose(out, (2, 0, 1))
        if img.dtype == np.uint8:
            return np.clip(out * 255.0, 0, 255).astype(np.uint8)
        return out.astype("float32")


class ColorJitter(BaseTransform):
    """Random brightness/contrast/saturation/hue in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = []
        if brightness:
            self.ts.append(BrightnessTransform(brightness))
        if contrast:
            self.ts.append(ContrastTransform(contrast))
        if saturation:
            self.ts.append(SaturationTransform(saturation))
        if hue:
            self.ts.append(HueTransform(hue))

    def _apply_image(self, img):
        for idx in np.random.permutation(len(self.ts)):
            img = self.ts[int(idx)](img)
        return img


class RandomRotation(BaseTransform):
    """Rotate by a random angle in [-degrees, degrees] (nearest sample,
    constant fill — the reference's PIL rotate collapsed to numpy)."""

    def __init__(self, degrees, fill=0):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees should be non-negative")
            degrees = (-degrees, degrees)
        self.degrees = tuple(float(d) for d in degrees)
        self.fill = fill

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        ang = np.deg2rad(np.random.uniform(*self.degrees))
        ax = 1 if chw else 0
        H, W = img.shape[ax], img.shape[ax + 1]
        cy, cx = (H - 1) / 2.0, (W - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(H), np.arange(W), indexing="ij")
        # inverse map: output pixel <- input coordinate
        c, s = np.cos(ang), np.sin(ang)
        sy = c * (yy - cy) - s * (xx - cx) + cy
        sx = s * (yy - cy) + c * (xx - cx) + cx
        yi = np.round(sy).astype(int)
        xi = np.round(sx).astype(int)
        valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
        yi, xi = np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)
        if chw:
            out = img[:, yi, xi]
            out = np.where(valid[None], out, self.fill)
        else:
            out = img[yi, xi]
            mask = valid[..., None] if img.ndim == 3 else valid
            out = np.where(mask, out, self.fill)
        return out.astype(img.dtype)


class RandomResizedCrop(BaseTransform):
    """Crop a random area/aspect patch, resize to ``size`` (the Inception
    augmentation; reference scale/ratio defaults)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        img, chw = _hwc_view(img)
        ax = 1 if chw else 0
        H, W = img.shape[ax], img.shape[ax + 1]
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            logr = np.random.uniform(np.log(self.ratio[0]),
                                     np.log(self.ratio[1]))
            ar = np.exp(logr)
            w = int(round(np.sqrt(target * ar)))
            h = int(round(np.sqrt(target / ar)))
            if 0 < w <= W and 0 < h <= H:
                i = np.random.randint(0, H - h + 1)
                j = np.random.randint(0, W - w + 1)
                patch = img[:, i:i + h, j:j + w] if chw \
                    else img[i:i + h, j:j + w]
                return self._restore_dtype(self._resize(patch), img.dtype)
        # fallback: center crop of the feasible aspect (reference parity)
        return self._restore_dtype(
            self._resize(CenterCrop(min(H, W))(img)), img.dtype)

    @staticmethod
    def _restore_dtype(out, dtype):
        # uint8 in -> uint8 out (reference parity): a silent float32 in
        # the 0-255 range would make a downstream ToTensor skip its /255
        if dtype == np.uint8:
            return np.clip(np.asarray(out), 0, 255).astype(np.uint8)
        return out
