"""paddle.vision.datasets parity.

Reference: python/paddle/vision/datasets/ (MNIST, Cifar, Flowers, ...).
This container is zero-egress: datasets load from local files when present
(PADDLE_TPU_DATA_HOME or explicit paths) and otherwise generate deterministic
synthetic data with the right shapes/classes so training pipelines and tests
run anywhere — downloads never happen implicitly.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset

DATA_HOME = os.environ.get("PADDLE_TPU_DATA_HOME",
                           os.path.expanduser("~/.cache/paddle_tpu/datasets"))


class MNIST(Dataset):
    """MNIST from local idx files; synthetic fallback (28x28, 10 classes)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None,
                 synthetic_size=1024):
        self.mode = mode
        self.transform = transform
        images = labels = None
        base = os.path.join(DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            images, labels = self._load_idx(image_path, label_path)
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            labels = rng.randint(0, 10, synthetic_size).astype("int64")
            images = (rng.rand(synthetic_size, 28, 28) * 255).astype("uint8")
        self.images, self.labels = images, labels

    @staticmethod
    def _load_idx(image_path, label_path):
        op = gzip.open if image_path.endswith(".gz") else open
        with op(image_path, "rb") as f:
            _, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), dtype=np.uint8).reshape(
                n, rows, cols)
        with op(label_path, "rb") as f:
            struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32")[None] / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    """CIFAR-10 from local pickled batches; synthetic fallback."""

    _DIR = "cifar-10-batches-py"
    _TRAIN_FILES = [f"data_batch_{i}" for i in range(1, 6)]
    _TEST_FILES = ["test_batch"]
    _LABEL_KEY = b"labels"
    num_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None, synthetic_size=1024):
        self.transform = transform
        path = data_file or os.path.join(DATA_HOME, self._DIR)
        if os.path.isdir(path):
            import pickle
            xs, ys = [], []
            names = self._TRAIN_FILES if mode == "train" else self._TEST_FILES
            for nm in names:
                with open(os.path.join(path, nm), "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                xs.append(d[b"data"])
                ys.extend(d[self._LABEL_KEY])
            self.images = np.concatenate(xs).reshape(-1, 3, 32, 32)
            self.labels = np.asarray(ys, dtype="int64")
        else:
            rng = np.random.RandomState(0 if mode == "train" else 1)
            self.labels = rng.randint(0, self.num_classes,
                                      synthetic_size).astype("int64")
            self.images = (rng.rand(synthetic_size, 3, 32, 32) * 255) \
                .astype("uint8")

    def __getitem__(self, idx):
        img = self.images[idx].astype("float32") / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(self.images[idx])
        return img, self.labels[idx]

    def __len__(self):
        return len(self.labels)


class Cifar100(Cifar10):
    _DIR = "cifar-100-python"
    _TRAIN_FILES = ["train"]
    _TEST_FILES = ["test"]
    _LABEL_KEY = b"fine_labels"
    num_classes = 100


class Flowers(Dataset):
    """vision/datasets/flowers.py: 102-category flowers.  Real-file mode
    reads the reference's artifacts — a jpg tarball (jpg/image_%05d.jpg),
    imagelabels.mat and setid.mat (scipy.io; 'trnid'/'valid'/'tstid'
    index vectors, 1-based) — and yields (image [3,H,W] float, label [1]
    int64).  Synthetic fallback keeps shapes and the 1..102 label range."""

    _FLAGS = {"train": "trnid", "valid": "valid", "test": "tstid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None,
                 synthetic_size=64):
        assert mode.lower() in self._FLAGS, mode
        self.mode = mode.lower()
        self.transform = transform
        self._tar = None
        if data_file is not None and os.path.exists(data_file):
            if label_file is None or not os.path.exists(label_file):
                # a real data_file with a missing/mistyped label_file must
                # not silently degrade to synthetic noise
                raise ValueError(
                    "Flowers: data_file is set but label_file is "
                    f"{'missing' if label_file else 'not given'} — the "
                    "labels live in imagelabels.mat; pass its path")
            import tarfile
            import scipy.io as scio
            self.labels = scio.loadmat(label_file)["labels"][0]
            if setid_file is None or not os.path.exists(setid_file):
                # silently serving ALL images to every mode would let eval
                # run on the training split with no sign anything is wrong
                raise ValueError(
                    "Flowers: data_file/label_file are set but setid_file "
                    f"is {'missing' if setid_file else 'not given'} — the "
                    "train/valid/test split indexes live in setid.mat; "
                    "pass its path")
            self.indexes = scio.loadmat(setid_file)[
                self._FLAGS[self.mode]][0]
            self._tar = tarfile.open(data_file)
            self._name2mem = {m.name: m for m in self._tar.getmembers()}
        else:
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            self.labels = rng.randint(1, 103, synthetic_size + 1)
            self.indexes = np.arange(1, synthetic_size + 1)
            self._images = (rng.rand(synthetic_size, 3, 32, 32) * 255) \
                .astype("uint8")

    def __getitem__(self, idx):
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]]).astype("int64")
        if self._tar is not None:
            import io as _io
            from PIL import Image
            raw = self._tar.extractfile(
                self._name2mem["jpg/image_%05d.jpg" % index]).read()
            img = np.asarray(Image.open(_io.BytesIO(raw)))
            img = img.transpose(2, 0, 1).astype("float32") / 127.5 - 1.0
        else:
            img = self._images[idx].astype("float32") / 127.5 - 1.0
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """vision/datasets/voc2012.py: segmentation pairs.  Real-file mode
    reads the VOCdevkit tarball — ImageSets/Segmentation/{train,val,
    trainval}.txt name lists, JPEGImages/{}.jpg inputs,
    SegmentationClass/{}.png masks — yielding (image [3,H,W],
    mask [H,W]).  Synthetic fallback: 21-class random masks."""

    _LIST = {"train": "train", "valid": "val", "test": "val",
             "trainval": "trainval"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=32):
        assert mode.lower() in self._LIST, mode
        self.mode = mode.lower()
        self.transform = transform
        self._tar = None
        if data_file is not None and os.path.exists(data_file):
            import tarfile
            self._tar = tarfile.open(data_file)
            names = self._tar.extractfile(
                "VOCdevkit/VOC2012/ImageSets/Segmentation/%s.txt"
                % self._LIST[self.mode]).read().decode().split()
            self._names = names
        else:
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            self._names = [f"synth_{i}" for i in range(synthetic_size)]
            self._images = (rng.rand(synthetic_size, 3, 32, 32) * 255) \
                .astype("uint8")
            self._masks = rng.randint(0, 21, (synthetic_size, 32, 32)) \
                .astype("int64")

    def __getitem__(self, idx):
        if self._tar is not None:
            import io as _io
            from PIL import Image
            name = self._names[idx]
            raw = self._tar.extractfile(
                "VOCdevkit/VOC2012/JPEGImages/%s.jpg" % name).read()
            img = np.asarray(Image.open(_io.BytesIO(raw)))
            img = img.transpose(2, 0, 1).astype("float32") / 127.5 - 1.0
            raw = self._tar.extractfile(
                "VOCdevkit/VOC2012/SegmentationClass/%s.png" % name).read()
            mask = np.asarray(Image.open(_io.BytesIO(raw))).astype("int64")
        else:
            img = self._images[idx].astype("float32") / 127.5 - 1.0
            mask = self._masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, mask

    def __len__(self):
        return len(self._names)


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp", ".npy")


def _default_loader(path):
    """npy loads headless; images via PIL when present (folder.py
    default_loader parity with a zero-dependency array path)."""
    if path.endswith(".npy"):
        return np.load(path)
    from PIL import Image
    with open(path, "rb") as f:
        return np.asarray(Image.open(f).convert("RGB"))


def make_dataset(directory, class_to_idx, extensions, is_valid_file=None):
    """folder.py:39 parity: walk sorted class dirs collecting
    (path, class_idx) samples."""
    samples = []
    directory = os.path.expanduser(directory)
    if extensions is not None:
        def is_valid_file(p):       # noqa: F811
            return p.lower().endswith(tuple(extensions))
    for target in sorted(class_to_idx):
        d = os.path.join(directory, target)
        if not os.path.isdir(d):
            continue
        for root, _, fnames in sorted(os.walk(d, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(root, fname)
                if is_valid_file(path):
                    samples.append((path, class_to_idx[target]))
    return samples


class DatasetFolder(Dataset):
    """folder.py:62: generic root/class_x/*.ext tree → (sample,
    class_index) dataset; classes sorted alphabetically."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        classes = sorted(e.name for e in os.scandir(root) if e.is_dir())
        class_to_idx = {c: i for i, c in enumerate(classes)}
        samples = make_dataset(root, class_to_idx, extensions,
                               is_valid_file)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\nSupported "
                f"extensions are: {','.join(extensions or [])}")
        self.loader = _default_loader if loader is None else loader
        self.extensions = extensions
        self.classes = classes
        self.class_to_idx = class_to_idx
        self.samples = samples
        self.targets = [s[1] for s in samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """folder.py:216: flat (possibly nested) image dir → [sample] records
    (no labels)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        if extensions is None and is_valid_file is None:
            extensions = IMG_EXTENSIONS
        if extensions is not None:
            def is_valid_file(p):   # noqa: F811
                return p.lower().endswith(tuple(extensions))
        samples = []
        for r, _, fnames in sorted(os.walk(root, followlinks=True)):
            for fname in sorted(fnames):
                path = os.path.join(r, fname)
                if is_valid_file(path):
                    samples.append(path)
        if not samples:
            raise RuntimeError(
                f"Found 0 files in subfolders of: {root}\nSupported "
                f"extensions are: {','.join(extensions or [])}")
        self.loader = _default_loader if loader is None else loader
        self.samples = samples

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
