"""paddle.vision parity: model zoo, transforms, datasets."""
from . import models, transforms, datasets  # noqa: F401
from .models import *  # noqa: F401,F403
