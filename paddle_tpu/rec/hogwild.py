"""Hogwild-style multi-threaded PS training.

Reference parity: paddle/fluid/framework/device_worker.h:237 HogwildWorker
(TrainFiles: each thread owns a DataFeed and runs the op graph against
SHARED parameters with no synchronization) and trainer.h:51 MultiTrainer
spinning one worker per thread, pushing sparse grads to the pservers
asynchronously.

TPU-first reframe: the reference's Hogwild exists to saturate CPU cores on
sparse CTR models.  With one accelerator the compute stream is already a
single queue, so the win moves to the HOST side: N worker threads each run
unique/pull/push (RPC + numpy latency) concurrently, keeping the chip's
queue full while any one thread blocks on the parameter server.  Dense
parameters are shared Hogwild-style: each worker computes gradients
against a lock-free snapshot and applies them to the CURRENT shared state
(stale-gradient async SGD — the same convergence contract as the
reference's unsynchronized writes, at whole-tensor granularity); sparse
grads push to the shared PS client, whose tables apply them under the
server's per-table serialization.
"""
from __future__ import annotations

import queue as queue_mod
import threading
from typing import Iterable, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .wide_deep import (WideDeep, _DenseCore, bce_with_logits_mean,
                        dense_param_map, make_adam_update)


class HogwildTrainer:
    """N host threads over one WideDeep model + shared PS client.

    ``trainer.train(batches, num_threads=4)`` consumes an iterable of
    (sparse_ids, dense_x, labels) batches from a shared queue — the
    DataFeed of HogwildWorker::TrainFiles — and returns per-batch losses
    in completion order.
    """

    def __init__(self, model: WideDeep, lr: float = 1e-3):
        from ..framework import functional as F
        self.model = model
        self.lr = float(lr)
        core = _DenseCore(model)
        apply, params, buffers = F.functionalize(core, training=True)
        self._params = params
        self._adam = {
            "m": {k: jnp.zeros_like(v) for k, v in params.items()},
            "v": {k: jnp.zeros_like(v) for k, v in params.items()},
            "t": jnp.zeros((), jnp.int32),
        }
        self._apply_lock = threading.Lock()

        def grads_fn(params, wide_rows, deep_rows, inv, dense_x, labels):
            def loss_of(p, wr, dr):
                out = apply(p, buffers, wr, dr, inv, inv, dense_x)
                x = out[0] if isinstance(out, tuple) else out
                return bce_with_logits_mean(x, labels)
            (loss), grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
                params, wide_rows, deep_rows)
            return loss, grads

        self._grads = jax.jit(grads_fn)
        self._adam_apply = jax.jit(make_adam_update(self.lr))

    # -- one worker step ------------------------------------------------------
    def _worker_step(self, ids, dense_x, labels) -> float:
        we, de = self.model.wide_emb, self.model.deep_emb
        ids = np.asarray(ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        w_rows = jnp.asarray(we.pull_padded_rows(uniq))
        d_rows = jnp.asarray(de.pull_padded_rows(uniq))
        inv_dev = jnp.asarray(inv.reshape(ids.shape), jnp.int32)
        # lock-free snapshot: stale by however many applies raced past us
        snapshot = self._params
        loss, (gp, gw, gd) = self._grads(
            snapshot, w_rows, d_rows, inv_dev,
            jnp.asarray(dense_x), jnp.asarray(labels))
        n = len(uniq)
        we.client.push_sparse(we.table_id, uniq, np.asarray(gw)[:n])
        de.client.push_sparse(de.table_id, uniq, np.asarray(gd)[:n])
        # apply the (possibly stale) dense grads to the CURRENT shared
        # state; the lock only guards the pointer swap — dispatch is async
        with self._apply_lock:
            self._params, self._adam = self._adam_apply(
                self._params, self._adam, gp)
        return float(loss)

    # -- the multi-thread drive (MultiTrainer::Run) ---------------------------
    def train(self, batches: Iterable, num_threads: int = 2,
              queue_size: int = 16) -> List[float]:
        """Run every batch through ``num_threads`` Hogwild workers; returns
        losses in completion order.  Exceptions from any worker re-raise
        after all threads retire."""
        if int(num_threads) < 1:
            raise ValueError(f"num_threads must be >= 1, got {num_threads}")
        q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_size)
        losses: List[float] = []
        errs: List[BaseException] = []
        loss_lock = threading.Lock()

        def worker():
            while True:
                item = q.get()
                try:
                    if item is None:
                        return
                    l = self._worker_step(*item)
                    with loss_lock:
                        losses.append(l)
                except BaseException as e:    # noqa: BLE001 — surfaced below
                    errs.append(e)
                finally:
                    q.task_done()

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(int(num_threads))]
        for t in threads:
            t.start()
        for b in batches:
            q.put(tuple(b))
        for _ in threads:
            q.put(None)
        for t in threads:
            t.join()
        if errs:
            raise errs[0]
        return losses

    def sync_params(self):
        """Point the eager model's dense params at the shared trained state
        (pointer swap, no copy) — call before eval/save."""
        for name, p in dense_param_map(self.model, self._params):
            p._value = self._params[name]


class PSGPUTrainer:
    """trainer.h:281 PSGPUTrainer parity, by construction: the device-cache
    WideDeepTrainer IS the PSGPU architecture — BuildGPUPS ≙ the cache fill
    (export_rows → device arenas), the on-accelerator sparse optimizer ≙
    apply_rule_device inside the fused step, EndPass ≙ writeback_all.
    This named wrapper forces cache mode on and exposes the reference's
    end_pass() verb."""

    def __init__(self, model, lr: float = 1e-3,
                 cache_capacity: int = 1 << 20, **kw):
        from .wide_deep import WideDeepTrainer
        self._inner = WideDeepTrainer(model, lr=lr, device_cache=True,
                                      cache_capacity=cache_capacity, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def end_pass(self):
        """PSGPUWrapper::EndPass — flush every cached row to the tables."""
        self._inner.flush()
