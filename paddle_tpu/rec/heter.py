"""Heterogeneous CPU↔accelerator trainer + the trainer/worker/wrapper
ledgers.

Reference parity: paddle/fluid/framework/trainer.h:163 HeterXpuTrainer —
CPU trainer processes run the sparse/IO legs and ship dense sections to an
accelerator service (RegisterServiceHandler/RunTask over HeterWrapper RPC,
heter_wrapper.h), with EndPass merging state back.  device_worker.h
HeterCpuWorker holds the CPU legs.

TPU-first reframe: on a PJRT host the accelerator is in-process, so the
HeterRequest/HeterResponse RPC collapses to bounded queues between three
pipeline stages — N *cpu workers* (parse + unique + PS pull: RPC/numpy
bound), ONE *device service* (jitted dense fwd/bwd + Adam on the chip; it
OWNS the dense params, so unlike Hogwild there are no stale writes), and
N *push workers* (D2H + sparse push back to the PS).  The stages overlap:
while the chip runs batch k, cpu workers pull k+1..k+q and push workers
drain k-1 — the same latency-hiding the reference buys with its service
thread-pool.  The cross-HOST seat of the heter design is the PS RPC layer
(ps/service.py), exactly as in the reference.
"""
from __future__ import annotations

import queue as queue_mod
import threading
from typing import Iterable, List

import numpy as np
import jax
import jax.numpy as jnp

from .wide_deep import (WideDeep, _DenseCore, bce_with_logits_mean,
                        dense_param_map, make_adam_update)


class HeterTrainer:
    """trainer.h:163 HeterXpuTrainer equivalent (see module docstring).

    ``train(batches, num_cpu_workers=2, queue_size=8)`` consumes
    (sparse_ids, dense_x, labels) batches; returns losses in completion
    order.  ``end_pass()`` drains and returns (the reference's EndPass)."""

    def __init__(self, model: WideDeep, lr: float = 1e-3,
                 sharded_embedding: bool = None, sharded_vocab: int = None,
                 mesh=None):
        from ..framework import functional as F
        from ..framework.flags import flag as _flag
        self.model = model
        self.lr = float(lr)
        core = _DenseCore(model)
        apply, params, buffers = F.functionalize(core, training=True)
        self._params = params
        self._adam = {
            "m": {k: jnp.zeros_like(v) for k, v in params.items()},
            "v": {k: jnp.zeros_like(v) for k, v in params.items()},
            "t": jnp.zeros((), jnp.int32),
        }

        def step_fn(params, adam, wide_rows, deep_rows, inv, dense_x,
                    labels):
            def loss_of(p, wr, dr):
                out = apply(p, buffers, wr, dr, inv, inv, dense_x)
                x = out[0] if isinstance(out, tuple) else out
                return bce_with_logits_mean(x, labels)
            loss, (gp, gw, gd) = jax.value_and_grad(
                loss_of, argnums=(0, 1, 2))(params, wide_rows, deep_rows)
            new_params, new_adam = make_adam_update(self.lr)(params, adam,
                                                             gp)
            return loss, new_params, new_adam, gw, gd

        self._step = jax.jit(step_fn)

        # -- mesh-sharded deep leg (FLAGS_sharded_embedding) ------------------
        # The heter pipeline's TPU-scale variant: the deep table lives
        # row-partitioned ON the accelerator mesh, so the cpu workers stop
        # pulling deep rows (host RPC leg shrinks to wide + ids), the
        # device service routes the lookup via all-to-all inside its one
        # jitted step, and the backward leg routes row gradients to the
        # owner shards and applies the sparse rule to the local slice only
        # — no deep push ever crosses the host boundary.
        self._sharded = (bool(_flag("sharded_embedding"))
                         if sharded_embedding is None
                         else bool(sharded_embedding))
        if self._sharded:
            if sharded_vocab is None:
                raise ValueError(
                    "sharded embedding mode needs sharded_vocab: the id "
                    "bound sizing the mesh-partitioned deep table")
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .sharded_embedding import ShardedTable
            de = model.deep_emb
            kw = {k: v for k, v in de.table_kw.items()
                  if k in ("eps", "l1", "l2", "lr_power")}
            self._dtab = ShardedTable(de.dim, sharded_vocab,
                                      optimizer=de.optimizer, lr=de.lr,
                                      mesh=mesh, **kw)
            self._dtab_tree = self._dtab.init_tree()
            self._rep_sh = NamedSharding(self._dtab.mesh, P())
            rep_put = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda v: jax.device_put(v, self._rep_sh), t)
            self._params = rep_put(self._params)
            self._adam = rep_put(self._adam)
            self._sharded_fns = {}
            dtab = self._dtab

            def make_sharded_step(cap_u, cap_f):
                def step(params, adam, dtree, wide_rows, uniq_r,
                         fill_ids, fill_rows, fill_state, inv, dense_x,
                         labels):
                    # cold fill: first-sighting rows imported at owners
                    dtree = dtab.set_rows(dtree, fill_ids, fill_rows,
                                          fill_state, cap=cap_f)
                    # routed lookup (rows only — state stays put)
                    deep_rows, _st, _ovf = dtab.gather(
                        dtree, uniq_r, cap=cap_u, with_state=False)

                    def loss_of(p, wr, dr):
                        out = apply(p, buffers, wr, dr, inv, inv, dense_x)
                        x = out[0] if isinstance(out, tuple) else out
                        return bce_with_logits_mean(x, labels)

                    loss, (gp, gw, gd) = jax.value_and_grad(
                        loss_of, argnums=(0, 1, 2))(params, wide_rows,
                                                    deep_rows)
                    new_params, new_adam = make_adam_update(self.lr)(
                        params, adam, gp)
                    # backward leg: row grads route to the owner shard
                    dtree = dtab.apply_rule(dtree, uniq_r, gd, cap=cap_u)
                    return loss, new_params, new_adam, dtree, gw
                return step

            self._make_sharded_step = make_sharded_step

    # -- pipeline stages ------------------------------------------------------
    def _cpu_leg(self, ids, dense_x, labels):
        """HeterCpuWorker: unique + PS pull (host RPC leg).  Sharded mode
        pulls only the WIDE rows — deep rows live on the mesh; the leg
        ships ids (padded for routing) plus first-sighting cold rows."""
        we, de = self.model.wide_emb, self.model.deep_emb
        ids = np.asarray(ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        w_rows = jnp.asarray(we.pull_padded_rows(uniq))
        inv_dev = jnp.asarray(inv.reshape(ids.shape), jnp.int32)
        if not self._sharded:
            d_rows = jnp.asarray(de.pull_padded_rows(uniq))
            return (uniq, w_rows, d_rows, inv_dev, jnp.asarray(dense_x),
                    jnp.asarray(labels))
        from ..distributed.ps.device_cache import pad_adaptive
        from ..ops.routing import pad_requests
        self._dtab.check_ids(uniq)
        n_sh = self._dtab.n_shards
        u_pad = pad_requests(len(uniq), n_sh, pad_adaptive)
        uniq_r = np.full(u_pad, -1, np.int32)
        uniq_r[:len(uniq)] = uniq
        # candidate cold ids: residency is CONFIRMED on the device thread
        # (single owner of the table state), the export here just keeps
        # the host RPC off the device leg's critical path
        cold, _warm = self._dtab.split_cold_warm(uniq)
        if len(cold):
            c_rows, c_state = de.client.export_rows(de.table_id, cold)
        else:
            c_rows = np.zeros((0, de.dim), np.float32)
            c_state = {k: np.zeros((0, de.dim), np.float32)
                       for k in self._dtab.state_names}
        f_pad = pad_requests(len(cold), n_sh, pad_adaptive)
        fill_ids = np.full(f_pad, -1, np.int32)
        fill_ids[:len(cold)] = cold
        fill_rows = np.zeros((f_pad, de.dim), np.float32)
        fill_rows[:len(cold)] = c_rows
        fill_state = {}
        for k in self._dtab.state_names:
            buf = np.zeros((f_pad, de.dim), np.float32)
            buf[:len(cold)] = c_state[k]
            fill_state[k] = buf
        return (uniq, w_rows, uniq_r, fill_ids, fill_rows, fill_state,
                inv_dev, np.asarray(dense_x, np.float32),
                np.asarray(labels, np.float32))

    def _device_leg(self, task):
        """RunTask: the dense section on the chip; owns param state."""
        if self._sharded:
            return self._device_leg_sharded(task)
        uniq, w_rows, d_rows, inv_dev, dense_x, labels = task
        loss, self._params, self._adam, gw, gd = self._step(
            self._params, self._adam, w_rows, d_rows, inv_dev, dense_x,
            labels)
        return uniq, gw, gd, loss

    def _device_leg_sharded(self, task):
        """Sharded RunTask: the ONE thread that owns the table state also
        owns residency, so double-fills from racing cpu workers are
        dropped here (a stale fill would overwrite on-device training)."""
        import jax as _jax
        (uniq, w_rows, uniq_r, fill_ids, fill_rows, fill_state, inv_dev,
         dense_x, labels) = task
        live = fill_ids >= 0
        if live.any():
            resident = np.fromiter(
                (int(i) in self._dtab.resident for i in fill_ids[live]),
                bool, int(live.sum()))
            if resident.any():
                drop = np.zeros_like(live)
                drop[np.nonzero(live)[0][resident]] = True
                fill_ids = np.where(drop, -1, fill_ids)
            self._dtab.resident.update(int(i) for i in fill_ids[fill_ids >= 0])
        n_sh = self._dtab.n_shards
        cap_u = (self._dtab.cap_for(uniq, len(uniq_r) // n_sh)
                 if self._dtab.bucket_cap else len(uniq_r) // n_sh)
        cap_f = len(fill_ids) // n_sh
        key = (len(uniq_r), len(fill_ids), inv_dev.shape, cap_u)
        fn = self._sharded_fns.get(key)
        if fn is None:
            fn = _jax.jit(self._make_sharded_step(cap_u, cap_f),
                          donate_argnums=(2,))
            self._sharded_fns[key] = fn
        rep = lambda x: _jax.device_put(jnp.asarray(x),  # noqa: E731
                                        self._rep_sh)
        loss, self._params, self._adam, self._dtab_tree, gw = fn(
            self._params, self._adam, self._dtab_tree, rep(w_rows),
            rep(uniq_r), rep(fill_ids), rep(fill_rows),
            {k: rep(v) for k, v in fill_state.items()}, rep(inv_dev),
            rep(dense_x), rep(labels))
        return uniq, gw, None, loss

    def _push_leg(self, uniq, gw, gd):
        """Sparse push back to the PS (host RPC leg).  Sharded mode has no
        deep push — the rule already ran on the owner shards."""
        we, de = self.model.wide_emb, self.model.deep_emb
        n = len(uniq)
        we.client.push_sparse(we.table_id, uniq, np.asarray(gw)[:n])
        if gd is not None:
            de.client.push_sparse(de.table_id, uniq, np.asarray(gd)[:n])

    # -- drive ----------------------------------------------------------------
    def train(self, batches: Iterable, num_cpu_workers: int = 2,
              queue_size: int = 8) -> List[float]:
        if int(num_cpu_workers) < 1:
            raise ValueError("num_cpu_workers must be >= 1")
        in_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_size)
        dev_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_size)
        push_q: "queue_mod.Queue" = queue_mod.Queue(maxsize=queue_size)
        losses: List[float] = []
        errs: List[BaseException] = []

        def cpu_worker():
            while True:
                item = in_q.get()
                try:
                    if item is None:
                        return
                    dev_q.put(self._cpu_leg(*item))
                except BaseException as e:   # noqa: BLE001 — surfaced below
                    errs.append(e)
                finally:
                    in_q.task_done()

        def device_service():
            # ONE thread owns the chip and the dense state (RunTask loop);
            # the chip queue stays full as long as cpu workers keep up
            while True:
                task = dev_q.get()
                try:
                    if task is None:
                        return
                    uniq, gw, gd, loss = self._device_leg(task)
                    push_q.put((uniq, gw, gd))
                    losses.append(float(loss))
                except BaseException as e:   # noqa: BLE001
                    errs.append(e)
                finally:
                    dev_q.task_done()

        def push_worker():
            while True:
                item = push_q.get()
                try:
                    if item is None:
                        return
                    self._push_leg(*item)
                except BaseException as e:   # noqa: BLE001
                    errs.append(e)
                finally:
                    push_q.task_done()

        cpus = [threading.Thread(target=cpu_worker, daemon=True)
                for _ in range(int(num_cpu_workers))]
        dev = threading.Thread(target=device_service, daemon=True)
        pushers = [threading.Thread(target=push_worker, daemon=True)
                   for _ in range(int(num_cpu_workers))]
        for t in cpus + [dev] + pushers:
            t.start()
        for b in batches:
            in_q.put(tuple(b))
        for _ in cpus:
            in_q.put(None)
        for t in cpus:
            t.join()
        dev_q.put(None)
        dev.join()
        for _ in pushers:
            push_q.put(None)
        for t in pushers:
            t.join()
        if errs:
            raise errs[0]
        return losses

    def end_pass(self):
        """EndPass: drain trained state the host can't see — in sharded
        mode the mesh-resident deep rows (+optimizer state) write back to
        the host PS table; otherwise nothing is buffered outside the
        queues once train() returns."""
        if self._sharded:
            de = self.model.deep_emb
            self._dtab.flush_to_client(self._dtab_tree, de.client,
                                       de.table_id)

    def sync_params(self):
        """MergeToRootScope: point the eager model's dense params at the
        trained state (pointer swap)."""
        for name, p in dense_param_map(self.model, self._params):
            p._value = self._params[name]


# ---------------------------------------------------------------------------
# Trainer / DeviceWorker / fleet-wrapper ledgers (ops/coverage.py discipline)
# ---------------------------------------------------------------------------

# every REGISTER_TRAINER_CLASS name (trainer_factory.cc:64-75)
TRAINER_LEDGER = {
    "MultiTrainer": (
        "engine", "static/executor.py train_from_dataset — the scanned "
        "epoch IS the multi-thread DataFeed loop (one lax.scan replaces "
        "N HogwildWorkers over a channel)"),
    "DistMultiTrainer": (
        "engine", "train_from_dataset + distributed/ps pull-push "
        "(rec/wide_deep.py WideDeepTrainer pull/push mode ≙ "
        "DownpourWorker TrainFiles)"),
    "HeterXpuTrainer": ("api", "paddle_tpu.rec.heter.HeterTrainer"),
    "HeterBoxTrainer": (
        "subsumed", "same heter pipeline as HeterXpuTrainer with BoxPS "
        "memory arenas; the arena seat is distributed/ps/device_cache.py "
        "(device HBM row arenas) — no separate trainer needed"),
    "PSGPUTrainer": ("api", "paddle_tpu.rec.hogwild.PSGPUTrainer"),
    "PipelineTrainer": (
        "api", "paddle_tpu.parallel.pipeline.PipelineModule (fleet "
        "strategy.pipeline; SectionWorker ≙ GPipe stage over shard_map)"),
}

# every REGISTER_DEVICE_WORKER_CLASS name (device_worker_factory.cc:64-80)
DEVICE_WORKER_LEDGER = {
    "HogwildWorker": ("api", "paddle_tpu.rec.hogwild.HogwildTrainer"),
    "DownpourWorker": (
        "engine", "rec/wide_deep.py pull → one-jit dense step → push "
        "(the TrainFiles loop of downpour async SGD)"),
    "DownpourWorkerOpt": (
        "subsumed", "op-graph splitting/pruning optimization of "
        "DownpourWorker — meaningless under one jitted XLA step"),
    "HeterCpuWorker": ("api", "paddle_tpu.rec.heter.HeterTrainer (cpu "
                       "worker stage)"),
    "HeterBoxWorker": ("subsumed", "HeterCpuWorker + BoxPS arenas; see "
                       "HeterBoxTrainer row"),
    "PSGPUWorker": ("api", "paddle_tpu.rec.hogwild.PSGPUTrainer"),
    "SectionWorker": ("api", "paddle_tpu.parallel.pipeline.GPipe"),
}

# framework/fleet/*.h wrappers (VERDICT r4 #10: no row silently partial)
FLEET_WRAPPER_LEDGER = {
    "fleet_wrapper": (
        "api", "paddle_tpu.distributed.fleet + distributed/ps "
        "(init/pull/push/barrier over ps/service.py RPC)"),
    "gloo_wrapper": (
        "api", "paddle_tpu.distributed.fleet.util (store-based CPU "
        "collectives; tests/test_dist_numerics.py 2-proc gate)"),
    "ps_gpu_wrapper": (
        "api", "paddle_tpu.distributed.ps.device_cache (HeterPS hot-row "
        "HBM arenas + on-chip sparse rules; BENCH wide_deep 12-15x)"),
    "heter_wrapper": (
        "api", "paddle_tpu.rec.heter.HeterTrainer (the RunTask RPC "
        "collapsed to in-process stage queues; cross-host seat = "
        "ps/service.py)"),
    "box_wrapper": (
        "subsumed", "BoxPS is a closed-source embedded PS for Baidu "
        "AIBox; its public capabilities — pinned pull/push batching into "
        "device arenas, pass-scoped caches (BeginPass/EndPass) — are the "
        "device_cache design (SlotDirectory + arenas + flush()); the "
        "proprietary backend has no open equivalent to match"),
    "heter_context": (
        "subsumed", "shard bookkeeping struct for ps_gpu_wrapper — the "
        "device_cache SlotDirectory holds that role"),
    "nccl_wrapper": (
        "n/a", "NCCL bootstrap — XLA collectives over the jax.distributed "
        "global mesh replace NCCL entirely (parallel/mesh.py)"),
}


def create_trainer(name: str):
    """TrainerFactory::CreateTrainer parity: resolve a reference trainer
    name to the equivalent entry point (raises KeyError for unknown names,
    TypeError for rows that are engine modes rather than classes)."""
    cls, target = TRAINER_LEDGER[name]
    if cls != "api":
        raise TypeError(
            f"{name} is not a standalone class here ({cls}): {target}")
    import importlib
    mod, attr = target.split(" ")[0].rsplit(".", 1)
    return getattr(importlib.import_module(mod), attr)
