"""Mesh-sharded embedding tables with in-graph all-to-all lookup.

Reference parity: the HeterPS hash-table shards
(framework/fleet/heter_ps/hashtable.h — each GPU owns a shard of the
sparse table; ids route to the owning card, gather there, and route back)
and the PS shard rule (distributed/ps/ ``id % shard_num``).  The reference
needs that machinery because CTR embedding tables outgrow one device; the
TPU-native answer keeps the table ON the mesh: row-partitioned over a mesh
axis (``P(axis, None)`` on the parameter, so ZeRO/autoshard layering
composes) with the id routing as ``lax.all_to_all`` inside ``shard_map``
(ops/routing.py), entirely inside the jitted step.  A billion-row table
single-chip HBM cannot hold becomes ``rows / axis_size`` per chip, and the
lookup costs ICI bytes instead of a parameter-server RPC.

Three consumption tiers:

  * :class:`ShardedEmbedding` — an ``nn.Layer`` whose ``table`` parameter
    is the sharded storage; ``forward`` dedups ids on device
    (``sort_unique_static``), routes the unique set, gathers and scatters
    back to row order.  Differentiable end-to-end (the all-to-all
    transposes to the reverse route), so ``TrainStep``/autoshard/ZeRO all
    compose — the generic-autodiff tier, used by the HLO-audit and bench
    builders.
  * :class:`ShardedTable` — the trainer-facing runtime: the same storage
    plus per-row optimizer-state planes and host-side residency
    bookkeeping, with routed gather / set / rule-update entry points that
    trainers call INSIDE their own jitted steps (manual sparse updates:
    row gradients route to the owner shard and update only its slice —
    no dense vocab-sized gradient ever materializes).
  * ``WideDeepTrainer`` / ``HeterTrainer`` integration (rec/wide_deep.py,
    rec/heter.py) behind ``FLAGS_sharded_embedding``: the deep-leg table
    lives on the mesh, composed with the hot-row device cache
    (distributed/ps/device_cache.py) so the skewed head short-circuits
    the all-to-all — only cache misses route.

Storage layout: see ops/routing.py (``rps = ceil(vocab / n)`` real rows
plus one scratch row per shard; :func:`~..ops.routing.storage_index` maps
logical ids to storage rows).
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import nn
from ..framework import flags as _flags
from ..ops import routing as _routing

__all__ = ["ShardedEmbedding", "ShardedTable", "ShardedWideDeep",
           "sharded_axis", "sharded_bucket_cap"]


def sharded_axis() -> str:
    return str(_flags.flag("sharded_embedding_axis"))


def sharded_bucket_cap() -> int:
    return int(_flags.flag("sharded_embedding_bucket_cap"))


def _axis_size(mesh, axis: str) -> int:
    n = dict(mesh.shape).get(axis, 0)
    if n < 1:
        raise ValueError(
            f"sharded embedding axis {axis!r} is not an axis of the mesh "
            f"{dict(mesh.shape)} (FLAGS_sharded_embedding_axis)")
    return int(n)


class ShardedEmbedding(nn.Layer):
    """Embedding whose table is row-partitioned over a mesh axis.

    The ``table`` parameter has storage shape ``[(rps+1)*n, dim]``
    (per-shard scratch rows included) and carries a ``P(axis, None)``
    annotation, so ``TrainStep`` stores it sharded, ZeRO layers its own
    dp shard on top idempotently, and the ``rec-embedding`` autoshard
    rule recognizes the ``.table`` path.  ``forward(ids)`` runs the full
    dedup → all-to-all route → gather → inverse-scatter chain in-graph.
    """

    def __init__(self, num_embeddings: int, embedding_dim: int, *,
                 mesh=None, axis: Optional[str] = None,
                 bucket_cap: Optional[int] = None, weight_attr=None,
                 annotate: bool = True):
        super().__init__()
        from ..parallel.mesh import get_mesh
        from .. import nn as _nn
        self.mesh = mesh or get_mesh()
        self.axis = axis or sharded_axis()
        self.n_shards = _axis_size(self.mesh, self.axis)
        self.num_embeddings = int(num_embeddings)
        self.embedding_dim = int(embedding_dim)
        self.rps = _routing.rows_per_shard(num_embeddings, self.n_shards)
        self.bucket_cap = (sharded_bucket_cap() if bucket_cap is None
                           else int(bucket_cap))
        rows = _routing.storage_table_rows(num_embeddings, self.n_shards)
        self.table = self.create_parameter(
            [rows, embedding_dim], attr=weight_attr,
            default_initializer=_nn.initializer.XavierUniform())
        # scratch rows zero: they absorb sentinel routing and must not
        # leak initializer noise into masked slots
        scratch = _routing.storage_index(
            np.arange(self.n_shards) * self.rps, self.rps) + self.rps
        self.table.set_value(self.table._value.at[jnp.asarray(scratch)]
                             .set(0.0))
        if annotate:
            from ..parallel.api import shard_parameter
            shard_parameter(self.table, P(self.axis, None))

    # -- in-graph pieces -----------------------------------------------------
    def lookup_unique(self, uniq_ids, table=None):
        """Routed gather of already-unique ids ``[U]`` (sentinel -1,
        ``U % n_shards == 0``) -> ``([U, D] rows, overflow)``."""
        t = self.table._value if table is None else table
        rows, ovf = _routing.all_to_all_gather(
            [t], uniq_ids, mesh=self.mesh, axis=self.axis, rps=self.rps,
            cap=self.bucket_cap or None)
        return rows[0], ovf

    def forward(self, ids, table=None):
        from ..framework.tensor import Tensor
        from .wide_deep import sort_unique_static
        x = ids._value if isinstance(ids, Tensor) else jnp.asarray(ids)
        flat = x.reshape(-1).astype(jnp.int32)
        u_pad = _routing.pad_requests(flat.shape[0], self.n_shards,
                                      lambda n: n)
        uniq, inv, count, _counts = sort_unique_static(
            jnp.pad(flat, (0, u_pad - flat.shape[0]),
                    constant_values=0) if u_pad != flat.shape[0] else flat,
            cap=u_pad)
        # dedup pads uniq with zeros beyond count — sentinel them so the
        # router drops them instead of hammering row 0
        uniq = jnp.where(jnp.arange(u_pad) < count, uniq.astype(jnp.int32),
                         -1)
        rows, _ovf = self.lookup_unique(uniq, table=table)
        out = rows[inv[:flat.shape[0]]].reshape(tuple(x.shape)
                                                + (self.embedding_dim,))
        return Tensor(out) if isinstance(ids, Tensor) else out

    def extra_repr(self):
        return (f"{self.num_embeddings}, {self.embedding_dim}, "
                f"axis={self.axis!r}, shards={self.n_shards}")


class ShardedTable:
    """Trainer-facing mesh-sharded row store: rows + per-row optimizer
    state on the mesh, host-side residency bookkeeping.

    The device arrays are OWNED BY THE CALLER's jitted step (pass the
    tree in, get the updated tree back, donate for in-place HBM reuse) —
    the ``DeviceEmbeddingCache`` arena discipline, at mesh scale.  The
    ``resident`` set tracks which logical ids currently live in the
    device table (vs. the host PS table / a hot-row cache arena), so
    trainers can split cold misses (host fetch, once per id) from warm
    misses (in-graph all-to-all, zero host row bytes).
    """

    def __init__(self, dim: int, vocab: int, *, optimizer: str = "adagrad",
                 mesh=None, axis: Optional[str] = None,
                 bucket_cap: Optional[int] = None, lr: float = 0.05,
                 eps: float = 1e-8, l1: float = 0.0, l2: float = 0.0,
                 lr_power: float = -0.5):
        from ..distributed.ps.device_cache import DEVICE_RULES
        from ..distributed.ps.table import _STATE_SPEC
        from ..parallel.mesh import get_mesh
        if optimizer not in DEVICE_RULES:
            raise ValueError(
                f"sharded table rule {optimizer!r} not in {DEVICE_RULES}")
        self.mesh = mesh or get_mesh()
        self.axis = axis or sharded_axis()
        self.n_shards = _axis_size(self.mesh, self.axis)
        self.dim = int(dim)
        self.vocab = int(vocab)
        self.rps = _routing.rows_per_shard(vocab, self.n_shards)
        self.opt = optimizer
        self.hyper = dict(lr=lr, eps=eps, l1=l1, l2=l2, lr_power=lr_power)
        self.state_names = tuple(_STATE_SPEC[optimizer])
        self.bucket_cap = (sharded_bucket_cap() if bucket_cap is None
                           else int(bucket_cap))
        self.resident: set = set()
        self._sharding = NamedSharding(self.mesh, P(self.axis, None))

    # -- storage -------------------------------------------------------------
    def init_tree(self) -> Dict:
        rows = _routing.storage_table_rows(self.vocab, self.n_shards)
        z = lambda: jax.device_put(  # noqa: E731
            jnp.zeros((rows, self.dim), jnp.float32), self._sharding)
        return {"rows": z(), "state": {k: z() for k in self.state_names}}

    def _leaves(self, tree):
        return [tree["rows"]] + [tree["state"][k] for k in self.state_names]

    def _tree_of(self, leaves):
        return {"rows": leaves[0],
                "state": dict(zip(self.state_names, leaves[1:]))}

    # -- host-side bookkeeping ----------------------------------------------
    def check_ids(self, ids: np.ndarray) -> None:
        if len(ids) and int(ids.max()) >= self.rps * self.n_shards:
            raise ValueError(
                f"id {int(ids.max())} exceeds the sharded table's row "
                f"space ({self.rps * self.n_shards}; vocab={self.vocab}) "
                f"— raise the table's vocab bound")

    def split_cold_warm(self, ids: np.ndarray):
        """(cold, warm) partition of a miss-id vector by residency."""
        if not len(ids):
            return ids, ids
        res = self.resident
        warm = np.fromiter((int(i) in res for i in ids), bool, len(ids))
        return ids[~warm], ids[warm]

    def cap_for(self, ids: np.ndarray, u: int) -> int:
        """Static routing cap for one padded request vector: per-owner
        host counts picked up to the octave, floored by the flag cap —
        overflow is impossible by construction, so the step never needs a
        D2H overflow fence (``u`` = per-shard slice length bounds it)."""
        from ..distributed.ps.device_cache import pad_adaptive
        need = 1
        if len(ids):
            need = int(np.bincount(ids // self.rps,
                                   minlength=self.n_shards).max())
        cap = max(self.bucket_cap or 0, pad_adaptive(need))
        return int(min(cap, u))

    # -- in-graph entry points (call inside the trainer's jitted step) -------
    def gather(self, tree, ids, cap=None, with_state: bool = True):
        """Routed lookup of rows (and, with ``with_state``, the optimizer
        state planes): ``[U]`` ids (sentinel -1) ->
        (rows [U,D], state {k: [U,D]} | {}, overflow)."""
        leaves = self._leaves(tree) if with_state else [tree["rows"]]
        outs, ovf = _routing.all_to_all_gather(
            leaves, ids, mesh=self.mesh, axis=self.axis,
            rps=self.rps, cap=cap)
        state = dict(zip(self.state_names, outs[1:])) if with_state else {}
        return outs[0], state, ovf

    def set_rows(self, tree, ids, rows, state, cap=None):
        """Routed import of rows + state at their owner shards (victim
        writeback / cold fill); sentinel ids land on scratch."""
        new, _ovf = _routing.all_to_all_set(
            self._leaves(tree), ids,
            [rows] + [state[k] for k in self.state_names],
            mesh=self.mesh, axis=self.axis, rps=self.rps, cap=cap)
        return self._tree_of(new)

    def apply_rule(self, tree, ids, grads, cap=None):
        """Routed sparse-optimizer update: the backward leg — row grads
        route to the owner shard and update ONLY its local slice."""
        new_rows, new_state, _ovf = _routing.all_to_all_apply_rule(
            tree["rows"], dict(tree["state"]), ids, grads, opt=self.opt,
            hyper=self.hyper, mesh=self.mesh, axis=self.axis, rps=self.rps,
            cap=cap)
        return {"rows": new_rows, "state": new_state}

    # -- host data movement --------------------------------------------------
    def host_read(self, tree, ids: np.ndarray):
        """Device gather + D2H of rows (and state) for logical ids — the
        flush/eval read path; no routing (storage_index is global)."""
        idx = jnp.asarray(_routing.storage_index(
            np.asarray(ids, np.int64), self.rps))
        rows = np.asarray(tree["rows"][idx])
        state = {k: np.asarray(tree["state"][k][idx])
                 for k in self.state_names}
        return rows, state

    def host_write(self, tree, ids: np.ndarray, rows, state):
        """Direct (unrouted) H2D import at logical ids — init/prefill."""
        idx = jnp.asarray(_routing.storage_index(
            np.asarray(ids, np.int64), self.rps))
        new = {"rows": tree["rows"].at[idx].set(jnp.asarray(rows)),
               "state": {k: tree["state"][k].at[idx].set(
                   jnp.asarray(state[k])) for k in self.state_names}}
        return new

    def flush_to_client(self, tree, client, table_id: int) -> int:
        """Write every resident row (+state) back to the host PS table —
        the EndPass leg for the mesh-resident tail.  Returns row count."""
        ids = np.fromiter(self.resident, np.int64, len(self.resident))
        if not len(ids):
            return 0
        rows, state = self.host_read(tree, ids)
        client.import_rows(table_id, ids, rows, state)
        return len(ids)


class ShardedWideDeep(nn.Layer):
    """Dense Wide&Deep CTR core over a :class:`ShardedEmbedding` deep leg
    — the generic-autodiff tier: one ``TrainStep`` carries the routed
    lookup, the dense MLP, and the table update (as a dense sharded
    gradient) in a single SPMD program.  This is the HLO-audit / bench /
    autoshard seat; the production trainers use the manual sparse-update
    path instead (``WideDeepTrainer`` + ``ShardedTable``).

    ``forward(ids, dense_x)`` -> logits; with ``labels`` -> mean BCE loss.
    """

    def __init__(self, vocab: int = 4096, emb_dim: int = 16,
                 num_slots: int = 26, dense_dim: int = 13,
                 hidden=(64, 32), *, mesh=None, axis: Optional[str] = None):
        super().__init__()
        self.num_slots = int(num_slots)
        self.deep_emb = ShardedEmbedding(vocab, emb_dim, mesh=mesh,
                                         axis=axis)
        layers = []
        in_dim = num_slots * emb_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.dnn = nn.Sequential(*layers)
        self.wide_dense = nn.Linear(dense_dim, 1)

    def forward(self, sparse_ids, dense_x, labels=None):
        from .. import ops
        from .wide_deep import bce_with_logits_mean
        deep = self.deep_emb(sparse_ids)
        deep_in = deep.reshape([deep.shape[0], -1])
        logits = self.dnn(ops.concat([deep_in, dense_x], axis=-1)) \
            + self.wide_dense(dense_x)
        if labels is None:
            return logits
        from ..framework.tensor import Tensor
        lab = labels._value if isinstance(labels, Tensor) else labels
        lg = logits._value if isinstance(logits, Tensor) else logits
        loss = bce_with_logits_mean(lg, lab)
        return Tensor(loss) if isinstance(logits, Tensor) else loss
