"""Wide&Deep CTR model over parameter-server sparse embeddings.

Reference parity: BASELINE workload 5 — the DistributedStrategy + sparse
embedding CTR configuration the reference serves with its PS stack
(fluid.layers.embedding(is_sparse=True, is_distributed=True) pulled through
lookup_sparse_table / parameter_prefetch).  Model shape follows the classic
Wide&Deep CTR recipe: a wide linear part over the raw sparse slots plus a
deep MLP over slot embeddings and dense features.

TPU-first: the sparse side is two host tables (dim-1 wide weights, dim-D
deep embeddings) behind DistributedEmbedding; everything dense — gathers,
MLP, loss, backward — is on-chip.  The trainer drives pull → dense step →
push per batch (the HeterPS loop).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import nn, optimizer as opt_mod
from ..framework.tensor import Tensor
from ..distributed.ps import DistributedEmbedding, LocalPsEndpoint


class WideDeep(nn.Layer):
    def __init__(self, client=None, emb_dim: int = 16, num_slots: int = 26,
                 dense_dim: int = 13, hidden=(400, 400, 400),
                 sparse_lr: float = 0.05, sparse_optimizer: str = "adagrad",
                 **table_kw):
        super().__init__()
        client = client or LocalPsEndpoint()
        self.client = client
        self.num_slots = num_slots
        self.wide_emb = DistributedEmbedding(client, table_id=0, dim=1,
                                             optimizer=sparse_optimizer,
                                             lr=sparse_lr, **table_kw)
        self.deep_emb = DistributedEmbedding(client, table_id=1, dim=emb_dim,
                                             optimizer=sparse_optimizer,
                                             lr=sparse_lr, **table_kw)
        layers = []
        in_dim = num_slots * emb_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.dnn = nn.Sequential(*layers)
        self.wide_dense = nn.Linear(dense_dim, 1)

    def forward(self, sparse_ids, dense_x):
        # wide: sum of per-slot scalar weights + linear over dense feats
        wide = self.wide_emb(sparse_ids).squeeze(-1).sum(axis=-1,
                                                         keepdim=True)
        wide = wide + self.wide_dense(dense_x)
        # deep: slot embeddings concat dense feats -> MLP
        deep_in = self.deep_emb(sparse_ids).reshape(
            [sparse_ids.shape[0], -1])
        from .. import ops
        deep = self.dnn(ops.concat([deep_in, dense_x], axis=-1))
        return wide + deep

    def flush_sparse_grads(self):
        self.wide_emb.flush_grads()
        self.deep_emb.flush_grads()


class WideDeepTrainer:
    """pull → ONE-JIT dense fwd/bwd/Adam → push (the PS train loop that
    the reference's Communicator+DeviceWorker pair runs, communicator.h:195).

    The whole dense side — wide sum, MLP, BCE loss, backward, Adam update,
    and the gradients w.r.t. the pulled embedding rows — is a single
    compiled XLA program per step: three host↔device transfers total
    (pulled rows in, row grads out, loss out) instead of per-op eager
    dispatch, which is the difference between latency-bound and
    compute-bound on a remote chip."""

    def __init__(self, model: WideDeep, lr: float = 1e-3,
                 async_push: bool = False):
        import jax
        from ..framework import functional as F
        self.model = model
        self.lr = float(lr)
        # a_sync communicator parity (communicator.h AsyncCommunicator):
        # sparse pushes (incl. the D2H grad read) drain on a background
        # thread, overlapping the next step's pull+compute; embeddings may
        # be read one step stale, and a failed push surfaces on the NEXT
        # step()/flush() — inherent to async mode, as in the reference.
        self._async_push = bool(async_push)
        self._push_queue = None
        self._push_thread = None
        self._push_err = []
        if self._async_push:
            import queue as queue_mod
            import threading
            self._push_queue = queue_mod.Queue(maxsize=4)
            # the closure captures only the queue + error list (NOT self):
            # the trainer must stay collectable; close() retires the thread
            q, errs = self._push_queue, self._push_err

            def drain():
                while True:
                    item = q.get()
                    try:
                        if item is None:
                            return
                        # one item = one step's pushes for BOTH tables, so
                        # a step's sparse updates apply atomically wrt
                        # flush boundaries; D2H happens here, off the
                        # trainer thread
                        for emb, uniq, grads_dev, n in item:
                            emb.client.push_sparse(
                                emb.table_id, uniq,
                                np.asarray(grads_dev)[:n])
                    except Exception as e:
                        errs.append(e)
                    finally:
                        q.task_done()

            self._push_thread = threading.Thread(target=drain, daemon=True)
            self._push_thread.start()

        core = _DenseCore(model)
        apply, params, buffers = F.functionalize(core, training=True)
        self._params = params
        self._buffers = buffers
        self._adam = {  # functional Adam state
            "m": {k: jnp.zeros_like(v) for k, v in params.items()},
            "v": {k: jnp.zeros_like(v) for k, v in params.items()},
            "t": jnp.zeros((), jnp.int32),
        }
        b1, b2, eps = 0.9, 0.999, 1e-8
        lr_ = self.lr

        def fused(params, adam, wide_rows, deep_rows, wide_inv, deep_inv,
                  dense_x, labels):
            def loss_of(p, wr, dr):
                out = apply(p, buffers, wr, dr, wide_inv, deep_inv,
                            dense_x)
                x = out[0] if isinstance(out, tuple) else out
                # BCE-with-logits, numerically stable
                l = jnp.maximum(x, 0) - x * labels + \
                    jnp.log1p(jnp.exp(-jnp.abs(x)))
                return jnp.mean(l)

            (loss), grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
                params, wide_rows, deep_rows)
            gp, gw, gd = grads
            t = adam["t"] + 1
            tf = t.astype(jnp.float32)
            corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
            new_m = {k: b1 * adam["m"][k] + (1 - b1) * gp[k] for k in gp}
            new_v = {k: b2 * adam["v"][k] + (1 - b2) * gp[k] ** 2
                     for k in gp}
            new_p = {k: params[k] - lr_ * corr * new_m[k] /
                     (jnp.sqrt(new_v[k]) + eps) for k in gp}
            return new_p, {"m": new_m, "v": new_v, "t": t}, loss, gw, gd

        self._fused = jax.jit(fused)

    def _raise_push_errors(self):
        if self._push_err:
            errs = list(self._push_err)
            del self._push_err[:]
            raise errs[0]

    def _push_both(self, we, de, uniq, gw, gd):
        n = len(uniq)
        if self._async_push:
            self._push_queue.put(((we, uniq, gw, n), (de, uniq, gd, n)))
        else:
            we.client.push_sparse(we.table_id, uniq, np.asarray(gw)[:n])
            de.client.push_sparse(de.table_id, uniq, np.asarray(gd)[:n])

    def close(self):
        """Retire the drain thread (idempotent)."""
        if self._push_thread is not None:
            self._push_queue.put(None)
            self._push_thread.join(timeout=5)
            self._push_thread = None

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    def step(self, sparse_ids, dense_x, labels) -> float:
        if self._async_push:
            # surface background push failures BEFORE advancing dense
            # state for this batch
            self._raise_push_errors()
        ids = np.asarray(sparse_ids)
        we, de = self.model.wide_emb, self.model.deep_emb
        # one unique/inverse shared by both tables (same id space)
        uniq, inv = np.unique(ids, return_inverse=True)
        w_rows = jnp.asarray(we.pull_padded_rows(uniq))
        d_rows = jnp.asarray(de.pull_padded_rows(uniq))
        inv_dev = jnp.asarray(inv.reshape(ids.shape), jnp.int32)
        self._params, self._adam, loss, gw, gd = self._fused(
            self._params, self._adam, w_rows, d_rows, inv_dev, inv_dev,
            jnp.asarray(dense_x), jnp.asarray(labels))
        self._push_both(we, de, uniq, gw, gd)
        # keep the eager model in sync: rebinding _value to the updated
        # device arrays is a pointer swap (no transfer), so eval /
        # state_dict always see the trained weights
        self.sync_params()
        return float(loss)

    def flush(self):
        """Drain pending async pushes (barrier before eval/save)."""
        if self._push_queue is not None:
            self._push_queue.join()
        self._raise_push_errors()

    def sync_params(self):
        """Point the eager model's dense params at the jit-updated device
        arrays (free — same buffers, no copy)."""
        if not hasattr(self, "_name_map"):
            core = _DenseCore(self.model)
            self._name_map = [(n, p) for n, p in core.named_parameters()
                              if n in self._params]
        for name, p in self._name_map:
            p._value = self._params[name]


class _DenseCore(nn.Layer):
    """The dense compute of WideDeep as a pure layer over pulled rows:
    (wide_rows [U1,1], deep_rows [U2,D], wide_inv [B,S], deep_inv [B,S],
    dense_x [B,F]) -> logits [B,1]."""

    def __init__(self, wd: WideDeep):
        super().__init__()
        self.dnn = wd.dnn
        self.wide_dense = wd.wide_dense
        self._emb_dim = wd.deep_emb.dim

    def forward(self, wide_rows, deep_rows, wide_inv, deep_inv, dense_x):
        from .. import ops
        from ..nn import functional as F
        wide_g = F.embedding(wide_inv, wide_rows)      # [B, S, 1]
        wide = wide_g.squeeze(-1).sum(axis=-1, keepdim=True) + \
            self.wide_dense(dense_x)
        deep_g = F.embedding(deep_inv, deep_rows)      # [B, S, D]
        deep_in = deep_g.reshape([deep_g.shape[0], -1])
        deep = self.dnn(ops.concat([deep_in, dense_x], axis=-1))
        return wide + deep




def synthetic_ctr_batch(batch: int, num_slots: int = 26, dense_dim: int = 13,
                        vocab: int = 1_000_000, seed: int = 0):
    """Criteo-shaped synthetic batch: 26 categorical slots (slot-offset id
    space), 13 dense features, clicked/not label correlated with features."""
    rng = np.random.RandomState(seed)
    # power-lawish ids per slot, offset so slots never collide
    ids = (rng.zipf(1.5, size=(batch, num_slots)) % (vocab // num_slots))
    ids = ids + np.arange(num_slots) * (vocab // num_slots)
    dense = rng.standard_normal((batch, dense_dim)).astype(np.float32)
    logit = 0.5 * dense[:, 0] - 0.3 * dense[:, 1] + \
        0.1 * (ids[:, 0] % 7 - 3)
    label = (logit + rng.standard_normal(batch) >
             0).astype(np.float32)[:, None]
    return ids.astype(np.int64), dense, label

def write_ctr_files(dirname, n_examples, n_files=4, num_slots: int = 26,
                    dense_dim: int = 13, vocab: int = 1_000_000, seed=0):
    """Write synthetic CTR data as MultiSlot text files (data_feed.proto
    format): 26 single-id sparse slots, one dense slot, one label slot.
    Returns the filelist."""
    import os
    os.makedirs(dirname, exist_ok=True)
    per = n_examples // n_files
    files = []
    for fi in range(n_files):
        ids, dense, label = synthetic_ctr_batch(per, num_slots, dense_dim,
                                                vocab, seed=seed + fi)
        path = os.path.join(dirname, f"ctr_{fi:03d}.txt")
        with open(path, "w") as f:
            for r in range(per):
                parts = [f"1 {ids[r, s]}" for s in range(num_slots)]
                parts.append(f"{dense_dim} " +
                             " ".join(f"{v:.5f}" for v in dense[r]))
                parts.append(f"1 {int(label[r, 0])}")
                f.write(" ".join(parts) + "\n")
        files.append(path)
    return files


def ctr_dataset(filelist, batch_size, num_slots: int = 26,
                dense_dim: int = 13, kind="InMemoryDataset"):
    """An InMemoryDataset/QueueDataset over CTR MultiSlot files, slot
    schema matching write_ctr_files."""
    from ..distributed.dataset import InMemoryDataset, QueueDataset
    ds = (InMemoryDataset if kind == "InMemoryDataset" else QueueDataset)()
    ds.init(batch_size=batch_size, thread_num=4)
    slots = [{"name": f"C{s}", "type": "uint64"} for s in range(num_slots)]
    slots.append({"name": "dense", "type": "float", "is_dense": True,
                  "shape": (dense_dim,)})
    slots.append({"name": "label", "type": "uint64"})
    ds.set_slots(slots)
    ds.set_filelist(list(filelist))
    return ds


def batch_from_feed(feed, num_slots: int = 26):
    """Compose a dataset feed dict into (ids, dense, label) trainer arrays."""
    ids = np.concatenate([feed[f"C{s}"] for s in range(num_slots)], axis=1)
    dense = feed["dense"].astype(np.float32)
    label = feed["label"].astype(np.float32)
    return ids.astype(np.int64), dense, label
