"""Wide&Deep CTR model over parameter-server sparse embeddings.

Reference parity: BASELINE workload 5 — the DistributedStrategy + sparse
embedding CTR configuration the reference serves with its PS stack
(fluid.layers.embedding(is_sparse=True, is_distributed=True) pulled through
lookup_sparse_table / parameter_prefetch).  Model shape follows the classic
Wide&Deep CTR recipe: a wide linear part over the raw sparse slots plus a
deep MLP over slot embeddings and dense features.

TPU-first: the sparse side is two host tables (dim-1 wide weights, dim-D
deep embeddings) behind DistributedEmbedding; everything dense — gathers,
MLP, loss, backward — is on-chip.  The trainer drives pull → dense step →
push per batch (the HeterPS loop).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import nn, optimizer as opt_mod
from ..framework.tensor import Tensor
from ..distributed.ps import DistributedEmbedding, LocalPsEndpoint
from ..profiler.metrics import default_registry as _registry

# storage-tier attribution for the cached/sharded embedding step: every
# deduped id is served by exactly one tier — the hot-row cache arena
# (hit, zero routing), the mesh table (warm miss, in-graph all-to-all),
# or the host PS (cold miss, one-time host fetch).  Counting ids per
# tier is what makes cache-hit claims auditable from /metrics.
_TIER_HITS = _registry().counter(
    "wide_deep_tier_hits_total",
    "Deduped embedding ids served per storage tier (cache_arena / "
    "mesh_table / host_ps) by the Wide&Deep cached and sharded steps.",
    labels=("tier",))


class WideDeep(nn.Layer):
    def __init__(self, client=None, emb_dim: int = 16, num_slots: int = 26,
                 dense_dim: int = 13, hidden=(400, 400, 400),
                 sparse_lr: float = 0.05, sparse_optimizer: str = "adagrad",
                 **table_kw):
        super().__init__()
        client = client or LocalPsEndpoint()
        self.client = client
        self.num_slots = num_slots
        self.wide_emb = DistributedEmbedding(client, table_id=0, dim=1,
                                             optimizer=sparse_optimizer,
                                             lr=sparse_lr, **table_kw)
        self.deep_emb = DistributedEmbedding(client, table_id=1, dim=emb_dim,
                                             optimizer=sparse_optimizer,
                                             lr=sparse_lr, **table_kw)
        layers = []
        in_dim = num_slots * emb_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.dnn = nn.Sequential(*layers)
        self.wide_dense = nn.Linear(dense_dim, 1)

    def forward(self, sparse_ids, dense_x):
        # wide: sum of per-slot scalar weights + linear over dense feats
        wide = self.wide_emb(sparse_ids).squeeze(-1).sum(axis=-1,
                                                         keepdim=True)
        wide = wide + self.wide_dense(dense_x)
        # deep: slot embeddings concat dense feats -> MLP
        deep_in = self.deep_emb(sparse_ids).reshape(
            [sparse_ids.shape[0], -1])
        from .. import ops
        deep = self.dnn(ops.concat([deep_in, dense_x], axis=-1))
        return wide + deep

    def flush_sparse_grads(self):
        self.wide_emb.flush_grads()
        self.deep_emb.flush_grads()


def sort_unique_static(ids_flat, cap):
    """Static-shape sort-based unique on DEVICE (the XLA replacement for
    the host np.unique every cached-mode step pays over the full B*S id
    block): sort, boundary flags, segment ids by cumsum, then one
    segment-sum for per-unique occurrence counts.

    Returns ``(uniq [cap], inv [N], count, counts [cap])`` — ``uniq`` is
    sorted-unique padded to the static ``cap`` (padding untouched beyond
    ``count``; compare count host-side and re-run at a bigger octave when
    it overflows), ``inv`` maps each input position to its unique slot
    exactly like ``np.unique(return_inverse=True)`` (np.unique also
    sorts, so the two paths produce bit-identical gathers), and
    ``counts`` is the segment-sum occupancy histogram (hot-id stats /
    dedup ratio gauges)."""
    import jax
    order = jnp.argsort(ids_flat)
    s = ids_flat[order]
    flags = jnp.concatenate([jnp.ones((1,), jnp.int32),
                             (s[1:] != s[:-1]).astype(jnp.int32)])
    seg = jnp.cumsum(flags) - 1                   # unique index, sorted order
    count = seg[-1] + 1
    uniq = jnp.zeros((cap,), s.dtype).at[jnp.clip(seg, 0, cap - 1)].set(s)
    inv = jnp.zeros_like(seg).at[order].set(seg)
    counts = jax.ops.segment_sum(jnp.ones_like(seg), seg,
                                 num_segments=cap)
    return uniq, inv, count, counts


def bce_with_logits_mean(x, labels):
    """Numerically stable mean BCE-with-logits (shared by the CTR
    trainers)."""
    l = jnp.maximum(x, 0) - x * labels + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return jnp.mean(l)


def make_adam_update(lr, b1=0.9, b2=0.999, eps=1e-8):
    """Functional Adam over a {name: array} tree with bias correction —
    the dense-side update both CTR trainers jit into their step."""
    def adam_update(params, adam, gp):
        t = adam["t"] + 1
        tf = t.astype(jnp.float32)
        corr = jnp.sqrt(1 - b2 ** tf) / (1 - b1 ** tf)
        new_m = {k: b1 * adam["m"][k] + (1 - b1) * gp[k] for k in gp}
        new_v = {k: b2 * adam["v"][k] + (1 - b2) * gp[k] ** 2 for k in gp}
        new_p = {k: params[k] - lr * corr * new_m[k] /
                 (jnp.sqrt(new_v[k]) + eps) for k in gp}
        return new_p, {"m": new_m, "v": new_v, "t": t}
    return adam_update


class WideDeepTrainer:
    """The PS CTR train loop at two service levels:

    **device-cache mode** (default when the sparse rule runs on-chip and the
    client supports export/import_rows): the HeterPS/PSGPU design
    (framework/fleet/ps_gpu_wrapper.h, trainer.h:281 PSGPUTrainer) — hot
    embedding rows and their optimizer state live in device HBM arenas
    (DeviceEmbeddingCache); per step the host ships only batch INDICES plus
    the miss block, and one jitted XLA program gathers rows, runs dense
    fwd/bwd/Adam, and applies the sparse rule on-chip.  Steady state moves
    zero row bytes over the wire, and ``step_async`` keeps the device queue
    full (host prepares batch N+1 while the chip runs batch N).

    **pull/push mode** (fallback; ``device_cache=False`` or a table rule
    the chip can't run): pull → ONE-JIT dense fwd/bwd/Adam → push, the
    Communicator+DeviceWorker loop (communicator.h:195) with three
    host↔device transfers per step.

    Cache-mode contracts:
    - Host tables hold stale rows until ``flush()`` (PSGPU EndPass
      semantics); eager ``model(...)`` eval stays correct anyway — the
      embeddings read THROUGH the cache while one is bound.
    - ``feature_wire_dtype`` ("float32" default — bit-identical numerics
      with pull/push mode) is the H2D dtype for dense features.  Pass
      "bfloat16" to halve the hot-path wire bytes (standard for
      normalized CTR features; bench.py opts in explicitly).  Labels
      always travel f32."""

    def __init__(self, model: WideDeep, lr: float = 1e-3,
                 async_push: bool = False, device_cache: bool = None,
                 cache_capacity: int = 1 << 20,
                 feature_wire_dtype="float32",
                 sharded_embedding: bool = None,
                 sharded_vocab: int = None, mesh=None):
        import jax
        from ..framework import functional as F
        from ..framework.flags import flag as _flag
        from ..distributed.ps.device_cache import (
            DeviceEmbeddingCache, SlotDirectory, DEVICE_RULES,
            apply_rule_device, pad_adaptive)
        self.model = model
        self.lr = float(lr)
        # a_sync communicator parity (communicator.h AsyncCommunicator):
        # sparse pushes (incl. the D2H grad read) drain on a background
        # thread, overlapping the next step's pull+compute; embeddings may
        # be read one step stale, and a failed push surfaces on the NEXT
        # step()/flush() — inherent to async mode, as in the reference.
        self._async_push = bool(async_push)
        self._push_queue = None
        self._push_thread = None
        self._push_err = []
        if self._async_push:
            import queue as queue_mod
            import threading
            self._push_queue = queue_mod.Queue(maxsize=4)
            # the closure captures only the queue + error list (NOT self):
            # the trainer must stay collectable; close() retires the thread
            q, errs = self._push_queue, self._push_err

            def drain():
                while True:
                    item = q.get()
                    try:
                        if item is None:
                            return
                        # one item = one step's pushes for BOTH tables, so
                        # a step's sparse updates apply atomically wrt
                        # flush boundaries; D2H happens here, off the
                        # trainer thread
                        for emb, uniq, grads_dev, n in item:
                            emb.client.push_sparse(
                                emb.table_id, uniq,
                                np.asarray(grads_dev)[:n])
                    except Exception as e:
                        errs.append(e)
                    finally:
                        q.task_done()

            self._push_thread = threading.Thread(target=drain, daemon=True)
            self._push_thread.start()

        core = _DenseCore(model)
        apply, params, buffers = F.functionalize(core, training=True)
        self._params = params
        self._buffers = buffers
        self._adam = {  # functional Adam state
            "m": {k: jnp.zeros_like(v) for k, v in params.items()},
            "v": {k: jnp.zeros_like(v) for k, v in params.items()},
            "t": jnp.zeros((), jnp.int32),
        }
        bce_mean = bce_with_logits_mean
        adam_update = make_adam_update(self.lr)

        def fused(params, adam, wide_rows, deep_rows, wide_inv, deep_inv,
                  dense_x, labels):
            def loss_of(p, wr, dr):
                out = apply(p, buffers, wr, dr, wide_inv, deep_inv,
                            dense_x)
                x = out[0] if isinstance(out, tuple) else out
                return bce_mean(x, labels)

            (loss), grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2))(
                params, wide_rows, deep_rows)
            gp, gw, gd = grads
            new_p, new_adam = adam_update(params, adam, gp)
            return new_p, new_adam, loss, gw, gd

        self._fused = jax.jit(fused)

        # -- device-cache mode (HeterPS/PSGPU) -------------------------------
        we, de = model.wide_emb, model.deep_emb
        can_cache = (we.optimizer in DEVICE_RULES and
                     hasattr(model.client, "export_rows"))
        if device_cache is None:
            # async_push explicitly asks for the a_sync pull/push contract
            # (host tables at most one step stale) — honor it over the cache
            device_cache = can_cache and not self._async_push
        elif device_cache and not can_cache:
            raise ValueError(
                f"device_cache: rule {we.optimizer!r} must be in "
                f"{DEVICE_RULES} and the client needs export/import_rows")
        elif device_cache and self._async_push:
            raise ValueError(
                "device_cache and async_push are mutually exclusive: the "
                "cache applies sparse updates on-chip (no pushes to drain) "
                "and host tables stay stale until flush()")
        self._use_cache = bool(device_cache)
        if self._use_cache:
            self._pad_adaptive = pad_adaptive
            self._feature_wire_dtype = (
                jnp.bfloat16 if str(feature_wire_dtype) in
                ("bfloat16", "bf16") else np.float32)
            # ONE slot directory: both tables share the id space, so ids
            # resolve to slots once per step
            self._slot_dir = SlotDirectory(cache_capacity)
            # device-dedup state (FLAGS_wide_deep_device_dedup): static-
            # shape octave cap + one jitted sort_unique_static per shape
            self._dedup_cap = None
            self._dedup_fns = {}

            def mk_cache(emb):
                kw = {k: v for k, v in emb.table_kw.items()
                      if k in ("eps", "l1", "l2", "lr_power")}
                return DeviceEmbeddingCache(
                    model.client, emb.table_id, emb.dim,
                    optimizer=emb.optimizer, lr=emb.lr,
                    directory=self._slot_dir, **kw)
            self._w_cache, self._d_cache = mk_cache(we), mk_cache(de)
            self._w_ar = self._w_cache.init_arenas()
            self._d_ar = self._d_cache.init_arenas()
            # eager eval reads THROUGH the cache (host tables are stale
            # until flush — PSGPU EndPass semantics)
            we._cache_read = lambda u: self._w_cache.read_rows(u, self._w_ar)
            de._cache_read = lambda u: self._d_cache.read_rows(u, self._d_ar)

            def scatter_miss(ar, slots, rows, state):
                return {"rows": ar["rows"].at[slots].set(rows),
                        "state": {k: ar["state"][k].at[slots].set(state[k])
                                  for k in ar["state"]}}
            self._scatter = jax.jit(scatter_miss, donate_argnums=(0,))

            opt_name = we.optimizer
            hy_w, hy_d = self._w_cache.hyper, self._d_cache.hyper

            def rule_and_scatter(ar, slots, rows, grads, hyper):
                st = {k: ar["state"][k][slots] for k in ar["state"]}
                new_rows, new_st = apply_rule_device(
                    opt_name, rows, st, grads, **hyper)
                return {"rows": ar["rows"].at[slots].set(new_rows),
                        "state": {k: ar["state"][k].at[slots].set(new_st[k])
                                  for k in ar["state"]}}

            def fused_cached(params, adam, w_ar, d_ar, slots_w, slots_d,
                             inv, dense_x, labels):
                inv32 = inv.astype(jnp.int32)
                dense32 = dense_x.astype(jnp.float32)
                lab32 = labels.astype(jnp.float32)
                w_rows = w_ar["rows"][slots_w]
                d_rows = d_ar["rows"][slots_d]

                def loss_of(p, wr, dr):
                    out = apply(p, buffers, wr, dr, inv32, inv32, dense32)
                    x = out[0] if isinstance(out, tuple) else out
                    return bce_mean(x, lab32)

                (loss), grads = jax.value_and_grad(
                    loss_of, argnums=(0, 1, 2))(params, w_rows, d_rows)
                gp, gw, gd = grads
                new_p, new_adam = adam_update(params, adam, gp)
                w_ar = rule_and_scatter(w_ar, slots_w, w_rows, gw, hy_w)
                d_ar = rule_and_scatter(d_ar, slots_d, d_rows, gd, hy_d)
                return new_p, new_adam, w_ar, d_ar, loss

            # raw (unjitted) body kept for the in-graph chained-K probe
            self._fused_cached_raw = fused_cached
            self._fused_cached = jax.jit(fused_cached,
                                         donate_argnums=(0, 1, 2, 3))

        # -- mesh-sharded deep table (FLAGS_sharded_embedding) ---------------
        # The HeterPS hashtable seat done TPU-style: the deep-leg table is
        # row-partitioned over a mesh axis; the hot-row cache arena keeps
        # the skewed head replicated (zero routing for hits), warm misses
        # route via lax.all_to_all INSIDE the jitted step (zero host row
        # bytes), and only cold ids (first sighting) pay a host PS fetch.
        # Off-path = this one branch; the replicated path is unchanged.
        self._sharded = (bool(_flag("sharded_embedding"))
                         if sharded_embedding is None
                         else bool(sharded_embedding))
        if self._sharded and not self._use_cache:
            raise ValueError(
                "FLAGS_sharded_embedding composes with device-cache mode "
                "only (the hot-row arena is the short-circuit for the "
                "skewed head); pull/push + sharded tables is the "
                "HeterTrainer seat")
        if self._sharded:
            if sharded_vocab is None:
                raise ValueError(
                    "sharded embedding mode needs sharded_vocab: the id "
                    "bound sizing the mesh-partitioned deep table")
            from jax.sharding import NamedSharding, PartitionSpec as P
            from .sharded_embedding import ShardedTable
            de = model.deep_emb
            kw = {k: v for k, v in de.table_kw.items()
                  if k in ("eps", "l1", "l2", "lr_power")}
            self._dtab = ShardedTable(de.dim, sharded_vocab,
                                      optimizer=de.optimizer, lr=de.lr,
                                      mesh=mesh, **kw)
            self._dtab_tree = self._dtab.init_tree()
            # one jitted program must see consistently-placed operands:
            # dense state + arenas replicate onto the table's mesh
            self._rep_sh = NamedSharding(self._dtab.mesh, P())
            rep_put = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda v: jax.device_put(v, self._rep_sh), t)
            self._params = rep_put(self._params)
            self._adam = rep_put(self._adam)
            self._w_ar = rep_put(self._w_ar)
            self._d_ar = rep_put(self._d_ar)
            self._sharded_fns = {}       # (shape/cap key) -> jitted step
            de._cache_read = self._sharded_read
            dtab = self._dtab

            def make_sharded_fused(cap_v, cap_w):
                """One compiled sharded step per (padded-shape, cap)
                signature — caps are static routing-buffer bounds, octave
                -laddered host-side so the compile count stays bounded."""
                def fused(params, adam, w_ar, d_ar, dtree, slots_w,
                          slots_d, inv, dense_x, labels, vic_ids,
                          vic_slots, warm_ids, warm_slots, cold_slots,
                          cold_rows, cold_state):
                    inv32 = inv.astype(jnp.int32)
                    dense32 = dense_x.astype(jnp.float32)
                    lab32 = labels.astype(jnp.float32)
                    # 1. victims: arena -> sharded table (routed SET; the
                    # arena reads precede every arena scatter this step)
                    vrows = d_ar["rows"][vic_slots]
                    vstate = {k: d_ar["state"][k][vic_slots]
                              for k in d_ar["state"]}
                    dtree = dtab.set_rows(dtree, vic_ids, vrows, vstate,
                                          cap=cap_v)
                    # 2. cold misses (first sighting, host-fetched rows)
                    d_ar = {"rows": d_ar["rows"].at[cold_slots].set(
                                cold_rows),
                            "state": {k: d_ar["state"][k].at[
                                cold_slots].set(cold_state[k])
                                for k in d_ar["state"]}}
                    # 3. warm misses: routed all-to-all fetch, table ->
                    # arena — the steady-state tail traffic; the cached
                    # head never reaches this exchange
                    wrows, wstate, _ovf = dtab.gather(dtree, warm_ids,
                                                      cap=cap_w)
                    d_ar = {"rows": d_ar["rows"].at[warm_slots].set(
                                wrows),
                            "state": {k: d_ar["state"][k].at[
                                warm_slots].set(wstate[k])
                                for k in d_ar["state"]}}
                    # 4. dense fwd/bwd + on-chip sparse rule (the
                    # fused_cached body, unchanged numerics)
                    w_rows = w_ar["rows"][slots_w]
                    d_rows = d_ar["rows"][slots_d]

                    def loss_of(p, wr, dr):
                        out = apply(p, buffers, wr, dr, inv32, inv32,
                                    dense32)
                        x = out[0] if isinstance(out, tuple) else out
                        return bce_mean(x, lab32)

                    (loss), grads = jax.value_and_grad(
                        loss_of, argnums=(0, 1, 2))(params, w_rows,
                                                    d_rows)
                    gp, gw, gd = grads
                    new_p, new_adam = adam_update(params, adam, gp)
                    w_ar = rule_and_scatter(w_ar, slots_w, w_rows, gw,
                                            hy_w)
                    d_ar = rule_and_scatter(d_ar, slots_d, d_rows, gd,
                                            hy_d)
                    return new_p, new_adam, w_ar, d_ar, dtree, loss
                return fused

            self._make_sharded_fused = make_sharded_fused

    def _raise_push_errors(self):
        if self._push_err:
            errs = list(self._push_err)
            del self._push_err[:]
            raise errs[0]

    def _push_both(self, we, de, uniq, gw, gd):
        n = len(uniq)
        if self._async_push:
            self._push_queue.put(((we, uniq, gw, n), (de, uniq, gd, n)))
        else:
            we.client.push_sparse(we.table_id, uniq, np.asarray(gw)[:n])
            de.client.push_sparse(de.table_id, uniq, np.asarray(gd)[:n])

    def close(self):
        """Retire the drain thread (idempotent)."""
        if self._push_thread is not None:
            self._push_queue.put(None)
            self._push_thread.join(timeout=5)
            self._push_thread = None

    def __del__(self):  # pragma: no cover — best-effort cleanup
        try:
            self.close()
        except Exception:
            pass

    def step(self, sparse_ids, dense_x, labels) -> float:
        return float(self.step_async(sparse_ids, dense_x, labels))

    def step_async(self, sparse_ids, dense_x, labels):
        """One train step WITHOUT fencing on the loss: returns the device
        scalar so the host can prepare batch N+1 while the chip runs batch
        N (jax async dispatch is the pipeline).  Fence with float(loss) or
        flush()."""
        if self._use_cache:
            return self._step_cached(sparse_ids, dense_x, labels)
        return self._step_pullpush(sparse_ids, dense_x, labels)

    def _dedup_device(self, ids):
        """Sort-based unique + segment-sum on DEVICE (VERDICT #5 relief,
        FLAGS_wide_deep_device_dedup): the chip dedups the B*S id block at
        a static octave cap; the host reads back only the deduped prefix
        (plus one count scalar) for hot-row-cache slot resolution, instead
        of running np.unique over the full block every step.  Cap
        overflow re-runs one octave up (compile count stays bounded by
        the octave ladder).  Returns (uniq np [count], inv device [B,S
        flat])."""
        import functools
        import jax
        flat = jnp.asarray(ids.reshape(-1))
        n = flat.size
        if self._dedup_cap is None:
            # seed the octave from a one-time host count
            u0 = len(np.unique(ids))
            self._dedup_cap = self._pad_adaptive(min(max(2 * u0, 16), n))
        while True:
            cap = min(self._dedup_cap, n)
            fn = self._dedup_fns.get((n, cap))
            if fn is None:
                fn = jax.jit(functools.partial(sort_unique_static, cap=cap))
                self._dedup_fns[(n, cap)] = fn
            uniq_dev, inv_dev, count_dev, _counts = fn(flat)
            count = int(count_dev)           # one scalar D2H
            if count <= cap or cap >= n:
                break
            # overflow: grow to the octave holding count (strictly > cap)
            self._dedup_cap = self._pad_adaptive(min(count, n))
        return np.asarray(uniq_dev[:count]), inv_dev

    def _prep_cached(self, sparse_ids):
        """Host side of a cached-mode step: id dedup, slot resolution,
        miss fill/scatter, octave-padded slot vector, wire-compressed
        inverse map.  Returns device (slots, inv)."""
        from ..framework.flags import flag
        ids = np.asarray(sparse_ids)
        if flag("wide_deep_device_dedup"):
            # np.unique also sorts, so both paths produce identical
            # (uniq, inv) and the step numerics are bit-identical
            uniq, inv = self._dedup_device(ids)
        else:
            uniq, inv = np.unique(ids, return_inverse=True)
        # ONE id→slot resolution for both tables, then per-table row moves.
        # A failure before the miss rows land in BOTH arenas rolls the
        # resolution back, so a retried step re-misses instead of hitting
        # never-filled slots (the victims fill() already wrote back stay in
        # the host table — consistent either way).
        res = self._slot_dir.resolve(uniq)
        try:
            mw_slots, mw_rows, mw_state = self._w_cache.fill(res, self._w_ar)
            md_slots, md_rows, md_state = self._d_cache.fill(res, self._d_ar)
        except Exception:
            # rollback is only valid pre-scatter (arenas untouched); a
            # fill failure is cleanly retryable
            self._slot_dir.rollback(res)
            raise
        if mw_slots is not None:
            self._w_ar = self._scatter(
                self._w_ar, jnp.asarray(mw_slots), jnp.asarray(mw_rows),
                {k: jnp.asarray(v) for k, v in mw_state.items()})
        if md_slots is not None:
            self._d_ar = self._scatter(
                self._d_ar, jnp.asarray(md_slots), jnp.asarray(md_rows),
                {k: jnp.asarray(v) for k, v in md_state.items()})
        # tier attribution (replicated cached mode has two tiers: the
        # arena for hits, the host PS for every miss)
        n_miss = len(res.miss_idx)
        if len(uniq) - n_miss:
            _TIER_HITS.labels(tier="cache_arena").inc(len(uniq) - n_miss)
        if n_miss:
            _TIER_HITS.labels(tier="host_ps").inc(n_miss)
        # eighth-octave-pad the slot vector (≤8 compiled shapes per
        # doubling of U); padding points at the scratch slot
        u = len(uniq)
        u_pad = self._pad_adaptive(u)
        slots_p = np.full(u_pad, self._slot_dir.cap, np.int32)
        slots_p[:u] = res.slots
        # wire compression: indices uint16 when they fit, features bf16
        inv_w = inv.reshape(ids.shape)
        inv_w = inv_w.astype(np.uint16 if u_pad <= 65536 else np.int32)
        return jnp.asarray(slots_p), jnp.asarray(inv_w)

    # -- mesh-sharded deep leg (FLAGS_sharded_embedding) ----------------------
    def _pad_routed(self, ids, slots, scratch_slot):
        """Pad an (ids, slots) pair for routing: octave length rounded to
        a shard multiple, sentinel -1 ids (the router drops them) and
        scratch arena slots (their scatters land on the arena's spare
        row).  Returns (ids [P] int32, slots [P] int32)."""
        from ..distributed.ps.device_cache import pad_adaptive
        from ..ops.routing import pad_requests
        n = len(ids)
        p = pad_requests(n, self._dtab.n_shards, pad_adaptive)
        out_ids = np.full(p, -1, np.int32)
        out_ids[:n] = ids
        out_slots = np.full(p, scratch_slot, np.int32)
        out_slots[:n] = slots
        return out_ids, out_slots

    def _prep_sharded(self, sparse_ids):
        """Host side of a sharded cached step: dedup + ONE slot
        resolution (shared with the wide table), wide fill from the host
        PS exactly as the replicated path, then the deep-side three-way
        split — victims route arena→table, warm misses route table→arena
        (both in-graph), cold misses pay the one-time host fetch."""
        from ..framework.flags import flag
        ids = np.asarray(sparse_ids)
        if flag("wide_deep_device_dedup"):
            uniq, inv = self._dedup_device(ids)
        else:
            uniq, inv = np.unique(ids, return_inverse=True)
        self._dtab.check_ids(uniq)
        res = self._slot_dir.resolve(uniq)
        try:
            # wide leg: unchanged host fill (incl. wide victim writeback)
            mw_slots, mw_rows, mw_state = self._w_cache.fill(res,
                                                             self._w_ar)
            # deep cold misses: ids never seen by the device table yet
            miss_ids = res.uniq[res.miss_idx]
            miss_slots = np.asarray(res.slots[res.miss_idx], np.int64)
            cold_sel = np.fromiter(
                (int(i) not in self._dtab.resident for i in miss_ids),
                bool, len(miss_ids))
            cold_ids, cold_slots = miss_ids[cold_sel], miss_slots[cold_sel]
            warm_ids, warm_slots = (miss_ids[~cold_sel],
                                    miss_slots[~cold_sel])
            de = self.model.deep_emb
            if len(cold_ids):
                c_rows, c_state = de.client.export_rows(de.table_id,
                                                        cold_ids)
            else:
                c_rows = np.zeros((0, de.dim), np.float32)
                c_state = {k: np.zeros((0, de.dim), np.float32)
                           for k in self._d_cache._state_names}
        except Exception:
            self._slot_dir.rollback(res)
            raise
        if mw_slots is not None:
            self._w_ar = self._scatter(
                self._w_ar, jnp.asarray(mw_slots), jnp.asarray(mw_rows),
                {k: jnp.asarray(v) for k, v in mw_state.items()})
        cap = self._slot_dir.cap          # the arena scratch slot
        # cold pad: bucket-padded like DeviceEmbeddingCache.fill (a tiny
        # fixed shape when there are none, so the steady state ships ~0
        # host bytes instead of a zero-filled bucket)
        from ..distributed.ps.device_cache import _pad_to_bucket
        nc = len(cold_ids)
        c_pad = 8 if nc == 0 else _pad_to_bucket(nc,
                                                 self._d_cache.miss_bucket)
        cold_slots_p = np.full(c_pad, cap, np.int32)
        cold_slots_p[:nc] = cold_slots
        cold_rows_p = np.zeros((c_pad, de.dim), np.float32)
        cold_rows_p[:nc] = c_rows
        cold_state_p = {}
        for k in self._d_cache._state_names:
            buf = np.zeros((c_pad, de.dim), np.float32)
            buf[:nc] = c_state[k]
            cold_state_p[k] = buf
        # routed pads (victims / warm misses) + static routing caps
        vic_ids_p, vic_slots_p = self._pad_routed(res.victim_ids,
                                                  res.victim_slots, cap)
        warm_ids_p, warm_slots_p = self._pad_routed(warm_ids, warm_slots,
                                                    cap)
        n_sh = self._dtab.n_shards
        cap_v = (self._dtab.cap_for(np.asarray(res.victim_ids, np.int64),
                                    len(vic_ids_p) // n_sh)
                 if self._dtab.bucket_cap else len(vic_ids_p) // n_sh)
        cap_w = (self._dtab.cap_for(np.asarray(warm_ids, np.int64),
                                    len(warm_ids_p) // n_sh)
                 if self._dtab.bucket_cap else len(warm_ids_p) // n_sh)
        # residency bookkeeping: victims now live in the table; warm (and
        # cold) misses move into the arena, which becomes authoritative
        self._dtab.resident.update(int(i) for i in res.victim_ids)
        self._dtab.resident.difference_update(int(i) for i in warm_ids)
        # tier attribution: arena short-circuit / routed table / host PS
        n_hit = len(uniq) - len(miss_ids)
        if n_hit:
            _TIER_HITS.labels(tier="cache_arena").inc(n_hit)
        if len(warm_ids):
            _TIER_HITS.labels(tier="mesh_table").inc(len(warm_ids))
        if nc:
            _TIER_HITS.labels(tier="host_ps").inc(nc)
        # slot vector + wire-compressed inverse (replicated-path shapes)
        u = len(uniq)
        u_pad = self._pad_adaptive(u)
        slots_p = np.full(u_pad, cap, np.int32)
        slots_p[:u] = res.slots
        inv_w = inv.reshape(ids.shape)
        inv_w = inv_w.astype(np.uint16 if u_pad <= 65536 else np.int32)
        import jax
        rep = lambda x: jax.device_put(jnp.asarray(x),  # noqa: E731
                                       self._rep_sh)
        return {
            "slots": rep(slots_p), "inv": rep(inv_w),
            "vic_ids": rep(vic_ids_p), "vic_slots": rep(vic_slots_p),
            "warm_ids": rep(warm_ids_p), "warm_slots": rep(warm_slots_p),
            "cold_slots": rep(cold_slots_p), "cold_rows": rep(cold_rows_p),
            "cold_state": {k: rep(v) for k, v in cold_state_p.items()},
            "caps": (int(cap_v), int(cap_w)),
            "stats": {"cold": nc, "warm": len(warm_ids),
                      "victims": len(res.victim_ids)},
        }

    def _step_sharded(self, sparse_ids, dense_x, labels):
        import jax
        prep = self._prep_sharded(sparse_ids)
        self._last_route_stats = prep["stats"]
        key = (prep["vic_ids"].shape[0], prep["warm_ids"].shape[0],
               prep["cold_rows"].shape[0], prep["slots"].shape[0],
               tuple(np.asarray(sparse_ids).shape), prep["caps"])
        fn = self._sharded_fns.get(key)
        if fn is None:
            fn = jax.jit(self._make_sharded_fused(*prep["caps"]),
                         donate_argnums=(0, 1, 2, 3, 4))
            self._sharded_fns[key] = fn
        dense_w = jax.device_put(
            jnp.asarray(np.asarray(dense_x, self._feature_wire_dtype)),
            self._rep_sh)
        lab_w = jax.device_put(
            jnp.asarray(np.asarray(labels, np.float32)), self._rep_sh)
        (self._params, self._adam, self._w_ar, self._d_ar,
         self._dtab_tree, loss) = fn(
            self._params, self._adam, self._w_ar, self._d_ar,
            self._dtab_tree, prep["slots"], prep["slots"], prep["inv"],
            dense_w, lab_w, prep["vic_ids"], prep["vic_slots"],
            prep["warm_ids"], prep["warm_slots"], prep["cold_slots"],
            prep["cold_rows"], prep["cold_state"])
        self.sync_params()
        return loss

    def _sharded_read(self, uniq):
        """Deep-table eval read-through for sharded mode: cache arena for
        cached ids, the mesh table for resident ids, host PS else."""
        uniq = np.asarray(uniq, np.int64).ravel()
        get = self._slot_dir._slot_of.get
        slots = np.fromiter((get(i, -1) for i in uniq.tolist()),
                            np.int64, len(uniq))
        de = self.model.deep_emb
        out = np.empty((len(uniq), de.dim), np.float32)
        hit = slots >= 0
        if hit.any():
            out[hit] = np.asarray(
                self._d_ar["rows"][jnp.asarray(slots[hit])])
        cold = ~hit
        if cold.any():
            resident = np.fromiter(
                (int(i) in self._dtab.resident for i in uniq[cold]),
                bool, int(cold.sum()))
            cold_ids = uniq[cold]
            block = np.empty((len(cold_ids), de.dim), np.float32)
            if resident.any():
                block[resident], _ = self._dtab.host_read(
                    self._dtab_tree, cold_ids[resident])
            if (~resident).any():
                block[~resident] = de.client.pull_sparse(
                    de.table_id, cold_ids[~resident])
            out[cold] = block
        return out

    def _step_cached(self, sparse_ids, dense_x, labels):
        if getattr(self, "_sharded", False):
            return self._step_sharded(sparse_ids, dense_x, labels)
        slots_dev, inv_dev = self._prep_cached(sparse_ids)
        dense_w = np.asarray(dense_x, self._feature_wire_dtype)
        lab_w = np.asarray(labels, np.float32)
        self._params, self._adam, self._w_ar, self._d_ar, loss = \
            self._fused_cached(self._params, self._adam, self._w_ar,
                               self._d_ar, slots_dev, slots_dev,
                               inv_dev, jnp.asarray(dense_w),
                               jnp.asarray(lab_w))
        self.sync_params()
        return loss

    def in_graph_step_s(self, sparse_ids, dense_x, labels, k_small=2,
                        k_large=6, reps=2):
        """Seconds per device-side train step, measured as the DELTA of
        two chained in-graph loop lengths over the cached-mode fused step
        (one dispatch per K, loss riding the carry so no step can be
        dead-code-eliminated — the bench.py/mfu_audit methodology).  This
        is Wide&Deep's in-graph control number (VERDICT r5 #2/#8): what
        the framework's compiled sparse+dense step costs with the host
        hash/dedup and tunnel RTT factored out."""
        import time
        import jax
        if not self._use_cache:
            raise RuntimeError("in-graph probe needs device-cache mode")
        if getattr(self, "_sharded", False):
            return self._in_graph_sharded_s(sparse_ids, dense_x, labels,
                                            k_small, k_large, reps)
        slots_dev, inv_dev = self._prep_cached(sparse_ids)
        dense_dev = jnp.asarray(np.asarray(dense_x,
                                           self._feature_wire_dtype))
        lab_dev = jnp.asarray(np.asarray(labels, np.float32))
        raw = self._fused_cached_raw

        def loop(params, adam, w_ar, d_ar, k):
            def one(_, c):
                p, a, w, d, acc = c
                p, a, w, d, loss = raw(p, a, w, d, slots_dev, slots_dev,
                                       inv_dev, dense_dev, lab_dev)
                return (p, a, w, d, acc + loss.astype(jnp.float32))
            init = (params, adam, w_ar, d_ar, jnp.float32(0.0))
            return jax.lax.fori_loop(0, k, one, init)[4]

        f = jax.jit(loop, static_argnums=(4,))
        times = {}
        for k in (k_small, k_large):
            float(f(self._params, self._adam, self._w_ar, self._d_ar, k))
            best = None
            for _ in range(reps):
                t0 = time.perf_counter()
                float(f(self._params, self._adam, self._w_ar, self._d_ar,
                        k))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            times[k] = best
        return (times[k_large] - times[k_small]) / (k_large - k_small)

    def _in_graph_sharded_s(self, sparse_ids, dense_x, labels, k_small,
                            k_large, reps):
        """Sharded-mode in-graph probe: the chained-K delta over the full
        sharded step body (victim route + warm all-to-all fetch + dense
        fwd/bwd + on-chip rule), so the number includes the routing legs
        a steady-state step actually pays."""
        import time
        import jax
        prep = self._prep_sharded(sparse_ids)
        raw = self._make_sharded_fused(*prep["caps"])
        dense_dev = jax.device_put(
            jnp.asarray(np.asarray(dense_x, self._feature_wire_dtype)),
            self._rep_sh)
        lab_dev = jax.device_put(
            jnp.asarray(np.asarray(labels, np.float32)), self._rep_sh)
        p = prep

        def loop(params, adam, w_ar, d_ar, dtree, k):
            def one(_, c):
                pr, a, w, d, t, acc = c
                pr, a, w, d, t, loss = raw(
                    pr, a, w, d, t, p["slots"], p["slots"], p["inv"],
                    dense_dev, lab_dev, p["vic_ids"], p["vic_slots"],
                    p["warm_ids"], p["warm_slots"], p["cold_slots"],
                    p["cold_rows"], p["cold_state"])
                return (pr, a, w, d, t, acc + loss.astype(jnp.float32))
            init = (params, adam, w_ar, d_ar, dtree, jnp.float32(0.0))
            return jax.lax.fori_loop(0, k, one, init)[5]

        f = jax.jit(loop, static_argnums=(5,))
        times = {}
        for k in (k_small, k_large):
            args = (self._params, self._adam, self._w_ar, self._d_ar,
                    self._dtab_tree, k)
            float(f(*args))
            best = None
            for _ in range(reps):
                t0 = time.perf_counter()
                float(f(*args))
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            times[k] = best
        return (times[k_large] - times[k_small]) / (k_large - k_small)

    def sharded_step_stats(self, sparse_ids, dense_x, labels):
        """Collective census of the compiled sharded step for this batch
        signature (AOT lower + compile, NO execution): per-kind counts,
        result bytes and ring-model wire bytes — the bytes/step
        accounting bench.py and PERF.md record.  Call with an
        already-trained batch so the prep pass leaves cache state
        effectively unchanged (all ids hit)."""
        if not getattr(self, "_sharded", False):
            raise RuntimeError("sharded_step_stats needs sharded mode "
                               "(FLAGS_sharded_embedding)")
        import jax
        from ..analysis.hlo.extract import program_stats
        prep = self._prep_sharded(sparse_ids)
        dense_w = jax.device_put(
            jnp.asarray(np.asarray(dense_x, self._feature_wire_dtype)),
            self._rep_sh)
        lab_w = jax.device_put(
            jnp.asarray(np.asarray(labels, np.float32)), self._rep_sh)
        # a fresh un-donated jit so the lowering never invalidates live
        # trainer state
        fn = jax.jit(self._make_sharded_fused(*prep["caps"]))
        compiled = fn.lower(
            self._params, self._adam, self._w_ar, self._d_ar,
            self._dtab_tree, prep["slots"], prep["slots"], prep["inv"],
            dense_w, lab_w, prep["vic_ids"], prep["vic_slots"],
            prep["warm_ids"], prep["warm_slots"], prep["cold_slots"],
            prep["cold_rows"], prep["cold_state"]).compile()
        stats = program_stats(compiled)
        return {
            "collectives": stats.collectives,
            "all_to_all_count": int(
                stats.collectives.get("all-to-all", {}).get("count", 0)),
            "all_to_all_wire_bytes": float(
                stats.collectives.get("all-to-all", {}).get("wire_bytes",
                                                            0.0)),
            "collective_wire_bytes": round(stats.collective_wire_bytes, 1),
            "route": dict(prep["stats"]),
            "n_shards": self._dtab.n_shards,
        }

    def _step_pullpush(self, sparse_ids, dense_x, labels):
        if self._async_push:
            # surface background push failures BEFORE advancing dense
            # state for this batch
            self._raise_push_errors()
        ids = np.asarray(sparse_ids)
        we, de = self.model.wide_emb, self.model.deep_emb
        # one unique/inverse shared by both tables (same id space)
        uniq, inv = np.unique(ids, return_inverse=True)
        w_rows = jnp.asarray(we.pull_padded_rows(uniq))
        d_rows = jnp.asarray(de.pull_padded_rows(uniq))
        inv_dev = jnp.asarray(inv.reshape(ids.shape), jnp.int32)
        self._params, self._adam, loss, gw, gd = self._fused(
            self._params, self._adam, w_rows, d_rows, inv_dev, inv_dev,
            jnp.asarray(dense_x), jnp.asarray(labels))
        self._push_both(we, de, uniq, gw, gd)
        # keep the eager model in sync: rebinding _value to the updated
        # device arrays is a pointer swap (no transfer), so eval /
        # state_dict always see the trained weights
        self.sync_params()
        return loss

    def flush(self):
        """Barrier before eval/save: drain pending async pushes, or in
        device-cache mode write every cached row back to the host table
        (PSGPU EndPass).  Sharded mode additionally drains the
        mesh-resident tail of the deep table (resident ids' rows + state)
        back to the host PS — cache and table populations are disjoint by
        construction, so nothing double-writes."""
        if self._use_cache:
            self._w_cache.writeback_all(self._w_ar)
            self._d_cache.writeback_all(self._d_ar)
            if getattr(self, "_sharded", False):
                de = self.model.deep_emb
                self._dtab.flush_to_client(self._dtab_tree, de.client,
                                           de.table_id)
        if self._push_queue is not None:
            self._push_queue.join()
        self._raise_push_errors()

    def sync_params(self):
        """Point the eager model's dense params at the jit-updated device
        arrays (free — same buffers, no copy)."""
        if not hasattr(self, "_name_map"):
            self._name_map = dense_param_map(self.model, self._params)
        for name, p in self._name_map:
            p._value = self._params[name]


def dense_param_map(model: "WideDeep", params):
    """(name, Parameter) pairs of the model's dense core that appear in a
    functional params tree — the pointer-swap map both CTR trainers use to
    keep the eager model in sync."""
    core = _DenseCore(model)
    return [(n, p) for n, p in core.named_parameters() if n in params]


class _DenseCore(nn.Layer):
    """The dense compute of WideDeep as a pure layer over pulled rows:
    (wide_rows [U1,1], deep_rows [U2,D], wide_inv [B,S], deep_inv [B,S],
    dense_x [B,F]) -> logits [B,1]."""

    def __init__(self, wd: WideDeep):
        super().__init__()
        self.dnn = wd.dnn
        self.wide_dense = wd.wide_dense
        self._emb_dim = wd.deep_emb.dim

    def forward(self, wide_rows, deep_rows, wide_inv, deep_inv, dense_x):
        from .. import ops
        from ..nn import functional as F
        wide_g = F.embedding(wide_inv, wide_rows)      # [B, S, 1]
        wide = wide_g.squeeze(-1).sum(axis=-1, keepdim=True) + \
            self.wide_dense(dense_x)
        deep_g = F.embedding(deep_inv, deep_rows)      # [B, S, D]
        deep_in = deep_g.reshape([deep_g.shape[0], -1])
        deep = self.dnn(ops.concat([deep_in, dense_x], axis=-1))
        return wide + deep




def synthetic_ctr_batch(batch: int, num_slots: int = 26, dense_dim: int = 13,
                        vocab: int = 1_000_000, seed: int = 0):
    """Criteo-shaped synthetic batch: 26 categorical slots (slot-offset id
    space), 13 dense features, clicked/not label correlated with features."""
    rng = np.random.RandomState(seed)
    # power-lawish ids per slot, offset so slots never collide
    ids = (rng.zipf(1.5, size=(batch, num_slots)) % (vocab // num_slots))
    ids = ids + np.arange(num_slots) * (vocab // num_slots)
    dense = rng.standard_normal((batch, dense_dim)).astype(np.float32)
    logit = 0.5 * dense[:, 0] - 0.3 * dense[:, 1] + \
        0.1 * (ids[:, 0] % 7 - 3)
    label = (logit + rng.standard_normal(batch) >
             0).astype(np.float32)[:, None]
    return ids.astype(np.int64), dense, label

def write_ctr_files(dirname, n_examples, n_files=4, num_slots: int = 26,
                    dense_dim: int = 13, vocab: int = 1_000_000, seed=0):
    """Write synthetic CTR data as MultiSlot text files (data_feed.proto
    format): 26 single-id sparse slots, one dense slot, one label slot.
    Returns the filelist."""
    import os
    os.makedirs(dirname, exist_ok=True)
    per = n_examples // n_files
    files = []
    for fi in range(n_files):
        ids, dense, label = synthetic_ctr_batch(per, num_slots, dense_dim,
                                                vocab, seed=seed + fi)
        path = os.path.join(dirname, f"ctr_{fi:03d}.txt")
        with open(path, "w") as f:
            for r in range(per):
                parts = [f"1 {ids[r, s]}" for s in range(num_slots)]
                parts.append(f"{dense_dim} " +
                             " ".join(f"{v:.5f}" for v in dense[r]))
                parts.append(f"1 {int(label[r, 0])}")
                f.write(" ".join(parts) + "\n")
        files.append(path)
    return files


def ctr_dataset(filelist, batch_size, num_slots: int = 26,
                dense_dim: int = 13, kind="InMemoryDataset"):
    """An InMemoryDataset/QueueDataset over CTR MultiSlot files, slot
    schema matching write_ctr_files."""
    from ..distributed.dataset import InMemoryDataset, QueueDataset
    ds = (InMemoryDataset if kind == "InMemoryDataset" else QueueDataset)()
    ds.init(batch_size=batch_size, thread_num=4)
    slots = [{"name": f"C{s}", "type": "uint64"} for s in range(num_slots)]
    slots.append({"name": "dense", "type": "float", "is_dense": True,
                  "shape": (dense_dim,)})
    slots.append({"name": "label", "type": "uint64"})
    ds.set_slots(slots)
    ds.set_filelist(list(filelist))
    return ds


def batch_from_feed(feed, num_slots: int = 26):
    """Compose a dataset feed dict into (ids, dense, label) trainer arrays."""
    ids = np.concatenate([feed[f"C{s}"] for s in range(num_slots)], axis=1)
    dense = feed["dense"].astype(np.float32)
    label = feed["label"].astype(np.float32)
    return ids.astype(np.int64), dense, label
