"""Wide&Deep CTR model over parameter-server sparse embeddings.

Reference parity: BASELINE workload 5 — the DistributedStrategy + sparse
embedding CTR configuration the reference serves with its PS stack
(fluid.layers.embedding(is_sparse=True, is_distributed=True) pulled through
lookup_sparse_table / parameter_prefetch).  Model shape follows the classic
Wide&Deep CTR recipe: a wide linear part over the raw sparse slots plus a
deep MLP over slot embeddings and dense features.

TPU-first: the sparse side is two host tables (dim-1 wide weights, dim-D
deep embeddings) behind DistributedEmbedding; everything dense — gathers,
MLP, loss, backward — is on-chip.  The trainer drives pull → dense step →
push per batch (the HeterPS loop).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .. import nn, optimizer as opt_mod
from ..framework.tensor import Tensor
from ..distributed.ps import DistributedEmbedding, LocalPsEndpoint


class WideDeep(nn.Layer):
    def __init__(self, client=None, emb_dim: int = 16, num_slots: int = 26,
                 dense_dim: int = 13, hidden=(400, 400, 400),
                 sparse_lr: float = 0.05):
        super().__init__()
        client = client or LocalPsEndpoint()
        self.client = client
        self.num_slots = num_slots
        self.wide_emb = DistributedEmbedding(client, table_id=0, dim=1,
                                             optimizer="adagrad",
                                             lr=sparse_lr)
        self.deep_emb = DistributedEmbedding(client, table_id=1, dim=emb_dim,
                                             optimizer="adagrad",
                                             lr=sparse_lr)
        layers = []
        in_dim = num_slots * emb_dim + dense_dim
        for h in hidden:
            layers += [nn.Linear(in_dim, h), nn.ReLU()]
            in_dim = h
        layers.append(nn.Linear(in_dim, 1))
        self.dnn = nn.Sequential(*layers)
        self.wide_dense = nn.Linear(dense_dim, 1)

    def forward(self, sparse_ids, dense_x):
        # wide: sum of per-slot scalar weights + linear over dense feats
        wide = self.wide_emb(sparse_ids).squeeze(-1).sum(axis=-1,
                                                         keepdim=True)
        wide = wide + self.wide_dense(dense_x)
        # deep: slot embeddings concat dense feats -> MLP
        deep_in = self.deep_emb(sparse_ids).reshape(
            [sparse_ids.shape[0], -1])
        from .. import ops
        deep = self.dnn(ops.concat([deep_in, dense_x], axis=-1))
        return wide + deep

    def flush_sparse_grads(self):
        self.wide_emb.flush_grads()
        self.deep_emb.flush_grads()


class WideDeepTrainer:
    """pull → on-chip fwd/bwd → push + dense update (the PS train loop that
    the reference's Communicator+DeviceWorker pair runs, communicator.h:195)."""

    def __init__(self, model: WideDeep, lr: float = 1e-3):
        self.model = model
        self.opt = opt_mod.Adam(parameters=model.parameters(),
                                learning_rate=lr)
        self.loss_fn = nn.BCEWithLogitsLoss()

    def step(self, sparse_ids, dense_x, labels) -> float:
        logits = self.model(Tensor(jnp.asarray(sparse_ids)),
                            Tensor(jnp.asarray(dense_x)))
        loss = self.loss_fn(logits, Tensor(jnp.asarray(labels)))
        loss.backward()
        self.model.flush_sparse_grads()   # sparse push (server-side rule)
        self.opt.step()                   # dense on-device update
        self.opt.clear_grad()
        return float(loss)


def synthetic_ctr_batch(batch: int, num_slots: int = 26, dense_dim: int = 13,
                        vocab: int = 1_000_000, seed: int = 0):
    """Criteo-shaped synthetic batch: 26 categorical slots (slot-offset id
    space), 13 dense features, clicked/not label correlated with features."""
    rng = np.random.RandomState(seed)
    # power-lawish ids per slot, offset so slots never collide
    ids = (rng.zipf(1.5, size=(batch, num_slots)) % (vocab // num_slots))
    ids = ids + np.arange(num_slots) * (vocab // num_slots)
    dense = rng.standard_normal((batch, dense_dim)).astype(np.float32)
    logit = 0.5 * dense[:, 0] - 0.3 * dense[:, 1] + \
        0.1 * (ids[:, 0] % 7 - 3)
    label = (logit + rng.standard_normal(batch) >
             0).astype(np.float32)[:, None]
    return ids.astype(np.int64), dense, label
