"""Recommendation models (BASELINE workload 5: Wide&Deep CTR)."""
from .wide_deep import WideDeep, WideDeepTrainer, synthetic_ctr_batch  # noqa: F401
from .hogwild import HogwildTrainer, PSGPUTrainer  # noqa: F401
from .heter import (  # noqa: F401
    HeterTrainer, create_trainer,
    TRAINER_LEDGER, DEVICE_WORKER_LEDGER, FLEET_WRAPPER_LEDGER,
)
from .sharded_embedding import (  # noqa: F401
    ShardedEmbedding, ShardedTable, ShardedWideDeep,
)

__all__ = ["WideDeep", "WideDeepTrainer", "HogwildTrainer",
           "PSGPUTrainer", "synthetic_ctr_batch", "ShardedEmbedding",
           "ShardedTable", "ShardedWideDeep", "HeterTrainer"]
