"""Recommendation models (BASELINE workload 5: Wide&Deep CTR)."""
from .wide_deep import WideDeep, WideDeepTrainer, synthetic_ctr_batch  # noqa: F401
from .hogwild import HogwildTrainer  # noqa: F401

__all__ = ["WideDeep", "WideDeepTrainer", "HogwildTrainer",
           "synthetic_ctr_batch"]
