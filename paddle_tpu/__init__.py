"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, re-architected for JAX/XLA/Pallas/pjit.

Reference: baiyfbupt/Paddle (see SURVEY.md). This is not a port -- the compute
path lowers through XLA:TPU, distributed execution uses jax.sharding Meshes
with ICI collectives, and the imperative/static dual API compiles whole steps
into single XLA computations.
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    Tensor, to_tensor, set_device, get_device, device_count,
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace, CUDAPinnedPlace,
    set_default_dtype, get_default_dtype, seed, get_rng_state, set_rng_state,
    set_flags, get_flags, enable_static, disable_static, in_dygraph_mode,
    grad, is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    complex64,
)
from .framework import bool_ as bool  # noqa: F401  (paddle.bool)
from .framework.core import no_grad_guard as no_grad, set_grad_enabled  # noqa: F401
from .ops import *  # noqa: F401,F403  (tensor API surface: paddle.add, ...)
from .ops import creation as _creation  # noqa: F401

from .ops.creation import rand, randn, randint, randperm, uniform, normal  # noqa: F401

# subpackages -- soft-imported during bring-up; all are required by release
import importlib as _importlib

_SUBPACKAGES = ["nn", "optimizer", "static", "io", "metric", "amp", "jit",
                "distributed", "vision", "text", "autograd", "hapi",
                "incubate", "inference", "profiler", "device",
                "quantization", "utils"]
for _name in _SUBPACKAGES:
    try:
        globals()[_name] = _importlib.import_module(f".{_name}", __name__)
    except ImportError as _e:  # pragma: no cover - only during partial builds
        import os as _os
        if _os.environ.get("PADDLE_TPU_STRICT_IMPORT"):
            raise
        globals()[_name] = None

try:
    from .framework.io_state import save, load  # noqa: F401
    from .hapi import Model  # noqa: F401
    from .nn.layer.layers import ParamAttr  # noqa: F401
except ImportError:  # pragma: no cover
    pass


def DataParallel(layer, *args, **kwargs):
    from .distributed.parallel import DataParallel as _DP
    return _DP(layer, *args, **kwargs)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)
