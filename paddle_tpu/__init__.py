"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, re-architected for JAX/XLA/Pallas/pjit.

Reference: baiyfbupt/Paddle (see SURVEY.md). This is not a port -- the compute
path lowers through XLA:TPU, distributed execution uses jax.sharding Meshes
with ICI collectives, and the imperative/static dual API compiles whole steps
into single XLA computations.
"""
from __future__ import annotations

__version__ = "0.1.0"

# RBG counter-based PRNG: threefry key derivation costs real step time on
# TPU for dropout-heavy models (+28% measured BERT throughput from this
# switch alone). Must be set before any key is created. Opt out with
# PADDLE_TPU_THREEFRY=1 when bit-exact threefry streams are required.
import os as _os
if _os.environ.get("PADDLE_TPU_THREEFRY", "0") in ("", "0"):
    try:
        import jax as _jax
        _jax.config.update("jax_default_prng_impl", "rbg")
    except Exception:  # pragma: no cover
        pass

from .framework import (  # noqa: F401
    Tensor, to_tensor, set_device, get_device, device_count,
    CPUPlace, TPUPlace, CUDAPlace, XPUPlace, CUDAPinnedPlace,
    set_default_dtype, get_default_dtype, seed, get_rng_state, set_rng_state,
    set_flags, get_flags, enable_static, disable_static, in_dygraph_mode,
    grad, is_compiled_with_cuda, is_compiled_with_xpu, is_compiled_with_tpu,
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    complex64,
)
from .framework import bool_ as bool  # noqa: F401  (paddle.bool)
from .framework.core import no_grad_guard as no_grad, set_grad_enabled  # noqa: F401
from .ops import *  # noqa: F401,F403  (tensor API surface: paddle.add, ...)
from .ops import creation as _creation  # noqa: F401

from .ops.creation import rand, randn, randint, randperm, uniform, normal  # noqa: F401

# subpackages -- soft-imported during bring-up; all are required by release
import importlib as _importlib

_SUBPACKAGES = ["nn", "optimizer", "static", "io", "metric", "amp", "jit",
                "distributed", "vision", "text", "autograd", "hapi",
                "incubate", "inference", "serving", "profiler", "device",
                "quantization", "analysis", "utils", "distribution", "onnx",
                "tensor", "regularizer", "compat", "sysconfig", "version",
                "fluid"]
for _name in _SUBPACKAGES:
    try:
        globals()[_name] = _importlib.import_module(f".{_name}", __name__)
    except ImportError as _e:  # pragma: no cover - only during partial builds
        import os as _os
        if _os.environ.get("PADDLE_TPU_STRICT_IMPORT"):
            raise
        globals()[_name] = None

try:
    from .framework.io_state import save, load  # noqa: F401
    from .hapi import Model  # noqa: F401
    from .nn.layer.layers import ParamAttr  # noqa: F401
except ImportError:  # pragma: no cover
    pass


def DataParallel(layer, *args, **kwargs):
    from .distributed.parallel import DataParallel as _DP
    return _DP(layer, *args, **kwargs)


def summary(net, input_size=None, dtypes=None):
    from .hapi.summary import summary as _summary
    return _summary(net, input_size, dtypes)


# -- top-level long tail (python/paddle/__init__.py parity) -------------------

def add_n(inputs, name=None):
    """sum_op parity: elementwise sum of a tensor list."""
    if isinstance(inputs, (list, tuple)):
        out = inputs[0]
        for t in inputs[1:]:
            out = out + t
        return out
    return inputs


def broadcast_shape(x_shape, y_shape):
    import numpy as _np
    return list(_np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """layers.create_parameter parity."""
    from .nn import initializer as _I
    from .framework.tensor import Parameter as _Param
    from .framework.dtype import convert_dtype as _cd
    init = default_initializer or (_I.Constant(0.0) if is_bias
                                   else _I.XavierUniform())
    return _Param(init(list(shape), _cd(dtype) or "float32"), name=name)


def is_tensor(x):
    from .framework.tensor import Tensor as _T
    return isinstance(x, _T)


def is_empty(x, name=None):
    from .framework.tensor import Tensor as _T, unwrap as _u
    import jax.numpy as _jnp
    return _T(_jnp.asarray(_u(x).size == 0))


def in_dynamic_mode():
    from .framework import core as _core
    return not _core.in_static_mode()


in_dygraph_mode = in_dynamic_mode


def get_cuda_rng_state():
    """CUDA-generator parity shim: TPU builds have no CUDA generator; the
    framework RNG state is returned so checkpoint round-trips still work."""
    from .framework.random import get_rng_state as _g
    return _g()


def set_cuda_rng_state(state):
    from .framework.random import set_rng_state as _s
    return _s(state)


def get_cudnn_version():
    return None      # no cuDNN in a TPU build (matches CPU-only paddle)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Delegates to numpy's global print options (Tensor repr prints via
    numpy)."""
    import numpy as _np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def flops(net, input_size, custom_ops=None, print_detail=False):
    """hapi dynamic_flops parity: count multiply-add FLOPs of a dygraph
    net by a forward pass with per-layer hooks."""
    from .hapi.flops import flops as _flops
    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail)


try:
    from .hapi import callbacks  # noqa: F401
except ImportError:  # pragma: no cover — partial builds degrade softly
    callbacks = None

from . import reader  # noqa: F401,E402  (legacy reader combinators)
from . import dataset  # noqa: F401,E402  (legacy reader-creator API)


# -- fluid-era aliases (python/paddle/__init__.py DEFINE_ALIAS block) ---------

VarBase = Tensor                    # paddle.framework.VarBase as Tensor
from .batch import batch  # noqa: F401,E402
from .version import full_version, commit  # noqa: F401,E402


def enable_dygraph(place=None):
    """fluid.dygraph.base.enable_dygraph parity (= paddle.disable_static)."""
    disable_static()


def disable_dygraph():
    """fluid.dygraph.base.disable_dygraph parity (= paddle.enable_static)."""
    enable_static()


def crop_tensor(x, shape=None, offsets=None, name=None):
    """fluid.layers.crop_tensor parity (crop_tensor_op.cc; exported
    top-level as paddle.crop in the reference). None shape keeps x's
    shape; None offsets means all-zero offsets."""
    from .ops.manipulation import crop as _crop
    if shape is None:
        shape = list(x.shape)
    if offsets is None:
        offsets = [0] * len(list(shape))
    return _crop(x, shape, offsets)


def data(name, shape, dtype="float32", lod_level=0):
    """fluid.data parity: declare a static-graph input Variable."""
    from . import static as _static
    return _static.data(name, shape, dtype or "float32", lod_level)


from .tensor import (  # noqa: F401,E402
    elementwise_add, elementwise_sub, elementwise_mul, elementwise_div,
    elementwise_floordiv, elementwise_mod, elementwise_pow, elementwise_max,
    elementwise_min, has_inf, has_nan, fill_constant,
)
