"""Quantization freeze pass: QAT/PTQ artifacts -> deployable int8 programs.

Reference parity: python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:1045 (QuantizationFreezePass: fold collected scales
into true int8 weight tensors, strip fake_quantize_dequantize ops, rewrite
the consuming matmul/conv to the int8 kernels, insert one dequantize with
the recorded out-scale) plus ConvertToInt8Pass (:1352).

TPU-shape: the pass walks the imperative model (the repo's QAT/PTQ form)
instead of an IrGraph.  Each ``QuantizedLinear``/``QuantizedConv2D`` —
optionally under an out-scale collector — becomes a Frozen* layer holding
int8 weights + fp32 scales whose forward is ONE int8 primitive
(ops/int8.py): quantize-at-scale, i8×i8→i32 MXU dot/conv, fused
requantize/dequantize epilogue.  ``jit.save`` of the frozen model then
exports integer-compute StableHLO, which is the "frozen Program" the
Predictor serves (see inference/__init__.py int8 selection).

Numerics contract: with the same collected scales the frozen output equals
the fake-QDQ simulation up to float associativity — the int8 rounding
happens at the same two points (input at s_x, weight at s_w), only the
compute dtype changes from simulated-fp32 to real int8/int32.
"""
from __future__ import annotations

import json
import os

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from . import functional as QF
from .quant_layers import (FakeQuantAbsMax, FakeQuantMovingAverage,
                           FakeChannelWiseQuantDequantAbsMax,
                           QuantizedConv2D, QuantizedLinear)


def _static_input_scale(fq):
    """Collected input scale of a QAT/PTQ activation quantizer, or None
    when the quantizer is dynamic (per-batch abs-max)."""
    if isinstance(fq, FakeQuantMovingAverage):
        return np.asarray(fq.scale.numpy(), np.float32).reshape(())
    return None


def _weight_quant(fq, weight, default_axis, bits):
    """(w_q int8, s_w fp32, per_channel) folding the weight quantizer's
    config into true int8 storage (quantize_weight_int8)."""
    if isinstance(fq, FakeChannelWiseQuantDequantAbsMax):
        axis = getattr(fq, "_quant_axis", default_axis)
        q, s = QF.quantize_weight_int8(weight, quant_axis=axis,
                                       bit_length=bits)
        return q, s, True
    q, s = QF.quantize_weight_int8(weight, quant_axis=None, bit_length=bits)
    return q, s, False


class FrozenQuantizedLinear(Layer):
    """A frozen linear rewrite site: int8 weight [in, out], per-channel
    (axis=1) or per-tensor scales, forward = ops.int8.linear_int8.

    The collected out-scale is always recorded in the ``out_scale``
    buffer (engines and the quant signature read it); it only enters the
    epilogue as a requantize step when ``fold_out_scale`` — strict int8
    activation dataflow, an EXTRA rounding vs the fake-quant training
    simulation (see QuantizationFreezePass)."""

    def __init__(self, qlayer: QuantizedLinear, weight_bits=8,
                 activation_bits=8, out_scale=None, fold_out_scale=False):
        super().__init__()
        self._weight_bits = int(weight_bits)
        self._activation_bits = int(activation_bits)
        w_q, s_w, self._per_channel = _weight_quant(
            qlayer._fake_quant_weight, qlayer.weight, 1, weight_bits)
        self.register_buffer("weight_int8", w_q)
        self.register_buffer("weight_scale",
                             Tensor(np.asarray(s_w.numpy(), np.float32)
                                    .reshape(-1)))
        s_x = _static_input_scale(qlayer._fake_quant_input)
        self._dynamic = s_x is None
        self.register_buffer("input_scale", Tensor(
            np.float32(1.0) if s_x is None else s_x))
        self.bias = qlayer.bias
        self._has_out_scale = out_scale is not None and fold_out_scale
        self.register_buffer("out_scale", Tensor(
            np.float32(out_scale) if out_scale is not None
            else np.float32(0.0)))

    def forward(self, x):
        from ..ops import int8 as I8
        return I8.linear_int8(
            x, self.weight_int8, self.input_scale, self.weight_scale,
            bias=self.bias,
            out_scale=self.out_scale if self._has_out_scale else None,
            bits=self._activation_bits, dynamic=self._dynamic)


class FrozenQuantizedConv2D(Layer):
    """A frozen conv2d rewrite site: int8 OIHW weight, per-output-channel
    scales (quant_axis=0), forward = ops.int8.conv2d_int8."""

    def __init__(self, qlayer: QuantizedConv2D, weight_bits=8,
                 activation_bits=8, out_scale=None, fold_out_scale=False):
        super().__init__()
        self._weight_bits = int(weight_bits)
        self._activation_bits = int(activation_bits)
        w_q, s_w, self._per_channel = _weight_quant(
            qlayer._fake_quant_weight, qlayer.weight, 0, weight_bits)
        self.register_buffer("weight_int8", w_q)
        self.register_buffer("weight_scale",
                             Tensor(np.asarray(s_w.numpy(), np.float32)
                                    .reshape(-1)))
        s_x = _static_input_scale(qlayer._fake_quant_input)
        self._dynamic = s_x is None
        self.register_buffer("input_scale", Tensor(
            np.float32(1.0) if s_x is None else s_x))
        self.bias = qlayer.bias
        self._stride = qlayer._stride
        self._padding = qlayer._padding
        self._dilation = qlayer._dilation
        self._groups = qlayer._groups
        self._data_format = qlayer._data_format
        self._has_out_scale = out_scale is not None and fold_out_scale
        self.register_buffer("out_scale", Tensor(
            np.float32(out_scale) if out_scale is not None
            else np.float32(0.0)))

    def forward(self, x):
        from ..nn.functional.conv import _norm_padding, _norm_tuple
        from ..ops import int8 as I8
        return I8.conv2d_int8(
            x, self.weight_int8, self.input_scale, self.weight_scale,
            bias=self.bias,
            out_scale=self.out_scale if self._has_out_scale else None,
            bits=self._activation_bits, dynamic=self._dynamic,
            stride=_norm_tuple(self._stride, 2),
            padding=_norm_padding(self._padding, 2),
            dilation=_norm_tuple(self._dilation, 2),
            groups=int(self._groups),
            channel_last=self._data_format in ("NHWC",))


_FROZEN = {QuantizedLinear: FrozenQuantizedLinear,
           QuantizedConv2D: FrozenQuantizedConv2D}


def _collected_out_scale(wrapper):
    """The out-scale a collector actually observed, or None when it never
    saw a train/calibration forward (state buffer still at its 1.0 init) —
    folding an unobserved scale would clip every output to [-1, 1]."""
    st = float(np.asarray(wrapper._out_scale.state.numpy()))
    if st == 1.0:
        return None
    return float(np.asarray(wrapper._out_scale.scale.numpy()))


class QuantizationFreezePass:
    """quantization_pass.py:1045 parity over the imperative model.

    ``apply(model)`` swaps every fake-quantized site for its frozen int8
    form in place (idempotent — frozen layers are left alone), recording
    collected out-scales (from an enclosing ImperativeCalcOutScale
    collector or PTQ calibration) on each site.  ``frozen_sites`` counts
    the rewrites.

    ``fold_out_scales=True`` additionally REQUANTIZES each site's output
    onto its out-scale int8 grid inside the fused epilogue — the strict
    int8-activation dataflow of the ConvertToInt8/TensorRT engines.
    That is one extra rounding per activation relative to the fake-quant
    training simulation (which only rounds at the next site's input
    quantizer), so the default keeps the reference freeze behavior:
    dequantize to float in the epilogue, out thresholds recorded as
    attributes for whoever consumes them."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 fold_out_scales=False):
        self._weight_bits = int(weight_bits)
        self._activation_bits = int(activation_bits)
        self._fold_out_scales = bool(fold_out_scales)
        self.frozen_sites = 0

    def _freeze_one(self, layer, out_scale=None):
        if out_scale is None:
            # PTQ calibration records the observed output scale directly
            # on the site (ptq.py); QAT sites get theirs from the
            # enclosing ImperativeCalcOutScale collector instead
            out_scale = getattr(layer, "_frozen_out_scale", None)
        for cls, fcls in _FROZEN.items():
            if isinstance(layer, cls):
                self.frozen_sites += 1
                return fcls(layer, weight_bits=self._weight_bits,
                            activation_bits=self._activation_bits,
                            out_scale=out_scale,
                            fold_out_scale=self._fold_out_scales)
        return None

    def apply(self, model):
        self._walk(model)
        return model

    def _walk(self, layer):
        from .qat import _OutScaleWrapper
        for name, child in list(layer._sub_layers.items()):
            if isinstance(child, _OutScaleWrapper):
                frozen = self._freeze_one(child._inner,
                                          out_scale=_collected_out_scale(
                                              child))
                if frozen is not None:
                    # the collector's job is done: its scale now lives in
                    # the frozen epilogue, so the wrapper goes away too
                    setattr(layer, name, frozen)
                else:
                    self._walk(child)
                continue
            frozen = self._freeze_one(child)
            if frozen is not None:
                setattr(layer, name, frozen)
            else:
                self._walk(child)


def freeze(model, weight_bits=8, activation_bits=8, fold_out_scales=False):
    """Freeze a QAT/PTQ-calibrated model to int8 execution, in place.

    Raises when the model has no fake-quantized site (the pass would be a
    silent no-op — run ImperativeQuantAware/PostTrainingQuantization
    first)."""
    p = QuantizationFreezePass(weight_bits=weight_bits,
                               activation_bits=activation_bits,
                               fold_out_scales=fold_out_scales)
    p.apply(model)
    if p.frozen_sites == 0:
        raise ValueError(
            "freeze: no QuantizedLinear/QuantizedConv2D sites found — "
            "quantize the model (QAT or PTQ) before freezing")
    model.eval()
    return model


def quant_signature(model):
    """Stable digest of a frozen model's quantization state (bits, site
    layout, scales) — the Predictor mixes it into the AOT executable
    cache key so int8 and float executables never collide."""
    import hashlib
    h = hashlib.sha1()
    for name, sub in model.named_sublayers():
        if isinstance(sub, (FrozenQuantizedLinear, FrozenQuantizedConv2D)):
            h.update(name.encode())
            h.update(bytes([sub._weight_bits, sub._activation_bits,
                            sub._per_channel, sub._dynamic,
                            sub._has_out_scale]))
            h.update(np.asarray(sub.weight_scale.numpy()).tobytes())
            h.update(np.asarray(sub.input_scale.numpy()).tobytes())
            h.update(np.asarray(sub.out_scale.numpy()).tobytes())
    return h.hexdigest()


def save_int8_model(model, path, input_spec=None, **configs):
    """Freeze (if not already frozen) and export the int8 inference
    artifact NEXT TO a float export: ``<path>.int8.pdmodel`` (integer
    StableHLO via jit.save) + ``<path>.quant.json`` (the quant signature
    sidecar the Predictor keys its executable cache on).

    The Predictor picks the ``.int8`` sibling transparently when
    ``FLAGS_use_int8_inference`` is on — serving configs that never heard
    of int8 keep loading ``<path>.pdmodel``."""
    from .. import jit
    has_frozen = any(isinstance(s, (FrozenQuantizedLinear,
                                    FrozenQuantizedConv2D))
                     for s in model.sublayers())
    if not has_frozen:
        freeze(model)
    model.eval()
    jit.save(model, path + ".int8", input_spec=input_spec, **configs)
    sig = quant_signature(model)
    sites = sum(1 for s in model.sublayers()
                if isinstance(s, (FrozenQuantizedLinear,
                                  FrozenQuantizedConv2D)))
    with open(path + ".quant.json", "w") as f:
        json.dump({"int8": True, "signature": sig, "sites": sites,
                   "weight_bits": 8, "format": "jit_stablehlo"}, f)
    return path + ".int8"


def load_quant_sidecar(prefix):
    """The quant.json sidecar for a model prefix, or None."""
    p = prefix + ".quant.json"
    if not os.path.isfile(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
