"""Quantization: QAT (fake-quant simulation) + PTQ (calibration / weight-only).

Reference parity: python/paddle/fluid/contrib/slim/quantization/ (the slim
quantization stack: imperative/qat.py, imperative/quant_nn.py,
post_training_quantization.py) over the fake_quantize_op.cc /
fake_dequantize_op.cc kernels.
"""
from .functional import (  # noqa: F401
    fake_quantize_dequantize_abs_max,
    fake_channel_wise_quantize_dequantize_abs_max,
    fake_quantize_dequantize_moving_average_abs_max,
    moving_average_abs_max_scale, quantize_weight_int8, dequantize_weight,
)
from .quant_layers import (  # noqa: F401
    FakeQuantAbsMax, FakeQuantMovingAverage,
    FakeChannelWiseQuantDequantAbsMax, MovingAverageAbsMaxScale,
    QuantizedConv2D, QuantizedLinear,
)
from .qat import ImperativeQuantAware, ImperativeCalcOutScale  # noqa: F401
from .ptq import PostTrainingQuantization, WeightQuantization  # noqa: F401
from .freeze import (  # noqa: F401
    QuantizationFreezePass, FrozenQuantizedLinear, FrozenQuantizedConv2D,
    freeze, save_int8_model, quant_signature, load_quant_sidecar,
)
