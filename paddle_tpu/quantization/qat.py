"""Imperative quantization-aware training (QAT).

Reference parity: python/paddle/fluid/contrib/slim/quantization/imperative/
qat.py — ImperativeQuantAware.quantize walks the dygraph model and swaps
Conv2D/Linear for quantized counterparts; ImperativeCalcOutScale hooks
output-scale collection onto activation layers for inference-time
quantization.
"""
from __future__ import annotations

from ..nn.layer import layers as L
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from .quant_layers import (QuantizedConv2D, QuantizedLinear,
                           MovingAverageAbsMaxScale)


class ImperativeQuantAware:
    """Swap quantizable sublayers of a dygraph model for fake-quantized
    versions (qat.py:54). After ``quantize(model)``, training proceeds
    normally — the fake-quant ops carry straight-through gradients."""

    _QUANTIZABLE = {Conv2D: QuantizedConv2D, Linear: QuantizedLinear}

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 quantizable_layer_type=("Conv2D", "Linear"),
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max",
                 weight_preprocess_layer=None, act_preprocess_layer=None,
                 weight_quantize_layer=None, act_quantize_layer=None):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._moving_rate = moving_rate
        self._types = set(quantizable_layer_type)
        self._weight_qt = weight_quantize_type
        self._act_qt = activation_quantize_type

    def _wrap(self, layer):
        for cls, qcls in self._QUANTIZABLE.items():
            if isinstance(layer, cls) and cls.__name__ in self._types:
                return qcls(layer, weight_bits=self._weight_bits,
                            activation_bits=self._activation_bits,
                            moving_rate=self._moving_rate,
                            weight_quantize_type=self._weight_qt,
                            activation_quantize_type=self._act_qt)
        return None

    def quantize(self, model):
        """In-place: replace each quantizable sublayer (qat.py:241)."""
        self._walk(model)
        return model

    def _walk(self, layer):
        for name, child in list(layer._sub_layers.items()):
            q = self._wrap(child)
            if q is not None:
                setattr(layer, name, q)
            else:
                self._walk(child)

    def save_quantized_model(self, layer, path, input_spec=None, **config):
        from .. import jit
        layer.eval()
        jit.save(layer, path, input_spec=input_spec, **config)


class ImperativeCalcOutScale:
    """Attach out-scale collectors after activation-producing layers
    (qat.py:299). Collected scales live in each collector's ``scale``
    buffer and are saved with state_dict.

    Coverage spans every layer the freeze pass can rewrite or whose
    output feeds a rewrite site — including the already-swapped
    QuantizedConv2D/QuantizedLinear wrappers, so the canonical
    ``quantize(model)`` → ``calc_out_scale(model)`` order leaves each
    int8 site with a recorded out-scale for its requantize epilogue
    (quantization_pass.py out_scale fold)."""

    _OUT_SCALE_TYPES = ("ReLU", "ReLU6", "LeakyReLU", "PReLU", "ELU",
                        "GELU", "Hardswish", "Hardsigmoid", "Sigmoid",
                        "Softmax", "Tanh", "Swish", "Mish",
                        "Conv2D", "Conv2DTranspose", "Linear",
                        "QuantizedConv2D", "QuantizedLinear",
                        "BatchNorm", "BatchNorm1D", "BatchNorm2D",
                        "BatchNorm3D", "SyncBatchNorm", "LayerNorm",
                        "MaxPool2D", "AvgPool2D", "AdaptiveAvgPool2D",
                        "AdaptiveMaxPool2D")

    def __init__(self, moving_rate=0.9):
        self._moving_rate = moving_rate

    def calc_out_scale(self, model):
        self._walk(model)
        return model

    def _walk(self, layer):
        for name, child in list(layer._sub_layers.items()):
            if isinstance(child, _OutScaleWrapper):
                continue                      # idempotent: already collected
            if type(child).__name__ in self._OUT_SCALE_TYPES:
                setattr(layer, name, _OutScaleWrapper(
                    child, self._moving_rate))
            else:
                self._walk(child)


class _OutScaleWrapper(L.Layer):
    def __init__(self, inner, moving_rate):
        super().__init__()
        self._inner = inner
        self._out_scale = MovingAverageAbsMaxScale(moving_rate=moving_rate)

    def forward(self, *args, **kwargs):
        return self._out_scale(self._inner(*args, **kwargs))
