"""Post-training quantization.

Reference parity: python/paddle/fluid/contrib/slim/quantization/
post_training_quantization.py — PostTrainingQuantization (activation-scale
calibration over sample batches: abs_max / avg / hist(percentile) algos)
and WeightQuantization (weight-only int8 shrinking).

TPU-shape: calibration runs the eager model under observers; the produced
quantized model keeps int8 weights + fp32 scales and dequantizes at load —
XLA folds the dequant convert into the consuming matmul/conv, so int8
storage costs nothing at step time.
"""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor
from ..nn.layer.common import Linear
from ..nn.layer.conv import Conv2D
from . import functional as QF
from .qat import ImperativeQuantAware


class PostTrainingQuantization:
    """Calibrate activation scales on sample data, then fake-quant-fold.

    Dygraph-first API (the reference's Executor/Program variant maps to
    the static path via the same observers): pass a ``model`` and a
    ``data_loader``; ``quantize()`` runs ``batch_nums`` calibration
    batches and returns the model with per-layer activation scales set
    and weights quantized per-channel.
    """

    def __init__(self, model=None, data_loader=None, batch_nums=10,
                 algo="abs_max", hist_percent=0.99999,
                 quantizable_op_type=("conv2d", "linear"),
                 weight_bits=8, activation_bits=8,
                 weight_quantize_type="channel_wise_abs_max",
                 executor=None, scope=None, model_dir=None, **kwargs):
        if algo not in ("abs_max", "avg", "hist", "KL", "mse"):
            raise ValueError(f"unknown algo {algo}")
        self._model = model
        self._loader = data_loader
        self._batch_nums = batch_nums
        self._algo = algo
        self._hist_percent = hist_percent
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits
        self._weight_quantize_type = weight_quantize_type
        self._observed = {}
        self._observed_out = {}

    # -- calibration ---------------------------------------------------------
    def _observe(self, name):
        store = self._observed.setdefault(name, [])
        store_out = self._observed_out.setdefault(name, [])

        def hook(layer, inputs, output):
            x = inputs[0] if isinstance(inputs, (tuple, list)) else inputs
            store.append(float(np.max(np.abs(np.asarray(x.numpy())))))
            # out-scale observation: the freeze pass folds it into the
            # int8 site's requantize epilogue (quantization_pass.py
            # out_scale), so PTQ flows get epilogue scales without QAT
            y = output[0] if isinstance(output, (tuple, list)) else output
            if hasattr(y, "numpy"):
                store_out.append(
                    float(np.max(np.abs(np.asarray(y.numpy())))))
            return None

        return hook

    def quantize(self):
        """Run calibration then swap to quantized layers with the
        calibrated activation scales baked in."""
        model = self._model
        hooks = []
        for name, sub in model.named_sublayers():
            if isinstance(sub, (Conv2D, Linear)):
                hooks.append(sub.register_forward_post_hook(
                    self._observe(name)))
        model.eval()
        for i, batch in enumerate(self._loader):
            if i >= self._batch_nums:
                break
            x = batch[0] if isinstance(batch, (tuple, list)) else batch
            model(x)
        for h in hooks:
            h.remove()
        # reduce observations to one scale per layer
        def _reduce(obs):
            a = np.asarray(obs, "float64")
            if self._algo == "avg":
                return float(a.mean())
            if self._algo in ("hist", "KL", "mse"):
                return float(np.quantile(a, self._hist_percent))
            return float(a.max())

        self._scales = {n: _reduce(o) for n, o in self._observed.items()}
        self._out_scales = {n: _reduce(o)
                            for n, o in self._observed_out.items() if o}
        # swap to QAT layers in test mode with the calibrated input scale
        ImperativeQuantAware(
            weight_bits=self._weight_bits,
            activation_bits=self._activation_bits,
            weight_quantize_type=self._weight_quantize_type).quantize(model)
        for name, sub in model.named_sublayers():
            fq = getattr(sub, "_fake_quant_input", None)
            if fq is not None and hasattr(fq, "scale"):
                base = name.rsplit("._fake_quant_input", 1)[0] \
                    if name.endswith("_fake_quant_input") else name
                s = self._scales.get(base)
                if s is not None:
                    fq.scale._value = fq.scale._value * 0 + s
                    fq.accum._value = fq.accum._value * 0 + s
                    fq.state._value = fq.state._value * 0 + 1.0
        # record the calibrated OUTPUT scale on each quantized site (the
        # module tree stays intact — no wrapper insertion post-swap); the
        # freeze pass folds it into the int8 requantize epilogue, so PTQ
        # flows reach freeze with an out-scale at every rewrite site just
        # like the QAT calc_out_scale flow
        from .quant_layers import QuantizedConv2D, QuantizedLinear
        for name, sub in model.named_sublayers():
            if isinstance(sub, (QuantizedConv2D, QuantizedLinear)):
                s = self._out_scales.get(name)
                if s is not None:
                    sub._frozen_out_scale = float(s)
        model.eval()
        return model

    def save_quantized_model(self, save_model_path, **config):
        from .. import jit
        jit.save(self._model, save_model_path, **config)


class WeightQuantization:
    """Weight-only int8 quantization (post_training_quantization.py:884):
    shrink a model's conv/linear weights to int8 + per-channel scales and
    dequantize back — storage-compression parity without touching
    activations."""

    def __init__(self, model):
        self._model = model

    def quantize_weight_to_int8(self, weight_bits=8,
                                quantizable_op_type=("conv2d", "linear")):
        packed = {}
        for name, sub in self._model.named_sublayers():
            if isinstance(sub, Conv2D):
                axis = 0
            elif isinstance(sub, Linear):
                axis = 1
            else:
                continue
            q, s = QF.quantize_weight_int8(sub.weight, quant_axis=axis,
                                           bit_length=weight_bits)
            packed[name] = (q, s)
            deq = QF.dequantize_weight(q, s, bit_length=weight_bits)
            sub.weight._value = deq._value
        return packed
