"""Fake-quantization primitives (QAT simulation ops).

Reference parity: paddle/fluid/operators/fake_quantize_op.cc —
fake_quantize_dequantize_abs_max, fake_quantize_dequantize_moving_average_
abs_max, fake_channel_wise_quantize_dequantize_abs_max — and
fake_dequantize_op.cc. The reference registers a forward kernel plus a
straight-through FakeQuantDequantGrad op; here the straight-through
estimator is one ``jax.custom_vjp`` and everything stays a pure fused XLA
expression (round/clip are cheap VPU ops on TPU — no custom kernel needed).

Moving-average state is functional: the op returns the new (scale, accum,
state) instead of mutating buffers in place, and the QAT layers thread it
(the TPU idiom for mutable quant state under jit).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


def _qdq(x, scale, qmax):
    s = jnp.maximum(scale, 1e-9)
    return jnp.round(jnp.clip(x / s, -1.0, 1.0) * qmax) * (s / qmax)


@jax.custom_vjp
def _qdq_ste(x, scale, qmax):
    return _qdq(x, scale, qmax)


def _qdq_fwd(x, scale, qmax):
    return _qdq(x, scale, qmax), (x, scale)


def _qdq_bwd(res, g):
    x, scale = res
    # straight-through inside the clip range (FakeQuantDequantGradOp)
    mask = (jnp.abs(x) <= jnp.maximum(scale, 1e-9)).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale), None


_qdq_ste.defvjp(_qdq_fwd, _qdq_bwd)


def _fake_qdq_abs_max_fn(x, bit_length=8):
    qmax = float(2 ** (bit_length - 1) - 1)
    scale = jnp.max(jnp.abs(x))
    return _qdq_ste(x, scale, qmax), scale


_fake_qdq_abs_max = Primitive("fake_quantize_dequantize_abs_max",
                              _fake_qdq_abs_max_fn, multi_output=True)


def fake_quantize_dequantize_abs_max(x, bit_length=8):
    """Per-tensor abs-max quant-dequant; returns (out, scale)."""
    return _fake_qdq_abs_max(x, bit_length=int(bit_length))


def _fake_qdq_channel_fn(x, bit_length=8, quant_axis=0):
    qmax = float(2 ** (bit_length - 1) - 1)
    axes = tuple(i for i in range(x.ndim) if i != quant_axis)
    scale = jnp.max(jnp.abs(x), axis=axes, keepdims=True)
    return _qdq_ste(x, scale, qmax), scale.reshape(-1)


_fake_qdq_channel = Primitive(
    "fake_channel_wise_quantize_dequantize_abs_max", _fake_qdq_channel_fn,
    multi_output=True)


def fake_channel_wise_quantize_dequantize_abs_max(x, bit_length=8,
                                                  quant_axis=0):
    """Per-channel abs-max quant-dequant; returns (out, scales[C])."""
    return _fake_qdq_channel(x, bit_length=int(bit_length),
                             quant_axis=int(quant_axis))


def _fake_qdq_moving_fn(x, in_scale, in_accum, in_state, moving_rate=0.9,
                        bit_length=8, is_test=False):
    qmax = float(2 ** (bit_length - 1) - 1)
    if is_test:
        return _qdq_ste(x, in_scale, qmax), in_scale, in_accum, in_state
    cur = jnp.max(jnp.abs(x))
    state = in_state * moving_rate + 1.0
    accum = in_accum * moving_rate + cur
    scale = accum / state
    return _qdq_ste(x, scale, qmax), scale, accum, state


_fake_qdq_moving = Primitive(
    "fake_quantize_dequantize_moving_average_abs_max", _fake_qdq_moving_fn,
    multi_output=True)


def fake_quantize_dequantize_moving_average_abs_max(
        x, scale, accum, state, moving_rate=0.9, bit_length=8,
        is_test=False):
    """Moving-average abs-max quant-dequant.

    Returns (out, new_scale, new_accum, new_state) — functional state
    threading replaces the reference's in-place InScale/OutScale buffers.
    """
    return _fake_qdq_moving(x, scale, accum, state,
                            moving_rate=float(moving_rate),
                            bit_length=int(bit_length), is_test=bool(is_test))


def _moving_average_abs_max_scale_fn(x, in_accum, in_state, moving_rate=0.9,
                                     is_test=False):
    if is_test:
        return in_accum / jnp.maximum(in_state, 1e-9), in_accum, in_state
    cur = jnp.max(jnp.abs(x))
    state = in_state * moving_rate + 1.0
    accum = in_accum * moving_rate + cur
    return accum / state, accum, state


_moving_scale = Primitive("moving_average_abs_max_scale",
                          _moving_average_abs_max_scale_fn,
                          multi_output=True, differentiable=False)


def moving_average_abs_max_scale(x, accum, state, moving_rate=0.9,
                                 is_test=False):
    """Track an activation's moving-average abs-max (out-scale collection,
    quant_nn.MovingAverageAbsMaxScale). Returns (scale, accum, state)."""
    return _moving_scale(x, accum, state, moving_rate=float(moving_rate),
                         is_test=bool(is_test))


def quantize_weight_int8(w, quant_axis=0, bit_length=8):
    """True int8 weight quantization for PTQ storage and the freeze pass:
    returns (int8 weights, fp32 scales) — per-channel along ``quant_axis``,
    or per-tensor when ``quant_axis=None``. Dequantize with
    ``dequantize_weight`` (fake_dequantize_op.cc DequantizeMaxAbs)."""
    wv = unwrap(w)
    qmax = float(2 ** (bit_length - 1) - 1)
    if quant_axis is None:
        axes = tuple(range(wv.ndim))
    else:
        axes = tuple(i for i in range(wv.ndim) if i != quant_axis)
    scale = jnp.maximum(jnp.max(jnp.abs(wv), axis=axes, keepdims=True), 1e-9)
    q = jnp.round(wv / scale * qmax).astype(jnp.int8)
    return Tensor(q), Tensor(scale)


def dequantize_weight(q, scale, bit_length=8, dtype=jnp.float32):
    qmax = float(2 ** (bit_length - 1) - 1)
    return Tensor(unwrap(q).astype(dtype) * (unwrap(scale) / qmax))
