"""Industrial / research long-tail operators.

Reference parity: the CTR-industrial and research ops the reference keeps
in operators/ behind no flag but outside the 2.0 API surface —
batch_fc_op.h (per-slot batched FC), fsp_op.h (FSP distillation matrix),
shuffle_batch_op.cc, hash_op.h (multi-hash bucketing), spp_op.h (spatial
pyramid pooling), positive_negative_pair_op.h (ranking pair metric),
tdm_child_op.h (TDM tree child lookup), nce_op.h (noise-contrastive
estimation).

TPU-first: each op is a small jnp composition (vectorized, no LoD loops);
hashing deviates from the reference's XXH64 (a bit-mix with the same
bucketing contract — hash values are an implementation detail nobody
checkpoints).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


def _arr(x):
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)


def _is_host(t: Tensor) -> bool:
    """True when the tensor's value is concretely readable (not traced)."""
    return not isinstance(unwrap(t), jax.core.Tracer)


def _batch_fc_fn(x, w, bias):
    out = jnp.einsum("sbi,sio->sbo", x, w)
    return out + bias[:, None, :]


_batch_fc_p = Primitive("batch_fc", _batch_fc_fn)


def batch_fc(input, w, bias=None):
    """batch_fc_op.h: per-slot batched FC (grad kernel: batch_fc_grad).
    input [S, B, In] · w [S, In, Out] (+ bias [S, Out]) → [S, B, Out]."""
    if bias is None:
        wa = _arr(w)
        bias = jnp.zeros((wa.shape[0], wa.shape[2]), wa.dtype)
    return _batch_fc_p(input, w, bias)


def _fsp_fn(x, y):
    h, w = x.shape[2], x.shape[3]
    return jnp.einsum("bchw,bdhw->bcd", x, y) / (h * w)


_fsp_p = Primitive("fsp", _fsp_fn)


def fsp_matrix(x, y):
    """fsp_op.h: flow-of-solution-procedure matrix for distillation
    (grad kernel: fsp_grad — FSP losses backprop into BOTH feature maps).
    x [B, C1, H, W], y [B, C2, H, W] → [B, C1, C2] = x·yᵀ / (H·W)."""
    return _fsp_p(x, y)


def _fresh_key(seed):
    """Explicit seed → deterministic key; None → the framework generator's
    NEXT key (advances per call, like the reference's Seed/SeedOut chain —
    a fixed default key would repeat the 'randomness' every step)."""
    if seed is not None:
        return jax.random.PRNGKey(int(seed))
    from ..framework.random import default_generator
    return default_generator.next_key()


def _shuffle_gather_fn(x, idx):
    flat = x.reshape(idx.shape[0], -1)   # lead = all dims but the last
    return flat[idx].reshape(x.shape)


_shuffle_gather_p = Primitive("shuffle_batch", _shuffle_gather_fn)


def shuffle_batch(x, seed=None):
    """shuffle_batch_op.cc: shuffle rows (all dims but the last collapse
    to the shuffled axis).  Returns (shuffled, shuffle_idx) — the index
    tensor the reference emits for the backward re-ordering; here the
    backward is the vjp of the gather (a scatter through the permutation,
    shuffle_batch_grad parity).  ``seed=None`` draws from the framework
    generator, re-shuffling on every call."""
    lead = int(np.prod(_arr(x).shape[:-1]))
    idx = jax.random.permutation(_fresh_key(seed), lead)
    return _shuffle_gather_p(x, idx), Tensor(idx)


def hash_bucket(x, num_hash: int = 1, mod_by: int = 1 << 20):
    """hash_op.h: each input row hashes ``num_hash`` times (seeded 0..n-1)
    into [0, mod_by) — the CTR multi-hash embedding trick.  Deviation from
    the reference: a splitmix-style integer mix instead of XXH64; the
    contract (deterministic, seed-distinct, well-spread buckets) holds.
    x [N, D] int → [N, num_hash, 1] int64-ish."""
    # hash the FULL 64-bit id as two 32-bit halves (truncating to the low
    # word would collide every pair of ids equal mod 2^32 under ALL seeds).
    # The split happens HOST-side in numpy: with jax x64 disabled a device
    # array cannot hold the high word at all.
    if isinstance(x, Tensor) and _is_host(x) or isinstance(
            x, (np.ndarray, list, tuple)):
        raw_np = np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                            np.int64)
        lo = jnp.asarray((raw_np & 0xFFFFFFFF).astype(np.uint32))
        hi = jnp.asarray(((raw_np >> 32) & 0xFFFFFFFF).astype(np.uint32))
    else:
        # traced/device: 32-bit ids only (x64 off); two's-complement
        # reinterpretation — masking with the 0xFFFFFFFF literal would
        # overflow int32 argument parsing.  A 64-bit dtype reaching this
        # branch (x64 enabled) WOULD lose its high word to the int32 cast
        # below — warn, because that is the exact collision class the host
        # branch guards against.  (32-bit ids have no high word; ids
        # truncated earlier at device transfer already warned there.)
        xa = _arr(x)
        if jnp.dtype(xa.dtype).itemsize >= 8:
            import warnings
            warnings.warn(
                "hash_bucket: traced 64-bit ids hash only the low 32 bits "
                "(the device mix runs on uint32); ids differing only above "
                "bit 31 will collide. Pass the raw ids host-side "
                "(numpy/list or host Tensor) to hash the full 64 bits.",
                RuntimeWarning, stacklevel=2)
        lo = xa.astype(jnp.int32).view(jnp.uint32)
        hi = jnp.zeros_like(lo)

    def mix(v, salt):
        h = v ^ jnp.uint32((salt * 0x9E3779B9) & 0xFFFFFFFF)
        h = (h ^ (h >> 16)) * jnp.uint32(0x85EBCA6B)
        h = (h ^ (h >> 13)) * jnp.uint32(0xC2B2AE35)
        return h ^ (h >> 16)

    outs = []
    for s in range(int(num_hash)):
        acc = jnp.uint32(s + 1)
        for d in range(lo.shape[-1]):
            acc = mix(lo[..., d] ^ acc, s + 1)
            acc = mix(hi[..., d] ^ acc, s + 1)
        outs.append((acc % jnp.uint32(mod_by)).astype(jnp.int32))
    return Tensor(jnp.stack(outs, axis=-1)[..., None])


def _spp_fn(x, pyramid_height=3, pool_type="max"):
    from ..nn.functional.pooling import _adaptive_pool_fn
    parts = []
    for level in range(int(pyramid_height)):
        bins = 2 ** level
        p = _adaptive_pool_fn(x, out_size=(bins, bins), kind=pool_type)
        parts.append(p.reshape(x.shape[0], -1))
    return jnp.concatenate(parts, axis=1)


_spp_p = Primitive("spp", _spp_fn)


def spp(x, pyramid_height: int = 3, pool_type: str = "max"):
    """spp_op.h: spatial pyramid pooling — concat of adaptive pools at
    1,2,4,…,2^(h-1) bins (grad kernel: spp_grad via the pool vjps).
    x [N, C, H, W] → [N, C·Σ bins²]."""
    if pool_type not in ("max", "avg"):
        raise ValueError(f"spp pool_type must be 'max' or 'avg', "
                         f"got {pool_type!r}")
    return _spp_p(x, pyramid_height=int(pyramid_height), pool_type=pool_type)


def positive_negative_pair(score, label, query_id, weight=None, column=-1):
    """positive_negative_pair_op.h: within each query, count document
    pairs ordered correctly (positive), inverted (negative), or tied
    (neutral) by score vs label — the PN-pair ranking metric.  Host-side
    metric (the reference computes on CPU); returns three 1-element
    Tensors."""
    s = np.asarray(score.numpy() if isinstance(score, Tensor) else score)
    l = np.asarray(label.numpy() if isinstance(label, Tensor)
                   else label).ravel()
    q = np.asarray(query_id.numpy() if isinstance(query_id, Tensor)
                   else query_id).ravel()
    w = (np.ones(len(l), np.float64) if weight is None
         else np.asarray(weight.numpy() if isinstance(weight, Tensor)
                         else weight).ravel())
    if s.ndim > 1:
        s = s[:, column]
    pos = neg = neu = 0.0
    for qid in np.unique(q):
        sel = q == qid
        ss, ll, ww = s[sel], l[sel], w[sel]
        # vectorized pair enumeration (upper triangle, label-distinct)
        n = len(ss)
        iu, ju = np.triu_indices(n, k=1)
        diff = ll[iu] != ll[ju]
        if not diff.any():
            continue
        iu, ju = iu[diff], ju[diff]
        pw = (ww[iu] + ww[ju]) * 0.5
        tied = ss[iu] == ss[ju]
        correct = (ss[iu] - ss[ju]) * (ll[iu] - ll[ju]) > 0
        neu += pw[tied].sum()
        pos += pw[~tied & correct].sum()
        neg += pw[~tied & ~correct].sum()
    mk = lambda v: Tensor(jnp.asarray([v], jnp.float32))  # noqa: E731
    return mk(pos), mk(neg), mk(neu)


def filter_by_instag(ins, ins_tag, filter_tag, out_val_if_empty: int = 0,
                     pad_value: int = -1):
    """filter_by_instag_op.h: keep the rows whose tag set intersects the
    filter tags — the industrial sample router (e.g. train one tower on a
    sub-population of a mixed batch).

    ``ins`` [N, ...] rows; ``ins_tag`` [N, K] per-row tags padded with
    ``pad_value``; ``filter_tag`` [M].  Returns (out [kept, ...],
    loss_weight [kept, 1], index_map [kept, 2] of (out_row, src_row)).
    When nothing matches, emits ONE row filled with ``out_val_if_empty``
    and loss weight 0 (the reference's empty-output contract).  Host-side:
    the output size is data-dependent (CPU-only kernel in the reference
    too)."""
    x = np.asarray(ins.numpy() if isinstance(ins, Tensor) else ins)
    tags = np.asarray(ins_tag.numpy() if isinstance(ins_tag, Tensor)
                      else ins_tag)
    want = np.asarray(filter_tag.numpy() if isinstance(filter_tag, Tensor)
                      else filter_tag).ravel()
    keep = (np.isin(tags, want) & (tags != pad_value)).any(axis=1)
    idx = np.nonzero(keep)[0]
    if len(idx) == 0:
        out = np.full((1,) + x.shape[1:], out_val_if_empty, x.dtype)
        lw = np.zeros((1, 1), np.float32)
        imap = np.zeros((1, 2), np.int64)
    else:
        out = x[idx]
        lw = np.ones((len(idx), 1), np.float32)
        imap = np.stack([np.arange(len(idx)), idx], axis=1).astype(np.int64)
    return (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lw)),
            Tensor(jnp.asarray(imap)))


def tdm_child(x, tree_info, child_nums: int):
    """tdm_child_op.h: gather each node's children from the TDM tree table.
    tree_info rows are [item_id, layer_id, ancestor_id, child_0, …]; a
    node with no children (or node 0) yields zeros.  Returns
    (child [N, child_nums], leaf_mask [N, child_nums]) where mask=1 marks
    children that are items (leaf nodes, item_id != 0)."""
    xa = _arr(x).astype(jnp.int32).reshape(-1)
    info = _arr(tree_info).astype(jnp.int32)
    children = info[xa, 3:3 + child_nums]                    # [N, C]
    has_child = ((xa != 0) & (info[xa, 3] != 0))[:, None]
    children = jnp.where(has_child, children, 0)
    is_item = (info[children, 0] != 0).astype(jnp.int32)
    mask = jnp.where(has_child, is_item, 0)
    return Tensor(children), Tensor(mask)


def _attention_lstm_fn(x, lengths, c0, h0, attn_w, attn_b, scalar,
                       scalar_b, lstm_w, lstm_b):
    """attention_lstm_op.cc math over masked-dense sequences.

    Per step t: scores = relu(scalar·relu([x, tile(c)]·attn_w + attn_b)
    + scalar_b) softmaxed over the valid positions; context = Σ att·x;
    gates = [context, h]·lstm_w + lstm_b (i, f, c̃, o); standard LSTM
    update.  x [B, T, M]; returns hidden states [B, T, D] (positions past
    each length zeroed)."""
    B, T, M = x.shape
    D = c0.shape[-1]
    mask = (jnp.arange(T)[None, :] < lengths[:, None])           # [B, T]
    w_x, w_c = attn_w[:M], attn_w[M:]                            # [(M|D),1]
    sx = jnp.einsum("btm,mo->bto", x, w_x)[..., 0]               # [B, T]

    def step(carry, t):
        h, c = carry
        s = sx + (c @ w_c)[..., 0][:, None] + attn_b.reshape(())
        s = jnp.maximum(s, 0.0)
        s = jnp.maximum(s * scalar.reshape(()) + scalar_b.reshape(()), 0.0)
        s = jnp.where(mask, s, -jnp.inf)
        att = jax.nn.softmax(s, axis=1)                          # [B, T]
        ctx = jnp.einsum("bt,btm->bm", att, x)                   # [B, M]
        gates = jnp.concatenate([ctx, h], axis=-1) @ lstm_w + lstm_b
        i, f, cc, o = jnp.split(gates, 4, axis=-1)
        c_new = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(cc)
        h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
        # steps past a sequence's length freeze its carry, so the final
        # (h, c) is the state at its LAST VALID step
        alive = (t < lengths)[:, None]
        h_new = jnp.where(alive, h_new, h)
        c_new = jnp.where(alive, c_new, c)
        return (h_new, c_new), h_new

    (h_fin, c_fin), hs = jax.lax.scan(step, (h0, c0), jnp.arange(T))
    out = jnp.transpose(hs, (1, 0, 2)) * mask[..., None]         # [B, T, D]
    return out, h_fin, c_fin


_attention_lstm_p = Primitive("attention_lstm", _attention_lstm_fn,
                              multi_output=True)


def attention_lstm(x, lengths, c0, h0, attn_w, attn_b, attn_scalar,
                   attn_scalar_b, lstm_w, lstm_b):
    """attention_lstm_op.cc parity: per-step attention over the sequence
    conditioned on the previous cell state, feeding a standard LSTM.
    Masked-dense carrier (x [B, T, M] + lengths) instead of LoD."""
    return _attention_lstm_p(x, lengths, c0, h0, attn_w, attn_b,
                             attn_scalar, attn_scalar_b, lstm_w, lstm_b)


def tdm_sampler(x, travel, layer, neg_samples_num_list, layer_offset_lod,
                output_positive: bool = True, seed: int = None):
    """tdm_sampler_op.h: per-layer positive + negative sampling along each
    leaf's tree path (the TDM training-pair generator).

    ``x`` [N] item ids index rows of ``travel`` [n_items, n_layers] (the
    path node at each layer; 0 = padding); ``layer`` is the flat node-id
    array with ``layer_offset_lod`` giving each layer's [start, end)
    range.  Negatives draw uniformly WITHOUT replacement from the layer,
    never equal to the positive (the reference's rejection loop).  Returns
    (out [N, L], labels [N, L], mask [N, L]) with L = Σ(neg_i +
    output_positive); padding layers emit zeros with mask 0.  Host-side —
    it is a data-prep op in the reference too (CPU-only kernel)."""
    if seed is None:
        # derive from the framework generator so paddle.seed() pins TDM
        # sampling like every other sampling op here
        seed = int(jax.random.randint(_fresh_key(None), (), 0, (1 << 31) - 1))
    rng = np.random.RandomState(seed)
    ids = np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                     np.int64).ravel()
    trav = np.asarray(travel.numpy() if isinstance(travel, Tensor)
                      else travel, np.int64)
    lay = np.asarray(layer.numpy() if isinstance(layer, Tensor)
                     else layer, np.int64).ravel()
    offs = list(layer_offset_lod)
    negs = list(neg_samples_num_list)
    pos = 1 if output_positive else 0
    L = sum(n + pos for n in negs)
    out = np.zeros((len(ids), L), np.int64)
    lab = np.zeros((len(ids), L), np.int64)
    mask = np.ones((len(ids), L), np.int64)
    for i, item in enumerate(ids.tolist()):
        off = 0
        for li, n_neg in enumerate(negs):
            nodes = lay[offs[li]:offs[li + 1]]
            positive = int(trav[item, li])
            width = n_neg + pos
            if positive == 0:                     # padding layer
                out[i, off:off + width] = 0
                lab[i, off:off + width] = 0
                mask[i, off:off + width] = 0
                off += width
                continue
            if pos:
                out[i, off] = positive
                lab[i, off] = 1
                off += 1
            cand = nodes[nodes != positive]
            if n_neg > len(cand):
                raise ValueError(
                    f"tdm_sampler: layer {li} has {len(nodes)} nodes — "
                    f"cannot draw {n_neg} negatives distinct from the "
                    f"positive; lower neg_samples_num_list[{li}]")
            pick = rng.choice(len(cand), size=n_neg, replace=False)
            out[i, off:off + n_neg] = cand[pick]
            lab[i, off:off + n_neg] = 0
            off += n_neg
    return (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(lab)),
            Tensor(jnp.asarray(mask)))


def _nce_fn(x, lab, wt, b, key_data, num_neg_samples=10,
            num_total_classes=0):
    # the key travels as RAW int32 data (static Variables cannot carry
    # typed PRNG-key avals); rebuild the typed key here
    from ..framework.random import ensure_key
    key = ensure_key(key_data)
    lab = lab.astype(jnp.int32).reshape(-1)
    v = int(num_total_classes) or wt.shape[0]
    neg = jax.random.randint(key, (x.shape[0], int(num_neg_samples)), 0, v)
    log_q = jnp.log(jnp.asarray(num_neg_samples / v, x.dtype))
    s_true = jnp.einsum("bd,bd->b", x, wt[lab]) + b[lab] - log_q
    s_neg = jnp.einsum("bd,bnd->bn", x, wt[neg]) + b[neg] - log_q
    loss = (jax.nn.softplus(-s_true) +
            jax.nn.softplus(s_neg).sum(axis=1))
    return loss[:, None]


_nce_p = Primitive("nce", _nce_fn)


def nce_loss(input, label, weight, bias=None, num_neg_samples: int = 10,
             num_total_classes: int = None, seed: int = None):
    """nce_op.h: noise-contrastive estimation with a uniform sampler.
    input [B, D], label [B], weight [V, D], bias [V] →  per-example loss
    [B, 1]: −log σ(s_true − log q) − Σ_neg log(1 − σ(s_neg − log q)),
    q = num_neg/V (uniform sampler probability mass per draw).
    ``seed=None`` draws FRESH negatives from the framework generator each
    call — a fixed default seed would pin the negative set and degenerate
    training.  Registered as a primitive, so it records into static
    programs; there the key rides a persistable refreshed by a pre-run
    hook (the Executor's lr-feed pattern), so every exe.run resamples."""
    from ..framework import core
    if num_total_classes:
        v = int(num_total_classes)
    elif hasattr(weight, "shape"):
        v = int(weight.shape[0])
    else:
        v = len(weight)
    if bias is None:
        bias = jnp.zeros((int(v),), jnp.float32)
    if core.in_static_mode() and seed is None:
        from ..framework.random import static_advancing_key
        key = static_advancing_key("nce")   # advances per run AND per scan step
    else:
        from ..framework.random import key_raw
        key = key_raw(_fresh_key(seed))
    return _nce_p(input, label, weight, bias, key,
                  num_neg_samples=int(num_neg_samples),
                  num_total_classes=int(v))





def _match_matrix_fn(x, y, w, x_len, y_len):
    out = jnp.einsum("bxd,dte,bye->btxy", x.astype(jnp.float32),
                     w.astype(jnp.float32), y.astype(jnp.float32))
    tx, ty = x.shape[1], y.shape[1]
    mx = jnp.arange(tx)[None, :] < x_len[:, None]        # [B, Tx]
    my = jnp.arange(ty)[None, :] < y_len[:, None]        # [B, Ty]
    return out * (mx[:, None, :, None] & my[:, None, None, :])


_match_matrix_p = Primitive("match_matrix_tensor", _match_matrix_fn)


def match_matrix_tensor(x, y, w, x_len, y_len):
    """match_matrix_tensor_op.h: the text-matching bilinear match matrix
    out[b,t,i,j] = xᵢ·W_t·yⱼ over a left/right sequence pair.  Masked
    dense: x [B, Tx, D], y [B, Ty, D], w [D, dim_t, D], per-example
    lengths → [B, dim_t, Tx, Ty] with invalid cells zeroed."""
    return _match_matrix_p(x, y, w, x_len, y_len)


def _topk_avg_pool_fn(x, row_len, col_len, topks=(1,)):
    # x [B, C, Tx, Ty]: per (b, c, row), average the top-k valid columns
    b, c, tx, ty = x.shape
    valid = jnp.arange(ty)[None, None, None, :] < \
        col_len[:, None, None, None]                     # [B,1,1,Ty]
    neg = jnp.asarray(-jnp.inf, jnp.float32)
    xs = jnp.where(valid, x.astype(jnp.float32), neg)
    xs = jnp.sort(xs, axis=-1)[..., ::-1]                # desc
    csum = jnp.cumsum(jnp.where(jnp.isfinite(xs), xs, 0.0), axis=-1)
    outs = []
    for k in topks:
        # average over min(k, n_valid) entries (reference divides by the
        # ACTUAL count when the row has fewer than k valid columns)
        kk = jnp.minimum(jnp.asarray(int(k)), col_len)[:, None, None]
        idx = jnp.clip(kk - 1, 0, ty - 1)
        top_sum = jnp.take_along_axis(
            csum, jnp.broadcast_to(idx[..., None], (b, c, tx, 1)), axis=-1
        )[..., 0]
        outs.append(jnp.where(kk > 0, top_sum / jnp.maximum(kk, 1), 0.0))
    out = jnp.stack(outs, axis=-1)                       # [B, C, Tx, K]
    out = jnp.transpose(out, (0, 2, 1, 3)).reshape(b, tx, c * len(topks))
    rows = jnp.arange(tx)[None, :] < row_len[:, None]
    return out * rows[..., None]


_topk_avg_p = Primitive("sequence_topk_avg_pooling", _topk_avg_pool_fn)


def sequence_topk_avg_pooling(x, row_len, col_len, topks, channel_num=None):
    """sequence_topk_avg_pooling_op.h: per match-matrix row, average the
    top-k valid columns for each k in ``topks`` (fewer-than-k rows divide
    by the actual count).  Masked dense: x [B, C, Tx, Ty] + row/col
    lengths → [B, Tx, C·len(topks)]."""
    c = _arr(x).shape[1]
    if channel_num is not None and int(channel_num) != c:
        raise ValueError(
            f"sequence_topk_avg_pooling: channel_num={channel_num} does "
            f"not match the input's channel axis ({c})")
    return _topk_avg_p(x, row_len, col_len,
                       topks=tuple(int(k) for k in topks))


def _var_conv_2d_fn(x, w, row_len, col_len, stride=(1, 1)):
    from ..nn.functional.conv import _conv_fn
    h, wd = x.shape[2], x.shape[3]
    mh = jnp.arange(h)[None, :] < row_len[:, None]
    mw = jnp.arange(wd)[None, :] < col_len[:, None]
    masked = x * (mh[:, None, :, None] & mw[:, None, None, :])
    out = _conv_fn(masked, w, None, stride=stride, padding="SAME")
    oh, ow = out.shape[2], out.shape[3]
    # valid output region shrinks per-axis with the SAME-padding grid
    sh, sw = stride
    rl = (row_len + sh - 1) // sh
    cl = (col_len + sw - 1) // sw
    mh2 = jnp.arange(oh)[None, :] < rl[:, None]
    mw2 = jnp.arange(ow)[None, :] < cl[:, None]
    return out * (mh2[:, None, :, None] & mw2[:, None, None, :])


_var_conv_2d_p = Primitive("var_conv_2d", _var_conv_2d_fn)


def var_conv_2d(x, w, row_len, col_len, stride=1, padding="SAME"):
    """var_conv_2d_op.h: convolution over variable-size 2D feature maps
    (each example's valid region differs; grad kernel: var_conv_2d_grad
    via the masked-conv vjp).  Masked dense: zero the invalid region, run
    ONE static conv, re-mask — the valid-output formula ceil(len/stride)
    is the SAME-padding grid, so other paddings are rejected rather than
    silently mislabeling zero-contaminated borders as valid.
    x [B, C, H, W], w [O, C, Kh, Kw]."""
    if padding != "SAME":
        raise NotImplementedError(
            "var_conv_2d supports padding='SAME' only (the masked-dense "
            "valid-region arithmetic is the SAME grid)")
    sh, sw = (stride, stride) if isinstance(stride, int) else \
        (stride[0], stride[1])
    return _var_conv_2d_p(x, w, row_len, col_len,
                          stride=(int(sh), int(sw)))
