"""Control-flow ops: while_loop / cond / case / switch_case.

Reference parity: the control-flow op family —
paddle/fluid/operators/controlflow/while_op.cc (sub-block body re-run until
the condition var flips), conditional_block_op.cc (guarded sub-block),
python/paddle/fluid/layers/control_flow.py (While :1038, while_loop :1104,
cond :2243, case :2862, switch_case :3035).

TPU-first, three execution regimes from ONE api:
  * eager (concrete Tensors): plain Python execution — the dygraph
    semantics; every iteration's ops land on the tape so backward works.
  * traced (inside jit / to_static / TrainStep): lowers to lax.while_loop /
    lax.cond — compiled, data-dependent control flow in one XLA program
    (what while_op's CPU-side loop over a sub-block can never be). Note
    XLA's while is not reverse-differentiable; use lax.scan-style bounded
    loops (or eager mode) when you need grads through a loop.
  * static Program recording: appends ONE macro op whose compiled form
    replays the user callables over tracer-backed Tensors inside
    lax.while_loop/lax.cond — the whole loop body fuses into the block's
    single XLA computation.
"""
from __future__ import annotations

from typing import Callable, List, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..framework import core
from ..framework.tensor import Tensor


def _unwrap(x):
    return x._value if isinstance(x, Tensor) else x


def _is_traced(vals) -> bool:
    return any(isinstance(v, jax.core.Tracer) for v in jax.tree_util.
               tree_leaves([_unwrap(v) for v in vals]))


def _wrap_tree(arrs):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a, stop_gradient=True), arrs)


def _unwrap_tree(t):
    return jax.tree_util.tree_map(
        _unwrap, t, is_leaf=lambda x: isinstance(x, Tensor))


def _tensor_fn_to_array_fn(fn):
    """Lift a Tensor-level callable to arrays (for lax lowering): arrays in,
    eager-dispatch the user's ops over tracer-backed Tensors, arrays out."""
    def run(*arrs):
        with core.dygraph_mode_guard(), core.no_grad_guard():
            out = fn(*_wrap_tree(list(arrs)))
        return _unwrap_tree(out)
    return run


def while_loop(cond: Callable, body: Callable, loop_vars: Sequence,
               is_test: bool = False, name: str = None) -> List:
    """paddle.static.nn.while_loop parity (control_flow.py:1104).

    cond(*vars) -> scalar bool; body(*vars) -> new vars (same structure).
    """
    if not loop_vars:
        raise ValueError("loop_vars of while_loop may not be empty")
    loop_vars = list(loop_vars)

    if core.in_static_mode():
        return _record_while(cond, body, loop_vars)

    if _is_traced(loop_vars):
        cfn = _tensor_fn_to_array_fn(cond)
        bfn = _tensor_fn_to_array_fn(body)
        arrs = tuple(_unwrap(v) for v in loop_vars)
        out = lax.while_loop(
            lambda vs: jnp.reshape(cfn(*vs), ()),
            lambda vs: tuple(jnp.asarray(x) for x in _as_tuple(bfn(*vs))),
            arrs)
        return [Tensor(o) for o in out]

    # eager: dygraph semantics (every iteration on the tape)
    while bool(_unwrap(cond(*loop_vars))):
        out = body(*loop_vars)
        loop_vars = list(out) if isinstance(out, (tuple, list)) else [out]
    return loop_vars


def cond(pred, true_fn: Callable = None, false_fn: Callable = None,
         name: str = None):
    """paddle.static.nn.cond parity (control_flow.py:2243): both branches
    must return the same structure."""
    if core.in_static_mode():
        return _record_cond(pred, true_fn, false_fn)

    pv = _unwrap(pred)
    if isinstance(pv, jax.core.Tracer):
        tfn = _tensor_fn_to_array_fn(lambda: true_fn())
        ffn = _tensor_fn_to_array_fn(lambda: false_fn())
        out = lax.cond(jnp.reshape(pv, ()).astype(bool),
                       lambda: _as_tuple(tfn()), lambda: _as_tuple(ffn()))
        return _rewrap_structure(out)

    return true_fn() if bool(pv) else false_fn()


def case(pred_fn_pairs, default: Callable = None, name: str = None):
    """fluid.layers.case parity (:2862): first true pred wins."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs.pop()[1] if not callable(pairs[-1]) \
            else (lambda: (_ for _ in ()).throw(
                ValueError("case needs a default fn")))

    def build(i):
        if i >= len(pairs):
            return default
        p, fn = pairs[i]
        return lambda: cond(p, fn, build(i + 1))

    return build(0)()


def switch_case(branch_index, branch_fns, default: Callable = None,
                name: str = None):
    """fluid.layers.switch_case parity (:3035)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    iv = _unwrap(branch_index)

    if core.in_static_mode() or isinstance(iv, jax.core.Tracer):
        keys = jnp.asarray([k for k, _ in items], jnp.int32)
        fns = [f for _, f in items]
        if default is None:
            default = fns[-1]
        # map branch_index -> position in fns (default when no key matches)
        def dispatch(idx_arr):
            pos = jnp.argmax(keys == idx_arr.astype(jnp.int32))
            matched = jnp.any(keys == idx_arr.astype(jnp.int32))
            branch = jnp.where(matched, pos, len(fns))
            return branch

        if core.in_static_mode():
            from ..static.program import Variable
            # record through cond-chain (simple, serializable-enough)
            def build(i):
                if i >= len(items):
                    return default
                k, fn = items[i]
                return lambda: cond(branch_index == k, fn, build(i + 1))
            return build(0)()
        afns = [(lambda f: lambda: _as_tuple(
            _tensor_fn_to_array_fn(lambda: f())()))(f) for f in fns]
        afns.append(lambda: _as_tuple(
            _tensor_fn_to_array_fn(lambda: default())()))
        out = lax.switch(dispatch(jnp.reshape(iv, ())), afns)
        return _rewrap_structure(out)

    key = int(iv)
    for k, f in items:
        if k == key:
            return f()
    if default is not None:
        return default()
    return items[-1][1]()


# -- helpers -----------------------------------------------------------------

def _as_tuple(x):
    return tuple(x) if isinstance(x, (tuple, list)) else (x,)


def _rewrap_structure(out):
    from ..framework.tensor_array import (BoundedTensorArray,
                                          EmptyListCarry)
    ts = [o if isinstance(o, (BoundedTensorArray, EmptyListCarry))
          else Tensor(o) for o in out]
    return ts[0] if len(ts) == 1 else ts


# -- static-graph recording ---------------------------------------------------
#
# The user callables are traced into a SUB-BLOCK (ops appended to the current
# block are captured and removed — the while_op.cc / conditional_block_op.cc
# sub-block), the free Variables they close over become extra macro-op
# inputs, and the macro's compiled form replays the captured ops inside
# lax.while_loop / lax.cond, fusing the whole construct into the Executor's
# single XLA computation.

def _trace_sub(fn, args):
    """Record fn(*args) under static mode, capturing the ops it appends.

    Returns (ops, out_vars, free_names): free_names are Variables referenced
    but neither produced inside nor passed as args (closure captures)."""
    from ..static.program import current_block, Variable

    block = current_block()
    start = len(block.ops)
    result = fn(*args)
    ops = block.ops[start:]
    del block.ops[start:]

    out_vars = list(result) if isinstance(result, (tuple, list)) else [result]
    for v in out_vars:
        if not isinstance(v, Variable):
            raise TypeError("static control-flow callables must return "
                            f"Variables, got {type(v).__name__}")
    arg_names = {v.name for v in args if isinstance(v, Variable)}
    produced, free = set(), []
    for op in ops:
        for n in op.input_names:
            if n not in produced and n not in arg_names and n not in free:
                free.append(n)
        produced.update(op.output_names)
    return ops, out_vars, free


def _replay(ops):
    def run(env):
        for op in ops:
            ins = [env[n] for n in op.input_names]
            outs = op.run_fn()(*ins)
            env.update(zip(op.output_names, outs))
        return env
    return run


def _record_while(cond, body, loop_vars):
    from ..static.program import current_block, Operator, Variable

    block = current_block()
    for v in loop_vars:
        if not isinstance(v, Variable):
            raise TypeError("while_loop loop_vars must be Variables in "
                            "static mode")
    cond_ops, cond_outs, cond_free = _trace_sub(cond, loop_vars)
    body_ops, body_outs, body_free = _trace_sub(body, loop_vars)
    if len(body_outs) != len(loop_vars):
        raise ValueError(f"body returns {len(body_outs)} vars, expected "
                         f"{len(loop_vars)}")
    free = cond_free + [n for n in body_free if n not in cond_free]
    names = [v.name for v in loop_vars]
    cond_name = cond_outs[0].name
    body_names = [v.name for v in body_outs]
    run_cond, run_body = _replay(cond_ops), _replay(body_ops)
    outs = [block.create_var(shape=v.shape, dtype=v.dtype)
            for v in body_outs]

    def macro_fn(*arrs):
        k = len(names)
        closure = dict(zip(free, arrs[k:]))

        def c(vs):
            env = dict(closure)
            env.update(zip(names, vs))
            return jnp.reshape(run_cond(env)[cond_name], ()).astype(bool)

        def b(vs):
            env = dict(closure)
            env.update(zip(names, vs))
            env = run_body(env)
            return tuple(env[n] for n in body_names)

        return lax.while_loop(c, b, tuple(arrs[:k]))

    op = Operator(block, prim="@while", inputs=names + free,
                  outputs=[o.name for o in outs], attrs={}, fn=macro_fn,
                  type_name="while")
    block.ops.append(op)
    block.program._version += 1
    for o in outs:
        o.op = op
    return outs


def _record_cond(pred, true_fn, false_fn):
    from ..static.program import current_block, Operator, Variable

    block = current_block()
    if not isinstance(pred, Variable):
        raise TypeError("cond pred must be a Variable in static mode")
    t_ops, t_outs, t_free = _trace_sub(lambda: true_fn(), ())
    f_ops, f_outs, f_free = _trace_sub(lambda: false_fn(), ())
    if len(t_outs) != len(f_outs):
        raise ValueError("cond branches must return the same structure")
    free = t_free + [n for n in f_free if n not in t_free]
    t_names = [v.name for v in t_outs]
    f_names = [v.name for v in f_outs]
    run_t, run_f = _replay(t_ops), _replay(f_ops)
    outs = [block.create_var(shape=v.shape, dtype=v.dtype) for v in t_outs]

    def macro_fn(p, *arrs):
        closure = dict(zip(free, arrs))
        return lax.cond(
            jnp.reshape(p, ()).astype(bool),
            lambda: tuple(run_t(dict(closure))[n] for n in t_names),
            lambda: tuple(run_f(dict(closure))[n] for n in f_names))

    op = Operator(block, prim="@cond", inputs=[pred.name] + free,
                  outputs=[o.name for o in outs], attrs={}, fn=macro_fn,
                  type_name="conditional_block")
    block.ops.append(op)
    block.program._version += 1
    for o in outs:
        o.op = op
    return outs[0] if len(outs) == 1 else outs


# -- TensorArray DSL (fluid/layers/control_flow.py array ops) -----------------

class TensorArray(list):
    """LoDTensorArray stand-in: a Python list of Tensors in eager mode; the
    static path records writes/reads as ops over the same object
    (lod_tensor_array / array_write_op, array_read_op)."""


def create_array(dtype="float32", initialized_list=None):
    """fluid.layers.create_array parity."""
    arr = TensorArray()
    if initialized_list:
        arr.extend(initialized_list)
    return arr


def array_write(x, i, array=None):
    """array_write_op: array[i] = x (grows the array as needed)."""
    if array is None:
        array = create_array()
    idx = int(_unwrap(i))
    while len(array) <= idx:
        array.append(None)
    array[idx] = x
    return array


def array_read(array, i):
    """array_read_op: array[i]."""
    return array[int(_unwrap(i))]


def array_length(array):
    """lod_array_length_op."""
    from ..framework.tensor import Tensor
    return Tensor(jnp.asarray(len(array), jnp.int64))
