"""Op-surface long tail: math/reduction/search/manipulation extras.

Reference parity: the remaining REGISTER_OPERATOR families under
paddle/fluid/operators/ — cum_op (logcumsumexp/cummin), kthvalue,
index_select-adjacent index_{add,fill,put}, diag_embed_op, unique ops,
searchsorted, multiplex_op.cc, clip_by_norm_op.cc, squared_l2_norm_op.cc,
accuracy_op.cc (metrics/), plus the python/paddle/tensor/ math surface the
2.x API exposes over them.  Each op is one fused XLA expression.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


def _unary(pname, jf, differentiable=True):
    p = Primitive(pname, jf, differentiable=differentiable)

    def f(x, name=None):
        return p(x)
    f.__name__ = pname
    return f


def _binary(pname, jf, differentiable=True):
    p = Primitive(pname, jf, differentiable=differentiable)

    def f(x, y, name=None):
        return p(x, y)
    f.__name__ = pname
    return f


# -- elementwise ---------------------------------------------------------------

logaddexp = _binary("logaddexp", jnp.logaddexp)
heaviside = _binary("heaviside", jnp.heaviside)
gcd = _binary("gcd", jnp.gcd, differentiable=False)
lcm = _binary("lcm", jnp.lcm, differentiable=False)
copysign = _binary("copysign", jnp.copysign)
nextafter = _binary("nextafter", jnp.nextafter, differentiable=False)
signbit = _unary("signbit", jnp.signbit, differentiable=False)
sinc = _unary("sinc", jnp.sinc)
exp2 = _unary("exp2", jnp.exp2)
erfc = _unary("erfc", jax.scipy.special.erfc)
ldexp = _binary("ldexp", jnp.ldexp)


# -- reductions / scans --------------------------------------------------------

_nanmean = Primitive("nanmean", lambda x, axis=None, keepdim=False:
                     jnp.nanmean(x, axis=axis, keepdims=keepdim))
_nanmedian = Primitive("nanmedian", lambda x, axis=None, keepdim=False:
                       jnp.nanmedian(x, axis=axis, keepdims=keepdim))
_logcumsumexp = Primitive(
    "logcumsumexp",
    lambda x, axis=-1: jax.lax.cumlogsumexp(x, axis=axis))


def _cummin_fn(x, axis=-1):
    vals = jax.lax.associative_scan(jnp.minimum, x, axis=axis)
    return vals


_cummin = Primitive("cummin_vals", _cummin_fn)


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return _nanmean(x, axis=ax, keepdim=bool(keepdim))


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return _nanmedian(x, axis=ax, keepdim=bool(keepdim))


def logcumsumexp(x, axis=-1, name=None):
    nd = len(x.shape) if isinstance(x, Tensor) else unwrap(x).ndim
    return _logcumsumexp(x, axis=int(axis) % nd)


def _cummin_idx_fn(x, axis=-1):
    # index of the running minimum: first position where the scan value
    # equals the element (ties -> earliest, matching cummax's convention)
    ax = axis % x.ndim
    n = x.shape[ax]
    pos = jnp.arange(n).reshape([n if i == ax else 1 for i in range(x.ndim)])
    running = jax.lax.associative_scan(jnp.minimum, x, axis=ax)
    cand = jnp.where(x == running, pos, n)
    return jax.lax.associative_scan(jnp.minimum, cand, axis=ax)


_cummin_idx = Primitive("cummin_idx", _cummin_idx_fn, differentiable=False)


def cummin(x, axis=-1, name=None):
    """(values, indices) like the reference cummin op."""
    return _cummin(x, axis=int(axis)), _cummin_idx(x, axis=int(axis))


def _kthvalue_fn(x, k=1, axis=-1, keepdim=False):
    vals = jnp.sort(x, axis=axis)
    idxs = jnp.argsort(x, axis=axis, stable=True)
    v = jnp.take(vals, k - 1, axis=axis)
    i = jnp.take(idxs, k - 1, axis=axis)
    if keepdim:
        v = jnp.expand_dims(v, axis)
        i = jnp.expand_dims(i, axis)
    return v, i


_kthvalue = Primitive("kthvalue", _kthvalue_fn, multi_output=True,
                      differentiable=False)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    v, i = _kthvalue(x, k=int(k), axis=int(axis), keepdim=bool(keepdim))
    return v, i


_diff = Primitive("diff", lambda x, n=1, axis=-1: jnp.diff(x, n=n, axis=axis))


def diff(x, n=1, axis=-1, name=None):
    return _diff(x, n=int(n), axis=int(axis))


_jtrap = jnp.trapezoid if hasattr(jnp, "trapezoid") else jnp.trapz
_trapezoid = Primitive("trapezoid",
                       lambda y, dx=1.0, axis=-1: _jtrap(y, dx=dx, axis=axis))
_trapezoid_x = Primitive("trapezoid_x",
                         lambda y, x, axis=-1: _jtrap(y, x, axis=axis))


def trapezoid(y, x=None, dx=1.0, axis=-1, name=None):
    if x is not None:
        return _trapezoid_x(y, x, axis=int(axis))
    return _trapezoid(y, dx=float(dx), axis=int(axis))


_dist = Primitive("dist", lambda x, y, p=2.0:
                  jnp.linalg.norm((x - y).reshape(-1).astype(jnp.float32),
                                  ord=p))


def dist(x, y, p=2.0, name=None):
    return _dist(x, y, p=float(p))


_squared_l2_norm = Primitive(
    "squared_l2_norm",
    lambda x: jnp.sum(jnp.square(x.astype(jnp.float32))))


def squared_l2_norm(x, name=None):
    return _squared_l2_norm(x)


_clip_by_norm = Primitive(
    "clip_by_norm",
    lambda x, max_norm=1.0: x * jnp.minimum(
        1.0, max_norm / jnp.maximum(
            jnp.sqrt(jnp.sum(jnp.square(x.astype(jnp.float32)))), 1e-12)
    ).astype(x.dtype))


def clip_by_norm(x, max_norm, name=None):
    return _clip_by_norm(x, max_norm=float(max_norm))


# -- search --------------------------------------------------------------------

_searchsorted = Primitive(
    "searchsorted",
    lambda sorted_seq, values, right=False: jnp.searchsorted(
        sorted_seq, values, side="right" if right else "left"),
    differentiable=False)


def searchsorted(sorted_sequence, values, out_int32=False, right=False,
                 name=None):
    out = _searchsorted(sorted_sequence, values, right=bool(right))
    return out.astype("int32") if out_int32 else out


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


# -- indexing ------------------------------------------------------------------

def _index_apply(x, index, value, axis, kind):
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0) if value.ndim == x.ndim else value
    if kind == "add":
        out = x_m.at[index].add(v_m)
    elif kind == "put":
        out = x_m.at[index].set(v_m)
    else:  # fill with scalar
        out = x_m.at[index].set(v_m)
    return jnp.moveaxis(out, 0, axis)


_index_add = Primitive(
    "index_add", lambda x, index, value, axis=0:
    _index_apply(x, index, value, axis, "add"))
_index_put_axis = Primitive(
    "index_put_axis", lambda x, index, value, axis=0:
    _index_apply(x, index, value, axis, "put"))
_index_fill_p = Primitive(
    "index_fill", lambda x, index, fill_value=0.0, axis=0:
    jnp.moveaxis(jnp.moveaxis(x, axis, 0).at[index].set(
        jnp.asarray(fill_value, x.dtype)), 0, axis))


def index_add(x, index, axis, value, name=None):
    return _index_add(x, index, value, axis=int(axis))


def index_fill(x, index, axis, fill_value, name=None):
    return _index_fill_p(x, index, fill_value=float(fill_value),
                         axis=int(axis))


def index_put(x, indices, value, accumulate=False, name=None):
    """index_put with a tuple of index arrays (tensor indexing)."""
    xv = unwrap(x)
    idx = tuple(unwrap(i) for i in indices)
    vv = unwrap(value)
    out = xv.at[idx].add(vv) if accumulate else xv.at[idx].set(vv)
    return Tensor(out)


_multiplex = Primitive(
    "multiplex",
    lambda index, *ins: jnp.stack(ins, 0)[
        index.reshape(-1).astype(jnp.int32),
        jnp.arange(ins[0].shape[0])])


def multiplex(inputs, index, name=None):
    """multiplex_op.cc: per-row select among candidate tensors."""
    return _multiplex(index, *inputs)


# -- shape / structure ---------------------------------------------------------

_diagonal = Primitive(
    "diagonal", lambda x, offset=0, axis1=0, axis2=1:
    jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return _diagonal(x, offset=int(offset), axis1=int(axis1),
                     axis2=int(axis2))


def _diag_embed_fn(x, offset=0, dim1=-2, dim2=-1):
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    i = jnp.arange(x.shape[-1])
    r = i + max(-offset, 0)
    c = i + max(offset, 0)
    out = base.at[..., r, c].set(x)
    nd = out.ndim
    d1 = dim1 % nd
    d2 = dim2 % nd
    perm = [a for a in range(nd) if a not in (nd - 2, nd - 1)]
    # insert the two matrix dims at the requested positions
    order = []
    src = iter(perm)
    for a in range(nd):
        if a == d1:
            order.append(nd - 2)
        elif a == d2:
            order.append(nd - 1)
        else:
            order.append(next(src))
    return jnp.transpose(out, order)


_diag_embed = Primitive("diag_embed", _diag_embed_fn)


def diag_embed(x, offset=0, dim1=-2, dim2=-1, name=None):
    return _diag_embed(x, offset=int(offset), dim1=int(dim1), dim2=int(dim2))


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None, name=None):
    """unique_consecutive_op.cc (host; output size is data-dependent)."""
    import numpy as np
    xv = np.asarray(unwrap(x))
    if axis is None:
        flat = xv.reshape(-1)
    else:
        flat = np.moveaxis(xv, int(axis), 0)
    keep = np.ones(len(flat), bool)
    if len(flat) > 1:
        diff = flat[1:] != flat[:-1]
        keep[1:] = diff.reshape(len(flat) - 1, -1).any(axis=1) \
            if diff.ndim > 1 else diff
    out = flat[keep]
    if axis is not None:
        out = np.moveaxis(out, 0, int(axis))
    rets = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        rets.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, len(flat)))
        rets.append(Tensor(jnp.asarray(counts)))
    return rets[0] if len(rets) == 1 else tuple(rets)


def tensor_split(x, num_or_indices, axis=0, name=None):
    import numpy as np
    xv = unwrap(x)
    parts = np.array_split(np.asarray(xv), num_or_indices, axis=axis) \
        if isinstance(num_or_indices, int) \
        else np.split(np.asarray(xv), list(num_or_indices), axis=axis)
    return [Tensor(jnp.asarray(p)) for p in parts]


def unflatten(x, axis, shape, name=None):
    xv = unwrap(x)
    ax = axis % xv.ndim
    new_shape = list(xv.shape[:ax]) + list(shape) + list(xv.shape[ax + 1:])
    from .manipulation import reshape
    return reshape(x, new_shape)


def block_diag(inputs, name=None):
    import numpy as np
    mats = [np.atleast_2d(np.asarray(unwrap(m))) for m in inputs]
    R = sum(m.shape[0] for m in mats)
    C = sum(m.shape[1] for m in mats)
    out = jnp.zeros((R, C), mats[0].dtype)
    r = c = 0
    for m in mats:
        out = out.at[r:r + m.shape[0], c:c + m.shape[1]].set(jnp.asarray(m))
        r += m.shape[0]
        c += m.shape[1]
    return Tensor(out)


_complex_p = Primitive("complex", lambda re, im: jax.lax.complex(re, im))


def complex(real, imag, name=None):
    return _complex_p(real, imag)


_tensordot = Primitive(
    "tensordot", lambda x, y, axes=2: jnp.tensordot(x, y, axes=axes))


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(axes, (list, tuple)):
        ax = tuple(tuple(a) if isinstance(a, (list, tuple)) else a
                   for a in axes)
    return _tensordot(x, y, axes=ax)


_vander = Primitive(
    "vander", lambda x, n=None, increasing=False:
    jnp.vander(x, N=n, increasing=increasing))


def vander(x, n=None, increasing=False, name=None):
    return _vander(x, n=None if n is None else int(n),
                   increasing=bool(increasing))


_renorm = Primitive(
    "renorm", lambda x, p=2.0, axis=0, max_norm=1.0:
    _renorm_impl(x, p, axis, max_norm))


def _renorm_impl(x, p, axis, max_norm):
    reduce_axes = tuple(a for a in range(x.ndim) if a != axis)
    norms = jnp.sum(jnp.abs(x.astype(jnp.float32)) ** p,
                    axis=reduce_axes, keepdims=True) ** (1.0 / p)
    scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
    return (x * scale).astype(x.dtype)


def renorm(x, p, axis, max_norm, name=None):
    return _renorm(x, p=float(p), axis=int(axis), max_norm=float(max_norm))


# -- metrics / misc ------------------------------------------------------------

def _accuracy_fn(pred_topk_idx, label, k=1):
    hit = jnp.any(pred_topk_idx[:, :k] == label.reshape(-1, 1), axis=1)
    return jnp.mean(hit.astype(jnp.float32))


_accuracy = Primitive("accuracy", _accuracy_fn, differentiable=False)


def accuracy(input, label, k=1, name=None):
    """accuracy_op.cc: fraction of rows whose top-k contains the label."""
    from .math import topk
    _, idx = topk(input, k=k)
    return _accuracy(idx, label, k=int(k))


def rank(x, name=None):
    return Tensor(jnp.asarray(unwrap(x).ndim))


# reference-named reduce aliases (fluid.layers.reduce_* DSL)
def reduce_sum(x, dim=None, keep_dim=False, name=None):
    from . import math as _m
    return _m.sum(x, axis=dim, keepdim=keep_dim)


def reduce_mean(x, dim=None, keep_dim=False, name=None):
    from . import math as _m
    return _m.mean(x, axis=dim, keepdim=keep_dim)


def reduce_max(x, dim=None, keep_dim=False, name=None):
    from . import math as _m
    return _m.max(x, axis=dim, keepdim=keep_dim)


def reduce_min(x, dim=None, keep_dim=False, name=None):
    from . import math as _m
    return _m.min(x, axis=dim, keepdim=keep_dim)


# -- long-tail additions (round 2) --------------------------------------------

polar = _binary("polar", lambda r, t: jax.lax.complex(r * jnp.cos(t),
                                                      r * jnp.sin(t)))
sgn = _unary("sgn", lambda x: jnp.where(
    jnp.abs(x) == 0, jnp.zeros_like(x), x / jnp.abs(x))
    if jnp.iscomplexobj(x) else jnp.sign(x))
isposinf = _unary("isposinf", jnp.isposinf, differentiable=False)
isneginf = _unary("isneginf", jnp.isneginf, differentiable=False)


def _take_fn(x, idx, mode="raise"):
    flat = x.reshape(-1)
    if mode in ("raise", "clip"):
        idx = jnp.where(idx < 0, idx + flat.shape[0], idx)
        return jnp.take(flat, idx, mode="clip")
    return jnp.take(flat, idx, mode=mode)


_take = Primitive("take", _take_fn)


def take(x, index, mode="raise", name=None):
    """take_op parity (paddle.take): flattened gather with clip/wrap modes.
    ``raise`` degrades to clip under jit (no data-dependent errors on TPU)."""
    return _take(x, unwrap(index), mode=mode)


def reverse(x, axis, name=None):
    """reverse_op.cc (fluid.layers.reverse): flip along the given axes."""
    from .manipulation import flip
    return flip(x, axis)


_nanquantile = Primitive(
    "nanquantile", lambda x, q, axis=None, keepdim=False:
    jnp.nanquantile(x, q, axis=axis, keepdims=keepdim))


def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return _nanquantile(x, q=q, axis=axis, keepdim=keepdim)


def _histogramdd_fn(x, weights=None, bins=10, ranges=None, density=False):
    h, edges = jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                               weights=weights)
    return (h,) + tuple(edges)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    """histogramdd (paddle.histogramdd). Returns (hist, [edges...]).
    ``ranges`` uses paddle's flat [min0, max0, min1, max1, ...] layout."""
    x = unwrap(x)
    w = None if weights is None else unwrap(weights)
    if ranges is not None:
        r = [float(v) for v in ranges]
        ranges = [(r[2 * i], r[2 * i + 1]) for i in range(len(r) // 2)]
    h, *edges = _histogramdd_fn(x, w, bins=bins, ranges=ranges,
                                density=density)
    return Tensor(h), [Tensor(e) for e in edges]


def _partial_slice(x, start_index, length):
    # partial_concat_op.cc normalizes negative start by the column count
    s = start_index if start_index >= 0 else start_index + x.shape[1]
    return x[:, s:] if length < 0 else x[:, s:s + length]


def _partial_concat_fn(*xs, start_index=0, length=-1):
    return jnp.concatenate(
        [_partial_slice(x, start_index, length) for x in xs], axis=1)


_partial_concat = Primitive("partial_concat", _partial_concat_fn)


def partial_concat(x, start_index=0, length=-1, name=None):
    """partial_concat_op.cc: concat a [start:start+length] column slice of
    each [B, D] input."""
    return _partial_concat(*[unwrap(t) for t in x],
                           start_index=int(start_index), length=int(length))


def _partial_sum_fn(*xs, start_index=0, length=-1):
    sl = [_partial_slice(x, start_index, length) for x in xs]
    return sum(sl[1:], sl[0])


_partial_sum = Primitive("partial_sum", _partial_sum_fn)


def partial_sum(x, start_index=0, length=-1, name=None):
    """partial_sum_op.cc: sum the same column slice of each input."""
    return _partial_sum(*[unwrap(t) for t in x],
                        start_index=int(start_index), length=int(length))




# -- fluid-era op long tail (op-coverage ledger round 3) -----------------------

def _add_pos_enc_fn(x, alpha=1.0, beta=1.0):
    """add_position_encoding_op.cc: x*alpha + sinusoid(position)*beta."""
    B, T, C = x.shape
    half = (C + 1) // 2
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) /
                    jnp.maximum(half, 1))
    enc = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)], axis=1)
    return x * alpha + enc[None, :, :C].astype(x.dtype) * beta


_add_pos_enc = Primitive("add_position_encoding", _add_pos_enc_fn)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _add_pos_enc(input, alpha=float(alpha), beta=float(beta))


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    """bilinear_tensor_product_op.cc — same math as nn.functional.bilinear
    (out[:, k] = x W_k y^T + b), so it delegates (one primitive, one VJP
    cache)."""
    from ..nn.functional.common import bilinear as _bilinear
    return _bilinear(x, y, weight, bias)


def _conv_shift_fn(x, y):
    """conv_shift_op.cc: circular correlation, out[i,j] = sum_k
    x[i, (j + k - m//2) mod n] * y[i, k]."""
    n, m = x.shape[1], y.shape[1]
    j = jnp.arange(n)[:, None]
    k = jnp.arange(m)[None, :]
    idx = (j + k - m // 2) % n                  # [n, m]
    gathered = x[:, idx]                        # [B, n, m]
    return jnp.einsum("bnm,bm->bn", gathered, y)


_conv_shift = Primitive("conv_shift", _conv_shift_fn)


def conv_shift(x, y, name=None):
    return _conv_shift(x, y)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="int64", name=None):
    """sampling_id_op.cc: sample one category per row of a probability
    matrix (multinomial with num_samples=1, squeezed). seed!=0 pins the
    draw (op-level seed semantics); the result honors ``dtype``."""
    if seed:
        from ..framework.random import default_generator
        st = default_generator.state()
        default_generator.manual_seed(int(seed))
        try:
            out = sampling_id(x, min, max, 0, dtype, name)
        finally:
            default_generator.set_state(st)
        return out
    from .creation import multinomial
    from .manipulation import squeeze, cast
    out = squeeze(multinomial(x, num_samples=1), axis=[-1])
    return cast(out, dtype)


def _segment_fn(x, ids, pool_type="SUM", num_segments=0):
    seg = {"SUM": jax.ops.segment_sum,
           "MEAN": None, "MAX": jax.ops.segment_max,
           "MIN": jax.ops.segment_min}[pool_type]
    cnt = jax.ops.segment_sum(jnp.ones((x.shape[0],), x.dtype), ids,
                              num_segments)
    present = (cnt > 0).reshape((-1,) + (1,) * (x.ndim - 1))
    if pool_type == "MEAN":
        s = jax.ops.segment_sum(x, ids, num_segments)
        return s / jnp.maximum(cnt, 1.0).reshape((-1,) + (1,) * (x.ndim - 1))
    out = seg(x, ids, num_segments)
    # empty segments fill 0 (segment_pool_op.cc), not the +/-inf identity
    return jnp.where(present, out, 0.0).astype(x.dtype)


_segment = Primitive("segment_pool", _segment_fn)


def segment_pool(x, segment_ids, pool_type="SUM", name=None):
    """segment_pool_op.cc over sorted segment ids."""
    import numpy as _np
    ns = int(_np.asarray(unwrap(segment_ids)).max()) + 1
    return _segment(x, unwrap(segment_ids), pool_type=str(pool_type).upper(),
                    num_segments=ns)


def _row_conv_fn(x, w):
    """row_conv_op.cc: lookahead causal conv — out[b,t] = sum_{k<ctx}
    x[b,t+k] * w[k] (zero past the end)."""
    B, T, C = x.shape
    ctx = w.shape[0]
    xp = jnp.pad(x, ((0, 0), (0, ctx - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for k in range(ctx):                    # ctx is small and static
        out = out + xp[:, k:k + T, :] * w[k]
    return out


_row_conv = Primitive("row_conv", _row_conv_fn)


def row_conv(input, weight, name=None):
    return _row_conv(input, weight)


def _cvm_fn(x, use_cvm=True):
    """cvm_op.cc: CTR show/click head — log-transform the 2 leading cvm
    features (show, clk) or drop them."""
    show = jnp.log(x[:, 0:1] + 1.0)
    clk = jnp.log(x[:, 1:2] + 1.0) - show
    if use_cvm:
        return jnp.concatenate([show, clk, x[:, 2:]], axis=1)
    return x[:, 2:]


_cvm = Primitive("cvm", _cvm_fn)


def cvm(input, cvm_tensor=None, use_cvm=True, name=None):
    return _cvm(input, use_cvm=bool(use_cvm))


def _mean_iou_fn(pred, label, num_classes=2):
    p = pred.reshape(-1)
    l = label.reshape(-1)
    idx = l * num_classes + p
    cm = jnp.bincount(idx, length=num_classes * num_classes).reshape(
        num_classes, num_classes).astype(jnp.float32)
    inter = jnp.diag(cm)
    union = cm.sum(0) + cm.sum(1) - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1.0), 0.0)
    valid = (union > 0).astype(jnp.float32)
    return jnp.sum(iou) / jnp.maximum(jnp.sum(valid), 1.0)


_mean_iou = Primitive("mean_iou", _mean_iou_fn, differentiable=False)


def mean_iou(input, label, num_classes, name=None):
    """mean_iou_op.cc: mean intersection-over-union over present classes."""
    return _mean_iou(input, label, num_classes=int(num_classes))


def _l1norm_fn(x):
    return jnp.sum(jnp.abs(x))


_l1_norm = Primitive("l1_norm", _l1norm_fn)


def l1_norm(x, name=None):
    return _l1_norm(x)


def _sq_l2_dist_fn(x, y):
    d = x - y
    return jnp.sum(d * d, axis=-1)


_sq_l2 = Primitive("squared_l2_distance", _sq_l2_dist_fn)


def squared_l2_distance(x, y, name=None):
    return _sq_l2(x, y)


def _im2sequence_fn(x, kernel=(3, 3), stride=(1, 1), padding=(0, 0, 0, 0)):
    """im2sequence_op.cc: sliding windows -> rows [B*oh*ow, C*kh*kw]."""
    pads = ((0, 0), (0, 0), (padding[0], padding[2]),
            (padding[1], padding[3]))
    xp = jnp.pad(x, pads)
    p = jax.lax.conv_general_dilated_patches(
        xp, kernel, stride, "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    B, CK, OH, OW = p.shape
    return p.transpose(0, 2, 3, 1).reshape(B * OH * OW, CK)


_im2seq = Primitive("im2sequence", _im2sequence_fn)


def im2sequence(input, filter_size=3, stride=1, padding=0, name=None):
    k = (filter_size,) * 2 if isinstance(filter_size, int) else tuple(filter_size)
    s = (stride,) * 2 if isinstance(stride, int) else tuple(stride)
    pd = (padding,) * 4 if isinstance(padding, int) else tuple(padding)
    return _im2seq(input, kernel=k, stride=s, padding=pd)


def _affine_channel_fn(x, scale, bias, channel_last=False):
    """affine_channel_op.cc: per-channel x*scale + bias."""
    shape = (1,) * (x.ndim - 1) + (-1,) if channel_last \
        else (1, -1) + (1,) * (x.ndim - 2)
    return x * scale.reshape(shape) + bias.reshape(shape)


_affine_channel = Primitive("affine_channel", _affine_channel_fn)


def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    return _affine_channel(x, scale, bias,
                           channel_last=not data_format.startswith("NC"))


__all__ = [
    "logaddexp", "heaviside", "gcd", "lcm", "copysign", "nextafter",
    "signbit", "sinc", "exp2", "erfc", "ldexp", "nanmean", "nanmedian",
    "logcumsumexp", "cummin", "kthvalue", "diff", "trapezoid", "dist",
    "squared_l2_norm", "clip_by_norm", "searchsorted", "bucketize",
    "index_add", "index_fill", "index_put", "multiplex", "diagonal",
    "diag_embed", "unique_consecutive", "tensor_split", "unflatten",
    "block_diag", "complex", "tensordot", "vander", "renorm", "accuracy",
    "rank", "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "polar", "sgn", "isposinf", "isneginf", "take", "reverse",
    "nanquantile", "histogramdd", "partial_concat", "partial_sum",
    "add_position_encoding", "bilinear_tensor_product", "conv_shift",
    "sampling_id", "segment_pool", "row_conv", "cvm", "mean_iou",
    "l1_norm", "squared_l2_distance", "im2sequence", "affine_channel",
]
