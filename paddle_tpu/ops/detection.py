"""Detection ops: boxes, anchors, ROI pooling, NMS, YOLO decoding.

Reference parity: paddle/fluid/operators/detection/ — yolo_box_op.cc,
roi_align_op.cc, roi_pool_op (fluid/operators/roi_pool_op.cc),
prior_box_op.cc, anchor_generator_op.cc, box_coder_op.cc,
iou_similarity_op.cc, box_clip_op.cc, multiclass_nms_op.cc and the
python/paddle/fluid/layers/detection.py DSL.

TPU-first: everything is a fixed-shape vectorized expression.  NMS — the
classically "dynamic" op — runs as a fixed-iteration suppression matrix
(scores sorted once, O(N^2) IoU mask, sequential argmax via lax.scan over a
static box budget), returning a keep-mask + indices instead of a
dynamically-sized list; callers slice by the returned count.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


# -- IoU / box utilities ------------------------------------------------------

def _iou_matrix(a, b):
    """[N,4] x [M,4] (xyxy) -> [N,M] IoU (iou_similarity_op.h)."""
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


_iou_similarity = Primitive("iou_similarity", _iou_matrix)


def iou_similarity(x, y, name=None):
    return _iou_similarity(x, y)


def _box_clip_fn(boxes, im_h=1.0, im_w=1.0):
    return jnp.stack([
        jnp.clip(boxes[..., 0], 0, im_w), jnp.clip(boxes[..., 1], 0, im_h),
        jnp.clip(boxes[..., 2], 0, im_w), jnp.clip(boxes[..., 3], 0, im_h),
    ], axis=-1)


_box_clip = Primitive("box_clip", _box_clip_fn)


def box_clip(boxes, im_shape, name=None):
    import numpy as np
    hw = np.asarray(unwrap(im_shape)).reshape(-1)
    return _box_clip(boxes, im_h=float(hw[0]), im_w=float(hw[1]))


def _box_coder_fn(prior, prior_var, target, code_type="encode_center_size",
                  box_normalized=True):
    """box_coder_op.cc: encode target vs prior anchors (or decode deltas)."""
    pw = prior[:, 2] - prior[:, 0] + (0.0 if box_normalized else 1.0)
    ph = prior[:, 3] - prior[:, 1] + (0.0 if box_normalized else 1.0)
    px = prior[:, 0] + pw * 0.5
    py = prior[:, 1] + ph * 0.5
    if code_type == "encode_center_size":
        tw = target[:, 2] - target[:, 0] + (0.0 if box_normalized else 1.0)
        th = target[:, 3] - target[:, 1] + (0.0 if box_normalized else 1.0)
        tx = target[:, 0] + tw * 0.5
        ty = target[:, 1] + th * 0.5
        out = jnp.stack([(tx - px) / pw, (ty - py) / ph,
                         jnp.log(tw / pw), jnp.log(th / ph)], axis=-1)
        return out / prior_var
    # decode: target holds deltas
    d = target * prior_var
    cx = d[:, 0] * pw + px
    cy = d[:, 1] * ph + py
    w = jnp.exp(d[:, 2]) * pw
    h = jnp.exp(d[:, 3]) * ph
    sub = 0.0 if box_normalized else 1.0
    return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                      cx + w * 0.5 - sub, cy + h * 0.5 - sub], axis=-1)


_box_coder = Primitive("box_coder", _box_coder_fn)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None):
    return _box_coder(prior_box, prior_box_var, target_box,
                      code_type=code_type, box_normalized=bool(box_normalized))


# -- anchors ------------------------------------------------------------------

def _prior_box_fn(feat_h, feat_w, im_h, im_w, min_sizes=(), max_sizes=(),
                  aspect_ratios=(1.0,), step_h=0.0, step_w=0.0, offset=0.5,
                  clip=False, flip=True):
    """prior_box_op.cc: SSD priors per feature-map cell."""
    ars = [1.0]
    for ar in aspect_ratios:
        if abs(ar - 1.0) > 1e-6:
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    sh = step_h or im_h / feat_h
    sw = step_w or im_w / feat_w
    cy = (jnp.arange(feat_h) + offset) * sh
    cx = (jnp.arange(feat_w) + offset) * sw
    boxes = []
    # prior_box_op.h pairs min_sizes[i] with max_sizes[i] (not a cross
    # product): per min size, the AR variants then one sqrt(min*max) square
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            w, h = ms * (ar ** 0.5), ms / (ar ** 0.5)
            boxes.append((w, h))
        if i < len(max_sizes):
            s = (ms * max_sizes[i]) ** 0.5
            boxes.append((s, s))
    wh = jnp.asarray(boxes, jnp.float32)  # [A, 2]
    grid_y, grid_x = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([grid_x, grid_y], -1)[:, :, None, :]  # [H,W,1,2]
    half = wh[None, None] * 0.5
    out = jnp.concatenate([centers - half, centers + half], -1)  # [H,W,A,4]
    out = out / jnp.asarray([im_w, im_h, im_w, im_h], jnp.float32)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


_prior_box = Primitive("prior_box", _prior_box_fn, differentiable=False)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              steps=(0.0, 0.0), offset=0.5, clip=False, flip=True, name=None):
    ih, iw = unwrap(image).shape[-2:]
    fh, fw = unwrap(input).shape[-2:]
    return _prior_box(feat_h=int(fh), feat_w=int(fw), im_h=float(ih),
                      im_w=float(iw), min_sizes=tuple(min_sizes),
                      max_sizes=tuple(max_sizes or ()),
                      aspect_ratios=tuple(aspect_ratios),
                      step_h=float(steps[1]), step_w=float(steps[0]),
                      offset=float(offset), clip=bool(clip), flip=bool(flip))


def _anchor_generator_fn(feat_h, feat_w, anchor_sizes=(64.0,),
                         aspect_ratios=(1.0,), stride=(16.0, 16.0),
                         offset=0.5):
    """anchor_generator_op.cc (RPN anchors, absolute pixels)."""
    boxes = []
    for s in anchor_sizes:
        for ar in aspect_ratios:
            area = float(s) * float(s)
            w = (area / ar) ** 0.5
            h = w * ar
            boxes.append((w, h))
    wh = jnp.asarray(boxes, jnp.float32)
    cx = (jnp.arange(feat_w) + offset) * stride[0]
    cy = (jnp.arange(feat_h) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    centers = jnp.stack([gx, gy], -1)[:, :, None, :]
    half = wh[None, None] * 0.5
    return jnp.concatenate([centers - half, centers + half], -1)


_anchor_generator = Primitive("anchor_generator", _anchor_generator_fn,
                              differentiable=False)


def anchor_generator(input, anchor_sizes, aspect_ratios, stride,
                     offset=0.5, name=None):
    fh, fw = unwrap(input).shape[-2:]
    return _anchor_generator(feat_h=int(fh), feat_w=int(fw),
                             anchor_sizes=tuple(float(s) for s in anchor_sizes),
                             aspect_ratios=tuple(float(a) for a in aspect_ratios),
                             stride=tuple(float(s) for s in stride),
                             offset=float(offset))


# -- ROI ops ------------------------------------------------------------------

def _roi_align_fn(x, rois, roi_batch_idx, pooled_h=1, pooled_w=1,
                  spatial_scale=1.0, sampling_ratio=-1, aligned=False):
    """roi_align_op.cc: bilinear-sampled average pooling per ROI.

    x: [N,C,H,W]; rois: [R,4] xyxy; roi_batch_idx: [R] image index."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    off = 0.5 if aligned else 0.0
    sr = sampling_ratio if sampling_ratio > 0 else 2

    x1 = rois[:, 0] * spatial_scale - off
    y1 = rois[:, 1] * spatial_scale - off
    x2 = rois[:, 2] * spatial_scale - off
    y2 = rois[:, 3] * spatial_scale - off
    rw = jnp.maximum(x2 - x1, 1e-3 if aligned else 1.0)
    rh = jnp.maximum(y2 - y1, 1e-3 if aligned else 1.0)
    bin_w = rw / pooled_w
    bin_h = rh / pooled_h

    # sample grid: [R, ph, pw, sr, sr, 2]
    py = jnp.arange(pooled_h)
    px = jnp.arange(pooled_w)
    sy = (jnp.arange(sr) + 0.5) / sr
    sx = (jnp.arange(sr) + 0.5) / sr
    yy = y1[:, None, None] + (py[None, :, None] + sy[None, None, :]) * \
        bin_h[:, None, None]                      # [R, ph, sr]
    xx = x1[:, None, None] + (px[None, :, None] + sx[None, None, :]) * \
        bin_w[:, None, None]                      # [R, pw, sr]

    def bilinear(img, ys, xs):
        # img [C,H,W]; ys [ph,sr]; xs [pw,sr] -> [C,ph,pw]
        y0 = jnp.clip(jnp.floor(ys), 0, H - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, W - 1)
        y1i = jnp.clip(y0 + 1, 0, H - 1)
        x1i = jnp.clip(x0 + 1, 0, W - 1)
        wy = jnp.clip(ys, 0, H - 1) - y0
        wx = jnp.clip(xs, 0, W - 1) - x0
        y0 = y0.astype(jnp.int32)
        y1i = y1i.astype(jnp.int32)
        x0 = x0.astype(jnp.int32)
        x1i = x1i.astype(jnp.int32)

        v00 = img[:, y0[:, :, None, None], x0[None, None, :, :]]
        v01 = img[:, y0[:, :, None, None], x1i[None, None, :, :]]
        v10 = img[:, y1i[:, :, None, None], x0[None, None, :, :]]
        v11 = img[:, y1i[:, :, None, None], x1i[None, None, :, :]]
        wy_ = wy[:, :, None, None]
        wx_ = wx[None, None, :, :]
        val = (v00 * (1 - wy_) * (1 - wx_) + v01 * (1 - wy_) * wx_ +
               v10 * wy_ * (1 - wx_) + v11 * wy_ * wx_)  # [C,ph,sr,pw,sr]
        return val.mean(axis=(2, 4))

    def per_roi(r):
        img = x[roi_batch_idx[r]]
        return bilinear(img, yy[r], xx[r])

    return jax.vmap(per_roi)(jnp.arange(R))  # [R, C, ph, pw]


def _roi_pool_fn(x, rois, roi_batch_idx, pooled_h=1, pooled_w=1,
                 spatial_scale=1.0):
    """roi_pool_op.cc: max pooling over quantized ROI bins."""
    N, C, H, W = x.shape
    R = rois.shape[0]
    x1 = jnp.round(rois[:, 0] * spatial_scale)
    y1 = jnp.round(rois[:, 1] * spatial_scale)
    x2 = jnp.round(rois[:, 2] * spatial_scale)
    y2 = jnp.round(rois[:, 3] * spatial_scale)
    rw = jnp.maximum(x2 - x1 + 1, 1.0)
    rh = jnp.maximum(y2 - y1 + 1, 1.0)

    hs = jnp.arange(H, dtype=jnp.float32)
    ws = jnp.arange(W, dtype=jnp.float32)

    def per_roi(r):
        img = x[roi_batch_idx[r]]  # [C,H,W]
        bh = rh[r] / pooled_h
        bw = rw[r] / pooled_w

        def bin_val(py, px):
            hstart = jnp.floor(py * bh) + y1[r]
            hend = jnp.ceil((py + 1) * bh) + y1[r]
            wstart = jnp.floor(px * bw) + x1[r]
            wend = jnp.ceil((px + 1) * bw) + x1[r]
            mh = (hs >= hstart) & (hs < hend)
            mw = (ws >= wstart) & (ws < wend)
            m = mh[:, None] & mw[None, :]
            empty = ~jnp.any(m)
            v = jnp.max(jnp.where(m[None], img, -jnp.inf), axis=(1, 2))
            return jnp.where(empty, 0.0, v)

        py = jnp.arange(pooled_h)
        px = jnp.arange(pooled_w)
        vals = jax.vmap(lambda a: jax.vmap(lambda b: bin_val(a, b))(px))(py)
        return jnp.transpose(vals, (2, 0, 1))  # [C, ph, pw]

    return jax.vmap(per_roi)(jnp.arange(R))


_roi_align = Primitive("roi_align", _roi_align_fn)
_roi_pool = Primitive("roi_pool", _roi_pool_fn)


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    bidx = _batch_index(boxes, boxes_num, unwrap(x).shape[0])
    return _roi_align(x, boxes, bidx, pooled_h=int(ph), pooled_w=int(pw),
                      spatial_scale=float(spatial_scale),
                      sampling_ratio=int(sampling_ratio),
                      aligned=bool(aligned))


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
             name=None):
    ph, pw = (output_size, output_size) if isinstance(output_size, int) \
        else output_size
    bidx = _batch_index(boxes, boxes_num, unwrap(x).shape[0])
    return _roi_pool(x, boxes, bidx, pooled_h=int(ph), pooled_w=int(pw),
                     spatial_scale=float(spatial_scale))


def _batch_index(boxes, boxes_num, n_images):
    import numpy as np
    R = unwrap(boxes).shape[0]
    if boxes_num is None:
        return jnp.zeros((R,), jnp.int32)
    counts = np.asarray(unwrap(boxes_num)).ravel()
    return jnp.asarray(np.repeat(np.arange(len(counts)), counts)
                       .astype(np.int32))


# -- YOLO ---------------------------------------------------------------------

def _yolo_box_fn(x, img_size, anchors=(), class_num=1, conf_thresh=0.01,
                 downsample_ratio=32, clip_bbox=True, scale_x_y=1.0):
    """yolo_box_op.cc: decode a YOLOv3 head to boxes+scores.

    x: [N, A*(5+C), H, W]; returns (boxes [N, A*H*W, 4],
    scores [N, A*H*W, C])."""
    N, _, H, W = x.shape
    A = len(anchors) // 2
    C = class_num
    x = x.reshape(N, A, 5 + C, H, W)
    grid_x = jnp.arange(W, dtype=jnp.float32)
    grid_y = jnp.arange(H, dtype=jnp.float32)
    anchors_wh = jnp.asarray(anchors, jnp.float32).reshape(A, 2)

    sx = jax.nn.sigmoid(x[:, :, 0]) * scale_x_y - (scale_x_y - 1) / 2
    sy = jax.nn.sigmoid(x[:, :, 1]) * scale_x_y - (scale_x_y - 1) / 2
    bx = (grid_x[None, None, None, :] + sx) / W
    by = (grid_y[None, None, :, None] + sy) / H
    bw = jnp.exp(x[:, :, 2]) * anchors_wh[None, :, 0, None, None] / \
        (W * downsample_ratio)
    bh = jnp.exp(x[:, :, 3]) * anchors_wh[None, :, 1, None, None] / \
        (H * downsample_ratio)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    probs = jnp.where(conf[:, :, None] < conf_thresh, 0.0, probs)

    im_h = img_size[:, 0].astype(jnp.float32)
    im_w = img_size[:, 1].astype(jnp.float32)
    x1 = (bx - bw / 2) * im_w[:, None, None, None]
    y1 = (by - bh / 2) * im_h[:, None, None, None]
    x2 = (bx + bw / 2) * im_w[:, None, None, None]
    y2 = (by + bh / 2) * im_h[:, None, None, None]
    if clip_bbox:
        x1 = jnp.clip(x1, 0, im_w[:, None, None, None] - 1)
        y1 = jnp.clip(y1, 0, im_h[:, None, None, None] - 1)
        x2 = jnp.clip(x2, 0, im_w[:, None, None, None] - 1)
        y2 = jnp.clip(y2, 0, im_h[:, None, None, None] - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(N, -1, 4)
    scores = jnp.moveaxis(probs, 2, -1).reshape(N, -1, C)
    return boxes, scores


_yolo_box = Primitive("yolo_box", _yolo_box_fn, multi_output=True)


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, scale_x_y=1.0, name=None):
    return _yolo_box(x, img_size, anchors=tuple(int(a) for a in anchors),
                     class_num=int(class_num), conf_thresh=float(conf_thresh),
                     downsample_ratio=int(downsample_ratio),
                     clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y))


# -- NMS ----------------------------------------------------------------------

def _nms_fn(boxes, scores, iou_threshold=0.3, top_k=-1):
    """Fixed-shape greedy NMS: returns (keep_idx [N] score-ordered with
    suppressed slots = -1, num_kept scalar).  multiclass_nms_op.cc's
    dynamic output list becomes (indices, count) — the TPU idiom."""
    N = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    iou = _iou_matrix(b, b)

    def body(keep_mask, i):
        # i is suppressed if any higher-scored KEPT box overlaps too much
        prior = (jnp.arange(N) < i) & keep_mask
        sup = jnp.any(prior & (iou[i] > iou_threshold))
        keep_mask = keep_mask.at[i].set(~sup)
        return keep_mask, None

    keep0 = jnp.ones((N,), bool)
    keep_mask, _ = lax.scan(body, keep0, jnp.arange(N))
    if top_k > 0:
        ranks = jnp.cumsum(keep_mask) - 1
        keep_mask = keep_mask & (ranks < top_k)
    kept_sorted = jnp.where(keep_mask, order, -1)
    return kept_sorted, jnp.sum(keep_mask.astype(jnp.int32))


_nms = Primitive("nms", _nms_fn, multi_output=True, differentiable=False)


def nms(boxes, scores=None, iou_threshold=0.3, top_k=-1, name=None):
    import numpy as np
    if scores is None:
        scores = Tensor(jnp.arange(unwrap(boxes).shape[0], 0, -1,
                                   dtype=jnp.float32))
    idx, n = _nms(boxes, scores, iou_threshold=float(iou_threshold),
                  top_k=int(top_k))
    # paddle's nms returns the kept indices; compact on host (eager op)
    iv = np.asarray(unwrap(idx))
    return Tensor(jnp.asarray(iv[iv >= 0][: int(n)]))


def bipartite_match(dist_matrix, name=None):
    """bipartite_match_op.cc greedy max matching (host-side; not a hot op)."""
    import numpy as np
    d = np.asarray(unwrap(dist_matrix)).copy()
    R, C = d.shape
    match_idx = -np.ones(C, np.int64)
    match_dist = np.zeros(C, np.float32)
    for _ in range(min(R, C)):
        r, c = np.unravel_index(np.argmax(d), d.shape)
        if d[r, c] <= 0:
            break
        match_idx[c] = r
        match_dist[c] = d[r, c]
        d[r, :] = -1
        d[:, c] = -1
    return Tensor(jnp.asarray(match_idx)), Tensor(jnp.asarray(match_dist))


__all__ = ["iou_similarity", "box_clip", "box_coder", "prior_box",
           "anchor_generator", "roi_align", "roi_pool", "yolo_box", "nms",
           "bipartite_match"]
