"""Long-tail research/industrial operators — the last ten ledger rows.

Reference parity (each op cites its kernel):
  rank_attention            operators/rank_attention_op.cc + rank_attention.cu.h
  pyramid_hash              operators/pyramid_hash_op.cc
  tree_conv                 operators/tree_conv_op.h + math/tree2col.cc
  correlation               operators/correlation_op.cu
  prroi_pool                operators/prroi_pool_op.h
  similarity_focus          operators/similarity_focus_op.h
  deformable_psroi_pooling  operators/deformable_psroi_pooling_op.h
  roi_perspective_transform operators/detection/roi_perspective_transform_op.cc
  bilateral_slice           operators/bilateral_slice_op.cu
  multi_gru                 operators/fused/multi_gru_op.cc

TPU-first shape: graph/set-structured preprocessing (tree DFS, n-gram
enumeration, greedy selection) runs host-side in numpy — the reference runs
these on CPU too — while every FLOP-bearing stage is a jnp Primitive so XLA
tiles it onto the MXU and jax.vjp derives the grad kernels the reference
hand-writes (rank_attention_grad, tree_conv_grad, prroi_pool_grad, ...).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


def _arr(x):
    return unwrap(x) if isinstance(x, Tensor) else jnp.asarray(x)


def _host(x, dtype=None):
    a = np.asarray(x.numpy() if isinstance(x, Tensor) else x)
    return a.astype(dtype) if dtype is not None else a


# ---------------------------------------------------------------------------
# rank_attention (CTR)
# ---------------------------------------------------------------------------

def _rank_attention_fn(x, rank_offset, param, max_rank=3):
    N, D = x.shape
    ro = rank_offset.astype(jnp.int32)
    lower = ro[:, 0] - 1                         # [N] this instance's rank
    faster = ro[:, 1::2] - 1                     # [N, K] related ranks
    index = ro[:, 2::2]                          # [N, K] related row ids
    valid = (lower[:, None] >= 0) & (faster >= 0)
    xg = x[jnp.clip(index, 0, N - 1)]            # [N, K, D]
    xg = jnp.where(valid[..., None], xg, 0.0)
    pidx = jnp.clip(lower[:, None] * max_rank + faster,
                    0, max_rank * max_rank - 1)  # [N, K]
    p3 = param.reshape(max_rank * max_rank, D, -1)
    pg = p3[pidx]                                # [N, K, D, C]
    pg = jnp.where(valid[..., None, None], pg, 0.0)
    return jnp.einsum("nkd,nkdc->nc", xg, pg)


_rank_attention_p = Primitive("rank_attention", _rank_attention_fn)


def rank_attention(x, rank_offset, rank_param, max_rank: int = 3,
                   max_size: int = 0):
    """rank_attention_op.cc: per-instance rank-gated attention over related
    instances.  ``x`` [N, D]; ``rank_offset`` [N, 2K+1] int — column 0 the
    instance's own rank (1-based, 0 = none), then (rank, row-index) pairs
    for K related instances; ``rank_param`` [max_rank²·D, C] organized by
    (own_rank, other_rank) blocks.  out[n] = Σ_k x[idx(n,k)] ·
    P[rank(n), rank_k] (invalid slots contribute zero) — the expand-input /
    expand-param + batched-GEMM of rank_attention.cu.h collapsed into one
    einsum.  ``max_size`` (a CUDA memory pre-allocation hint) has no TPU
    meaning and is accepted for signature parity."""
    return _rank_attention_p(x, rank_offset, rank_param,
                             max_rank=int(max_rank))


# ---------------------------------------------------------------------------
# pyramid_hash (industrial search)
# ---------------------------------------------------------------------------

_XXH_P1, _XXH_P2, _XXH_P3 = 2654435761, 2246822519, 3266489917
_XXH_P4, _XXH_P5 = 668265263, 374761393
_M32 = 0xFFFFFFFF


def _rotl32(x, r):
    return ((x << r) | (x >> (32 - r))) & _M32


def xxh32(data: bytes, seed: int = 0) -> int:
    """Real XXH32 (pure Python, host-side) — the exact hash
    pyramid_hash_op.cc:229 uses, so row assignments match the reference
    and reference-trained pyramid_hash checkpoints stay portable."""
    n = len(data)
    i = 0
    if n >= 16:
        v1 = (seed + _XXH_P1 + _XXH_P2) & _M32
        v2 = (seed + _XXH_P2) & _M32
        v3 = seed & _M32
        v4 = (seed - _XXH_P1) & _M32
        while i + 16 <= n:
            for j, v in enumerate((v1, v2, v3, v4)):
                lane = int.from_bytes(data[i + 4 * j:i + 4 * j + 4],
                                      "little")
                v = (_rotl32((v + lane * _XXH_P2) & _M32, 13)
                     * _XXH_P1) & _M32
                if j == 0:
                    v1 = v
                elif j == 1:
                    v2 = v
                elif j == 2:
                    v3 = v
                else:
                    v4 = v
            i += 16
        h = (_rotl32(v1, 1) + _rotl32(v2, 7) + _rotl32(v3, 12)
             + _rotl32(v4, 18)) & _M32
    else:
        h = (seed + _XXH_P5) & _M32
    h = (h + n) & _M32
    while i + 4 <= n:
        lane = int.from_bytes(data[i:i + 4], "little")
        h = (_rotl32((h + lane * _XXH_P3) & _M32, 17) * _XXH_P4) & _M32
        i += 4
    while i < n:
        h = (_rotl32((h + data[i] * _XXH_P5) & _M32, 11) * _XXH_P1) & _M32
        i += 1
    h ^= h >> 15
    h = (h * _XXH_P2) & _M32
    h ^= h >> 13
    h = (h * _XXH_P3) & _M32
    h ^= h >> 16
    return h


def _term_hash(term, salt):
    """Hash one n-gram: XXH32 over the little-endian int64 id bytes,
    seeded per embedding chunk (pyramid_hash_op.cc hash loop parity)."""
    return xxh32(np.asarray(term, "<i8").tobytes(), seed=salt)


def _pyramid_gather_fn(w, idx):
    flat = w.reshape(-1)
    return flat[idx.reshape(-1)].reshape(idx.shape[0], -1)


_pyramid_gather_p = Primitive("pyramid_hash", _pyramid_gather_fn)


def pyramid_hash(x, w, offsets=None, *, num_emb, space_len, rand_len,
                 pyramid_layer: int = 2, drop_out_percent: float = 0.0,
                 is_training: bool = False, seed: int = 0,
                 white_list=None, black_list=None):
    """pyramid_hash_op.cc: enumerate every n-gram of lengths 2..pyramid_layer
    per sequence, filter (white/black lists ≙ the reference's bloom
    filters, here exact sets — a superset of the filter contract), hash
    each kept n-gram ``num_emb/rand_len`` times and assemble its embedding
    from ``rand_len``-wide slices of ``w`` (flat [space_len+rand_len]).

    ``x``: list of int sequences, or a flat array with LoD ``offsets``.
    Returns (out [M, num_emb], drop_pos [Σngrams], new_offsets) — M is
    data-dependent, so enumeration runs host-side (a CPU-only kernel in
    the reference too); the embedding assembly is a differentiable device
    gather, so grads flow to ``w`` (pyramid_hash_grad parity)."""
    if offsets is not None:
        flat = _host(x, np.int64).ravel()
        offs = list(offsets)
        seqs = [flat[offs[i]:offs[i + 1]] for i in range(len(offs) - 1)]
    else:
        seqs = [_host(s, np.int64).ravel() for s in x]
    wset = None if white_list is None else \
        {tuple(map(int, t)) for t in white_list}
    bset = set() if black_list is None else \
        {tuple(map(int, t)) for t in black_list}
    if num_emb % rand_len != 0:
        raise ValueError(f"num_emb ({num_emb}) must be a multiple of "
                         f"rand_len ({rand_len})")
    chunks = num_emb // rand_len
    rng = np.random.RandomState(seed)

    pos_rows, drop_pos, new_offsets = [], [], [0]
    for seq in seqs:
        kept = 0
        L = len(seq)
        if L >= 2:
            for ilayer in range(1, pyramid_layer):
                if ilayer >= L:
                    break
                for start in range(L - ilayer):
                    term = tuple(map(int, seq[start:start + ilayer + 1]))
                    use = ((wset is None or term in wset)
                           and term not in bset)
                    if use and is_training and drop_out_percent > 0:
                        use = rng.rand() >= drop_out_percent
                    drop_pos.append(1 if use else 0)
                    if use:
                        pos_rows.append([
                            _term_hash(term, c * rand_len) % space_len
                            for c in range(chunks)])
                        kept += 1
        new_offsets.append(new_offsets[-1] + kept)

    if not pos_rows:
        out = Tensor(jnp.zeros((0, num_emb), jnp.float32))
        return out, Tensor(jnp.asarray(drop_pos, jnp.int32)), new_offsets
    pos = np.asarray(pos_rows, np.int32)                      # [M, chunks]
    idx = pos[:, :, None] + np.arange(rand_len)[None, None, :]
    out = _pyramid_gather_p(w, jnp.asarray(idx.reshape(len(pos), num_emb)))
    return out, Tensor(jnp.asarray(drop_pos, jnp.int32)), new_offsets


# ---------------------------------------------------------------------------
# tree_conv (TBCNN)
# ---------------------------------------------------------------------------

def _tree_patch_coef(edges: np.ndarray, n: int, max_depth: int) -> np.ndarray:
    """Continuous-binary-tree coefficients (math/tree2col.cc): for every
    root u, DFS its subtree to depth < max_depth; each visited node v (at
    1-based child index within pclen siblings, depth d) contributes
    [eta_l, eta_r, eta_t] where eta_t=(D-d)/D, eta_l=(1-eta_t)·pos,
    eta_r=(1-eta_t)·(1-pos).  Returns coef [n, n, 3] with row u-1 holding
    root u's patch."""
    tr = [[] for _ in range(n + 2)]
    for u, v in edges:
        u, v = int(u), int(v)
        if u == 0 or v == 0:
            break                    # 0,0 terminates the edge list
        tr[u].append(v)
    coef = np.zeros((n, n, 3), np.float32)
    D = float(max_depth)

    def eta(index, pclen, depth):
        et = (D - depth) / D
        pos = 0.5 if pclen == 1 else (index - 1.0) / (pclen - 1.0)
        el = (1.0 - et) * pos
        er = (1.0 - et) * (1.0 - el)   # note: 1 - FULL eta_l (tree2col.h:49)
        return el, er, et

    for root in range(1, n + 1):
        stack = [(root, 0)]
        visited = {root}
        el, er, et = eta(1, 1, 0)
        coef[root - 1, root - 1] += (el, er, et)
        while stack:
            node, depth = stack[-1]
            advanced = False
            for i, v in enumerate(tr[node]):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, depth + 1))
                    el, er, et = eta(i + 1, len(tr[node]), depth + 1)
                    coef[root - 1, v - 1] += (el, er, et)
                    advanced = True
            if not advanced:
                stack.pop()
    return coef


def _tree_conv_fn(nodes, coef, filt):
    # nodes [B,n,F], coef [B,n,n,3], filt [F,3,O,M]
    patch = jnp.einsum("buvj,bvf->bufj", coef, nodes)        # [B,n,F,3]
    B, n = patch.shape[0], patch.shape[1]
    w2 = filt.reshape(filt.shape[0] * 3, -1)                 # [F·3, O·M]
    out = patch.reshape(B, n, -1) @ w2                       # [B,n,O·M]
    return out.reshape(B, n, filt.shape[2], filt.shape[3])


_tree_conv_p = Primitive("tree_conv", _tree_conv_fn)


def tree_conv(nodes_vector, edge_set, filter, max_depth: int = 2):
    """tree_conv_op.h: tree-based convolution (TBCNN).  ``nodes_vector``
    [B, n, F] node features; ``edge_set`` [B, E, 2] int 1-based parent→child
    edges (0,0-terminated); ``filter`` [F, 3, O, M].  Patch coefficients
    (the eta triangle weights of tree2col.cc) are built host-side from the
    graph structure; the patch·filter contraction is one einsum+matmul, so
    grads flow to features and filter (tree_conv_grad parity)."""
    feats = _arr(nodes_vector)
    edges = _host(edge_set, np.int64)
    n = feats.shape[1]
    coef = np.stack([_tree_patch_coef(e, n, int(max_depth)) for e in edges])
    return _tree_conv_p(nodes_vector, jnp.asarray(coef), filter)


# ---------------------------------------------------------------------------
# correlation (FlowNet cost volume)
# ---------------------------------------------------------------------------

def _correlation_fn(x1, x2, pad_size=0, kernel_size=1, max_displacement=1,
                    stride1=1, stride2=1):
    B, C, H, W = x1.shape
    krad = (kernel_size - 1) // 2
    drad = max_displacement // stride2
    G = krad + max_displacement        # guard so every shift stays in-range
    pads = [(0, 0), (0, 0), (pad_size + G,) * 2, (pad_size + G,) * 2]
    p1 = jnp.pad(x1, pads)
    p2 = jnp.pad(x2, pads)
    A_h = H + 2 * pad_size - 2 * (krad + max_displacement)
    A_w = W + 2 * pad_size - 2 * (krad + max_displacement)
    out_h = -(-A_h // stride1)
    out_w = -(-A_w // stride1)
    Lh = A_h + 2 * krad                # rows touched by the window sweep
    Lw = A_w + 2 * krad
    s0 = max_displacement - krad + G   # first window row in padded coords

    outs = []
    for tj in range(-drad, drad + 1):
        for ti in range(-drad, drad + 1):
            sh = p2[:, :, s0 + tj * stride2: s0 + tj * stride2 + Lh,
                    s0 + ti * stride2: s0 + ti * stride2 + Lw]
            prod = (p1[:, :, s0:s0 + Lh, s0:s0 + Lw] * sh).sum(axis=1)
            win = jax.lax.reduce_window(
                prod, 0.0, jax.lax.add,
                (1, kernel_size, kernel_size), (1, stride1, stride1),
                "valid")
            outs.append(win[:, :out_h, :out_w])
    out = jnp.stack(outs, axis=1)      # [B, D², out_h, out_w]
    return out / (kernel_size * kernel_size * C)


_correlation_p = Primitive("correlation", _correlation_fn)


def correlation(x1, x2, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply: int = 1):
    """correlation_op.cu: FlowNet cost volume.  out[b, (tj,ti), y, x] =
    mean over channels and the kernel window of x1[·, y', x'] ·
    x2[·, y'+tj·stride2, x'+ti·stride2] on zero-padded inputs; output
    channel grid is (2·max_displacement/stride2+1)².  Only the multiply
    correlation type exists in the reference kernel (correlation_op.cu:128);
    pass corr_type_multiply=1."""
    if int(corr_type_multiply) != 1:
        raise NotImplementedError(
            "correlation: only corr_type_multiply=1 exists in the "
            "reference kernel (correlation_op.cu)")
    return _correlation_p(x1, x2, pad_size=int(pad_size),
                          kernel_size=int(kernel_size),
                          max_displacement=int(max_displacement),
                          stride1=int(stride1), stride2=int(stride2))


# ---------------------------------------------------------------------------
# prroi_pool (precise ROI pooling)
# ---------------------------------------------------------------------------

def _hat_integral(u):
    """F(u) = ∫_{-∞}^{u} max(0, 1-|s|) ds — closed form of the bilinear
    hat; coefficient of grid point g over window [a,b] is F(b-g)-F(a-g)
    (the analytic MatCalculation of prroi_pool_op.h:32)."""
    u = jnp.clip(u, -1.0, 1.0)
    neg = 0.5 * (u + 1.0) ** 2
    pos = 0.5 + u - 0.5 * u ** 2
    return jnp.where(u <= 0, neg, pos)


def _prroi_fn(x, rois, batch_ids, pooled_height=1, pooled_width=1,
              spatial_scale=1.0):
    N, C, H, W = x.shape
    ph, pw = pooled_height, pooled_width

    def one(roi, bid):
        sw, sh, ew, eh = (roi * spatial_scale)
        rw = jnp.maximum(ew - sw, 0.0)
        rh = jnp.maximum(eh - sh, 0.0)
        bh, bw = rh / ph, rw / pw
        win = jnp.maximum(bh * bw, 0.0)
        ys = sh + jnp.arange(ph) * bh                     # [ph]
        xs = sw + jnp.arange(pw) * bw                     # [pw]
        gy = jnp.arange(H)[None, :]
        gx = jnp.arange(W)[None, :]
        cy = _hat_integral(ys[:, None] + bh - gy) - \
            _hat_integral(ys[:, None] - gy)               # [ph, H]
        cx = _hat_integral(xs[:, None] + bw - gx) - \
            _hat_integral(xs[:, None] - gx)               # [pw, W]
        img = x[bid]                                      # [C, H, W]
        s = jnp.einsum("ph,qw,chw->cpq", cy, cx, img)
        return jnp.where(win > 0, s / jnp.maximum(win, 1e-12), 0.0)

    return jax.vmap(one)(rois.astype(jnp.float32),
                         batch_ids.astype(jnp.int32))


_prroi_p = Primitive("prroi_pool", _prroi_fn)


def prroi_pool(x, rois, pooled_height, pooled_width, spatial_scale=1.0,
               batch_roi=None):
    """prroi_pool_op.h: Precise RoI pooling — each bin is the EXACT
    integral of the bilinearly-interpolated feature over the bin window
    divided by the bin area (no sampling-point approximation).  The
    per-pixel hat-integral coefficients are closed-form, so one einsum per
    roi replaces the MatCalculation accumulation and jax.vjp yields both
    the feature and the roi-coordinate gradients (prroi_pool_grad).
    ``rois`` [R, 4] (x1, y1, x2, y2); ``batch_roi`` [R] image index per
    roi (defaults to all-zeros)."""
    r = _arr(rois)
    bids = jnp.zeros((r.shape[0],), jnp.int32) if batch_roi is None \
        else _arr(batch_roi)
    return _prroi_p(x, rois, bids, pooled_height=int(pooled_height),
                    pooled_width=int(pooled_width),
                    spatial_scale=float(spatial_scale))


# ---------------------------------------------------------------------------
# similarity_focus
# ---------------------------------------------------------------------------

def similarity_focus(x, axis: int, indexes):
    """similarity_focus_op.h: build a 0/1 focus mask of x's shape.  For
    each batch item and each index along ``axis``, greedily walk that
    slice's cells in descending value order, selecting cells whose
    remaining two coordinates are both unused (rows/cols marked used as
    selected) until min(dim_a, dim_b) cells are picked; selected
    positions light up across the WHOLE ``axis`` dimension.  Host-side:
    the sort + greedy tagging is sequential (a CPU-only kernel in the
    reference, with no grad op — the mask is non-differentiable)."""
    xa = _host(x, np.float64)
    if xa.ndim != 4:
        raise ValueError("similarity_focus expects a 4-D input")
    if axis not in (1, 2, 3):
        raise ValueError("axis must be 1, 2 or 3")
    out = np.zeros_like(xa, np.float32)
    other = [a for a in (1, 2, 3) if a != axis]
    B = xa.shape[0]
    for b in range(B):
        for index in indexes:
            sl = np.take(xa[b], int(index), axis=axis - 1)   # [da, db]
            da, db = sl.shape
            order = np.argsort(-sl, axis=None, kind="stable")
            used_a = np.zeros(da, bool)
            used_b = np.zeros(db, bool)
            picked = 0
            for flat in order:
                ia, ib = divmod(int(flat), db)
                if used_a[ia] or used_b[ib]:
                    continue
                used_a[ia] = used_b[ib] = True
                picked += 1
                idx = [slice(None)] * 3
                idx[other[0] - 1] = ia
                idx[other[1] - 1] = ib
                out[b][tuple(idx)] = 1.0
                if picked == min(da, db):
                    break
    return Tensor(jnp.asarray(out))


# ---------------------------------------------------------------------------
# deformable_psroi_pooling (DCN)
# ---------------------------------------------------------------------------

def _def_psroi_fn(x, rois, batch_ids, trans, no_trans=True,
                  spatial_scale=1.0, output_dim=1, group_height=1,
                  group_width=1, pooled_height=1, pooled_width=1,
                  part_height=1, part_width=1, sample_per_part=1,
                  trans_std=0.0):
    N, C, H, W = x.shape
    O, PH, PW, S = output_dim, pooled_height, pooled_width, sample_per_part
    num_classes = 1 if no_trans else trans.shape[1] // 2
    ceach = O // num_classes

    phs = jnp.arange(PH)
    pws = jnp.arange(PW)
    # per-bin part cell and group channel (static arithmetic)
    part_h = jnp.floor(phs.astype(jnp.float32) / PH * part_height
                       ).astype(jnp.int32)                      # [PH]
    part_w = jnp.floor(pws.astype(jnp.float32) / PW * part_width
                       ).astype(jnp.int32)                      # [PW]
    gh = jnp.clip((phs * group_height) // PH, 0, group_height - 1)
    gw = jnp.clip((pws * group_width) // PW, 0, group_width - 1)
    ctop = jnp.arange(O)
    chan = (ctop[:, None, None] * group_height + gh[None, :, None]) \
        * group_width + gw[None, None, :]                       # [O,PH,PW]
    class_id = ctop // ceach                                    # [O]

    def one(roi, bid, tr):
        r = jnp.round(roi)
        sw = r[0] * spatial_scale - 0.5
        sh = r[1] * spatial_scale - 0.5
        ew = (r[2] + 1.0) * spatial_scale - 0.5
        eh = (r[3] + 1.0) * spatial_scale - 0.5
        rw = jnp.maximum(ew - sw, 0.1)
        rh = jnp.maximum(eh - sh, 0.1)
        bh, bw = rh / PH, rw / PW
        sbh, sbw = bh / S, bw / S
        if no_trans:
            tx = jnp.zeros((1, PH, PW))
            ty = jnp.zeros((1, PH, PW))
        else:
            # trans [2·num_classes, part_h, part_w] → per (class, bin)
            t = tr.reshape(num_classes, 2, part_height, part_width)
            tx = t[:, 0][:, part_h][:, :, part_w] * trans_std
            ty = t[:, 1][:, part_h][:, :, part_w] * trans_std
        wstart = pws[None, None, :] * bw + sw + tx * rw         # [ncls,PH,PW]
        hstart = phs[None, :, None] * bh + sh + ty * rh
        iw = jnp.arange(S) * sbw
        ih = jnp.arange(S) * sbh
        ws = wstart[..., None, None] + iw[None, None, None, None, :]
        hs = hstart[..., None, None] + ih[None, None, None, :, None]
        valid = (ws >= -0.5) & (ws <= W - 0.5) & \
                (hs >= -0.5) & (hs <= H - 0.5)                  # [ncls,PH,PW,S,S]
        wc = jnp.clip(ws, 0.0, W - 1.0)
        hc = jnp.clip(hs, 0.0, H - 1.0)
        w0 = jnp.floor(wc).astype(jnp.int32)
        h0 = jnp.floor(hc).astype(jnp.int32)
        w1 = jnp.minimum(w0 + 1, W - 1)
        h1 = jnp.minimum(h0 + 1, H - 1)
        aw = wc - w0
        ah = hc - h0
        img = x[bid]                                            # [C,H,W]
        # broadcast class-indexed coords to every output channel
        ci = class_id
        samp_h0 = h0[ci]; samp_h1 = h1[ci]                      # [O,PH,PW,S,S]
        samp_w0 = w0[ci]; samp_w1 = w1[ci]
        a_w = aw[ci]; a_h = ah[ci]; v = valid[ci]
        ch = chan[..., None, None]                              # [O,PH,PW,1,1]
        ch = jnp.broadcast_to(ch, samp_h0.shape)
        g = lambda hh, ww: img[ch, hh, ww]
        val = (g(samp_h0, samp_w0) * (1 - a_h) * (1 - a_w)
               + g(samp_h0, samp_w1) * (1 - a_h) * a_w
               + g(samp_h1, samp_w0) * a_h * (1 - a_w)
               + g(samp_h1, samp_w1) * a_h * a_w)
        val = jnp.where(v, val, 0.0)
        cnt = v.sum(axis=(-1, -2))
        return jnp.where(cnt > 0, val.sum(axis=(-1, -2)) /
                         jnp.maximum(cnt, 1), 0.0)              # [O,PH,PW]

    tr_in = trans if not no_trans else jnp.zeros((rois.shape[0], 2,
                                                  part_height, part_width))
    return jax.vmap(one)(rois.astype(jnp.float32),
                         batch_ids.astype(jnp.int32), tr_in)


_def_psroi_p = Primitive("deformable_psroi_pooling", _def_psroi_fn)


def deformable_psroi_pooling(x, rois, trans=None, no_trans=None,
                             spatial_scale=1.0, output_dim=None,
                             group_size=1, pooled_size=1, part_size=None,
                             sample_per_part=1, trans_std=0.1,
                             batch_roi=None):
    """deformable_psroi_pooling_op.h: position-sensitive ROI pooling with
    learned per-part offsets (the DCN head).  ``x`` [N, C, H, W] with
    C = output_dim·group_h·group_w; ``rois`` [R, 4]; ``trans``
    [R, 2·num_classes, part_h, part_w] offsets (None ≙ no_trans).  Each
    bin averages sample_per_part² bilinear samples of its group channel,
    shifted by trans·trans_std·roi_size; out-of-image samples are
    dropped from the average (top_count semantics)."""
    gs = (group_size, group_size) if isinstance(group_size, int) \
        else tuple(group_size)
    ps = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else tuple(pooled_size)
    if part_size is None:
        part = ps
    else:
        part = (part_size, part_size) if isinstance(part_size, int) \
            else tuple(part_size)
    if no_trans is None:
        no_trans = trans is None
    r = _arr(rois)
    bids = jnp.zeros((r.shape[0],), jnp.int32) if batch_roi is None \
        else _arr(batch_roi)
    if output_dim is None:
        output_dim = _arr(x).shape[1] // (gs[0] * gs[1])
    args = [x, rois, bids]
    if trans is not None:
        args.append(trans)
    else:
        args.append(jnp.zeros((r.shape[0], 2, part[0], part[1]),
                              jnp.float32))
    return _def_psroi_p(*args, no_trans=bool(no_trans),
                        spatial_scale=float(spatial_scale),
                        output_dim=int(output_dim),
                        group_height=int(gs[0]), group_width=int(gs[1]),
                        pooled_height=int(ps[0]), pooled_width=int(ps[1]),
                        part_height=int(part[0]), part_width=int(part[1]),
                        sample_per_part=int(sample_per_part),
                        trans_std=float(trans_std))


# ---------------------------------------------------------------------------
# roi_perspective_transform (OCR detection)
# ---------------------------------------------------------------------------

_EPS = 1e-4


def _perspective_matrix(rx, ry, th, tw):
    """get_transform_matrix (roi_perspective_transform_op.cc:110): the
    homography mapping output-grid coords to the quad, with the
    reference's estimated/normalized width-height renormalization."""
    len1 = jnp.sqrt((rx[0] - rx[1]) ** 2 + (ry[0] - ry[1]) ** 2)
    len2 = jnp.sqrt((rx[1] - rx[2]) ** 2 + (ry[1] - ry[2]) ** 2)
    len3 = jnp.sqrt((rx[2] - rx[3]) ** 2 + (ry[2] - ry[3]) ** 2)
    len4 = jnp.sqrt((rx[3] - rx[0]) ** 2 + (ry[3] - ry[0]) ** 2)
    est_h = (len2 + len4) / 2.0
    est_w = (len1 + len3) / 2.0
    nh = max(2, th)
    nw_f = jnp.round(est_w * (nh - 1) / jnp.maximum(est_h, _EPS)) + 1
    nw = jnp.clip(nw_f, 2, tw)
    dx1 = rx[1] - rx[2]
    dx2 = rx[3] - rx[2]
    dx3 = rx[0] - rx[1] + rx[2] - rx[3]
    dy1 = ry[1] - ry[2]
    dy2 = ry[3] - ry[2]
    dy3 = ry[0] - ry[1] + ry[2] - ry[3]
    den = dx1 * dy2 - dx2 * dy1 + 1e-5
    m6 = (dx3 * dy2 - dx2 * dy3) / den / (nw - 1)
    m7 = (dx1 * dy3 - dx3 * dy1) / den / (nh - 1)
    m8 = jnp.asarray(1.0)
    m3 = (ry[1] - ry[0] + m6 * (nw - 1) * ry[1]) / (nw - 1)
    m4 = (ry[3] - ry[0] + m7 * (nh - 1) * ry[3]) / (nh - 1)
    m5 = ry[0]
    m0 = (rx[1] - rx[0] + m6 * (nw - 1) * rx[1]) / (nw - 1)
    m1 = (rx[3] - rx[0] + m7 * (nh - 1) * rx[3]) / (nh - 1)
    m2 = rx[0]
    return jnp.stack([m0, m1, m2, m3, m4, m5, m6, m7, m8])


def _in_quad(px, py, rx, ry):
    """Point-in-quadrilateral with the reference's epsilon edge rules +
    crossing count (roi_perspective_transform_op.cc:46)."""
    on_edge = jnp.zeros(px.shape, bool)
    n_cross = jnp.zeros(px.shape, jnp.int32)
    for i in range(4):
        xs, ys = rx[i], ry[i]
        xe, ye = rx[(i + 1) % 4], ry[(i + 1) % 4]
        horiz = jnp.abs(ys - ye) < _EPS
        on_h = horiz & (jnp.abs(py - ys) < _EPS) & (jnp.abs(py - ye) < _EPS) \
            & (px >= jnp.minimum(xs, xe) - _EPS) \
            & (px <= jnp.maximum(xs, xe) + _EPS)
        ix = (py - ys) * (xe - xs) / jnp.where(horiz, 1.0, ye - ys) + xs
        in_y = (py >= jnp.minimum(ys, ye) - _EPS) & \
               (py <= jnp.maximum(ys, ye) + _EPS)
        on_v = (~horiz) & (jnp.abs(ix - px) < _EPS) & in_y
        on_edge |= on_h | on_v
        crossing_y = (py > jnp.minimum(ys, ye) + _EPS) & \
                     (py <= jnp.maximum(ys, ye) + _EPS)
        n_cross += jnp.where((~horiz) & crossing_y & (ix > px + _EPS),
                             1, 0)
    return on_edge | (n_cross % 2 == 1)


def _roi_perspective_fn(x, rois, batch_ids, transformed_height=1,
                        transformed_width=1, spatial_scale=1.0):
    N, C, H, W = x.shape
    th, tw = transformed_height, transformed_width

    def one(roi, bid):
        rx = roi[0::2] * spatial_scale
        ry = roi[1::2] * spatial_scale
        m = _perspective_matrix(rx, ry, th, tw)
        ow = jnp.arange(tw)[None, :].astype(jnp.float32)
        oh = jnp.arange(th)[:, None].astype(jnp.float32)
        den = m[6] * ow + m[7] * oh + m[8]
        in_w = (m[0] * ow + m[1] * oh + m[2]) / den           # [th, tw]
        in_h = (m[3] * ow + m[4] * oh + m[5]) / den
        inside_q = _in_quad(in_w, in_h, rx, ry)
        in_bounds = (in_w > -0.5 + _EPS) & (in_w < W - 0.5 - _EPS) & \
                    (in_h > -0.5 + _EPS) & (in_h < H - 0.5 - _EPS)
        mask = inside_q & in_bounds
        wc = jnp.clip(in_w, 0.0, W - 1.0)
        hc = jnp.clip(in_h, 0.0, H - 1.0)
        w0 = jnp.floor(wc).astype(jnp.int32)
        h0 = jnp.floor(hc).astype(jnp.int32)
        w1 = jnp.minimum(w0 + 1, W - 1)
        h1 = jnp.minimum(h0 + 1, H - 1)
        aw = wc - w0
        ah = hc - h0
        img = x[bid]                                          # [C,H,W]
        val = (img[:, h0, w0] * (1 - ah) * (1 - aw)
               + img[:, h0, w1] * (1 - ah) * aw
               + img[:, h1, w0] * ah * (1 - aw)
               + img[:, h1, w1] * ah * aw)                    # [C,th,tw]
        out = jnp.where(mask[None], val, 0.0)
        return out, mask.astype(jnp.int32)[None], m

    return jax.vmap(one)(rois.astype(jnp.float32),
                         batch_ids.astype(jnp.int32))


_roi_perspective_p = Primitive("roi_perspective_transform",
                               _roi_perspective_fn, multi_output=True)


def roi_perspective_transform(x, rois, transformed_height, transformed_width,
                              spatial_scale=1.0, batch_roi=None):
    """roi_perspective_transform_op.cc: crop each quadrilateral ROI
    (``rois`` [R, 8] = 4 corner (x, y) pairs) through its perspective
    homography into a [transformed_height, transformed_width] patch with
    bilinear sampling; pixels mapping outside the quad or the feature
    bounds are zero.  Returns (out [R, C, th, tw], mask [R, 1, th, tw],
    transform_matrix [R, 9])."""
    r = _arr(rois)
    bids = jnp.zeros((r.shape[0],), jnp.int32) if batch_roi is None \
        else _arr(batch_roi)
    return _roi_perspective_p(x, rois, bids,
                              transformed_height=int(transformed_height),
                              transformed_width=int(transformed_width),
                              spatial_scale=float(spatial_scale))


# ---------------------------------------------------------------------------
# bilateral_slice (HDRnet)
# ---------------------------------------------------------------------------

def _bilateral_slice_fn(grid, guide, inp, has_offset=False):
    B, Cg, gd, gh, gw = grid.shape
    _, C, H, W = inp.shape
    cs = C + 1 if has_offset else C
    out_c = Cg // cs

    gx = (jnp.arange(W) + 0.5) * gw / W                       # [W]
    gy = (jnp.arange(H) + 0.5) * gh / H                       # [H]
    gz = guide * gd                                           # [B,H,W]

    def corners(v, size):
        f = jnp.floor(v - 0.5)
        w0 = jnp.maximum(1.0 - jnp.abs(f + 0.5 - v), 0.0)
        i0 = jnp.clip(f.astype(jnp.int32), 0, size - 1)
        w1 = jnp.maximum(1.0 - jnp.abs(f + 1.5 - v), 0.0)
        i1 = jnp.clip(f.astype(jnp.int32) + 1, 0, size - 1)
        return (i0, w0), (i1, w1)

    xc = corners(gx, gw)
    yc = corners(gy, gh)
    zc = corners(gz, gd)
    coeff = jnp.zeros((B, Cg, H, W), grid.dtype)
    for zi, zwt in zc:        # zi [B,H,W]
        for yi, ywt in yc:    # yi [H]
            for xi, xwt in xc:
                # advanced indexing: zi [B,H,W] broadcasts with yi/xi grids
                yi_b = jnp.broadcast_to(yi[:, None], (H, W))
                xi_b = jnp.broadcast_to(xi[None, :], (H, W))
                samp = grid[jnp.arange(B)[:, None, None, None],
                            jnp.arange(Cg)[None, :, None, None],
                            zi[:, None], yi_b[None, None], xi_b[None, None]]
                wt = (zwt[:, None] * ywt[None, None, :, None]
                      * xwt[None, None, None, :])             # [B,1,H,W]
                coeff = coeff + samp * wt
    c4 = coeff.reshape(B, out_c, cs, H, W)
    out = jnp.einsum("bocHW,bcHW->boHW", c4[:, :, :C], inp)
    if has_offset:
        out = out + c4[:, :, C]
    return out


_bilateral_slice_p = Primitive("bilateral_slice", _bilateral_slice_fn)


def bilateral_slice(x, guide, grid, has_offset: bool = False):
    """bilateral_slice_op.cu (python arg order:
    contrib/layers/nn.py:1491 bilateral_slice(x, guide, grid, has_offset)):
    HDRnet slicing — per output pixel, hat-weighted trilinear sample of
    the bilateral ``grid`` [B, coeff_ch, gd, gh, gw] at (x·gw/W, y·gh/H,
    guide·gd), applying the sliced per-pixel affine coefficients to ``x``
    [B, C, H, W] (coeff_ch = (C+1)·out_c with offset, C·out_c without).
    One gather per corner + einsum; grads flow to grid, guide and input
    (bilateral_slice_grad parity)."""
    return _bilateral_slice_p(grid, guide, x, has_offset=bool(has_offset))


# ---------------------------------------------------------------------------
# multi_gru
# ---------------------------------------------------------------------------

def multi_gru(x, weight_x, weight_h, bias=None, layers: int = 1,
              origin_mode: bool = False, lengths=None):
    """fused/multi_gru_op.cc: stacked BIDIRECTIONAL GRU — 2·layers weight
    pairs (forward/backward per layer, multi_gru_op.cc:61), each layer
    consuming the previous layer's fwd‖bwd concat.  The reference op is a
    oneDNN x86 inference fusion; on TPU the same capability is this
    composition — XLA fuses the scan body itself, so the fusion axis has
    no separate kernel.  ``x`` [B, T, I]; weight_x[i] [I_i, 3H],
    weight_h[i] [H, 3H], bias[i] [3H]; gate order (u, r, c) as
    fusion_gru; origin_mode picks h' = u·h + (1-u)·c.  Returns
    [B, T, 2H] of the last layer."""
    def cell(xg, h, wh, origin):
        H_ = h.shape[-1]
        hg = h @ wh[:, :2 * H_]
        u = jax.nn.sigmoid(xg[:, :H_] + hg[:, :H_])
        r = jax.nn.sigmoid(xg[:, H_:2 * H_] + hg[:, H_:])
        c = jnp.tanh(xg[:, 2 * H_:] + (r * h) @ wh[:, 2 * H_:])
        return u * h + (1 - u) * c if origin else (1 - u) * h + u * c

    xa = _arr(x).astype(jnp.float32)
    B, T, _ = xa.shape
    mask = None
    if lengths is not None:
        mask = jnp.arange(T)[None, :] < _arr(lengths)[:, None]   # [B,T]

    out = xa
    for layer in range(int(layers)):
        dirs = []
        for d in range(2):
            i = 2 * layer + d
            wx = _arr(weight_x[i]).astype(jnp.float32)
            wh = _arr(weight_h[i]).astype(jnp.float32)
            b = None if bias is None else _arr(bias[i]).astype(jnp.float32)
            xs = out if d == 0 else out[:, ::-1]
            m = mask if d == 0 else (None if mask is None
                                     else mask[:, ::-1])
            xg = xs @ wx + (0 if b is None else b)               # [B,T,3H]
            Hsz = wh.shape[0]

            if m is None:
                def step(h, g):
                    h_new = cell(g, h, wh, origin_mode)
                    return h_new, h_new
                seq = jnp.swapaxes(xg, 0, 1)
            else:
                def step(h, t):
                    g, mt = t
                    h_new = cell(g, h, wh, origin_mode)
                    h_new = jnp.where(mt[:, None], h_new, h)
                    return h_new, h_new
                seq = (jnp.swapaxes(xg, 0, 1), jnp.swapaxes(m, 0, 1))
            _, hs = jax.lax.scan(step, jnp.zeros((B, Hsz)), seq)
            hs = jnp.swapaxes(hs, 0, 1)                          # [B,T,H]
            dirs.append(hs if d == 0 else hs[:, ::-1])
        out = jnp.concatenate(dirs, axis=-1)
        if mask is not None:
            out = out * mask[..., None]
    return Tensor(out)
