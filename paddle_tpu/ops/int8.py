"""Int8 inference primitives: true integer MXU compute.

Reference parity: the execution half of the slim deploy story —
QuantizationFreezePass rewrites matmul/conv sites to the int8 kernels of
operators/fake_dequantize_op.cc + the cuDNN/TensorRT int8 engines.  On TPU
the MXU consumes int8 operands natively: ``lax.dot_general`` /
``lax.conv_general_dilated`` with ``preferred_element_type=jnp.int32``
emit integer dot/convolution StableHLO (i8×i8→i32 systolic passes, 2-4x
the bf16 MACs/cycle), and the requantize/dequantize epilogue is a cheap
VPU multiply fused by XLA onto the accumulator tiles.

These primitives are the first place the repo emits integer-compute HLO
rather than float-with-simulated-rounding.  They are inference-only
(``differentiable=False``) and AMP-exempt: autocast must never touch the
int8 operands or the fp32 scale epilogue (amp/__init__.py AMP_EXEMPT).

Quantization convention (shared with quantization/functional.py):
symmetric, qmax = 2^(bits-1)-1 = 127; activations clip to [-scale, scale]
before rounding (the fake-QDQ contract, so frozen numerics match the QAT
simulation bit-for-bit up to float associativity).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.primitive import Primitive

QMAX_INT8 = 127.0


def _quantize_act(x, scale, qmax):
    """fp -> int8 on the activation path: clip to the calibrated range,
    round-half-away like the fake-QDQ ops (jnp.round matches)."""
    s = jnp.maximum(scale, 1e-9).astype(jnp.float32)
    q = jnp.round(jnp.clip(x.astype(jnp.float32) / s, -1.0, 1.0) * qmax)
    return q.astype(jnp.int8), s


def _epilogue(acc_i32, s_x, s_w, qmax, bias, out_scale, out_dtype):
    """ONE fused requantize/dequantize epilogue over the int32 accumulator:
    dequant by (s_x/qmax)*(s_w/qmax), add bias, and — when the freeze pass
    recorded an out-scale for this site — requantize the output onto the
    int8 grid of the NEXT layer's input (the reference's quantize_op after
    dequantize fold, one round+mul here instead of a QDQ pair)."""
    deq = acc_i32.astype(jnp.float32) * (s_x / qmax) * (s_w / qmax)
    if bias is not None:
        deq = deq + bias.astype(jnp.float32)
    if out_scale is not None:
        so = jnp.maximum(out_scale, 1e-9).astype(jnp.float32)
        deq = jnp.round(jnp.clip(deq / so, -1.0, 1.0) * qmax) * (so / qmax)
    return deq.astype(out_dtype)


def _linear_int8_fn(x, w_q, s_x, s_w, *rest, bits=8, has_bias=False,
                    has_out_scale=False, dynamic=False):
    """x [.., in] fp; w_q [in, out] int8; s_w [1, out] (per-channel) or
    scalar (per-tensor); s_x scalar.  int8×int8→int32 on the MXU."""
    qmax = float(2 ** (bits - 1) - 1)
    rest = list(rest)
    bias = rest.pop(0) if has_bias else None
    out_scale = rest.pop(0) if has_out_scale else None
    if dynamic:
        s_x = jnp.max(jnp.abs(x))
    x_q, s_x = _quantize_act(x, s_x, qmax)
    acc = jax.lax.dot_general(
        x_q, w_q,
        dimension_numbers=(((x_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    s_w = jnp.reshape(s_w.astype(jnp.float32), (-1,))   # broadcast over out
    return _epilogue(acc, s_x, s_w, qmax, bias, out_scale, x.dtype)


_linear_int8_p = Primitive("linear_int8", _linear_int8_fn,
                           differentiable=False)


def linear_int8(x, w_q, s_x, s_w, bias=None, out_scale=None, bits=8,
                dynamic=False):
    """Frozen linear site: quantize input at ``s_x`` (or dynamically when
    ``dynamic``), int8 matmul with int32 accumulation, fused epilogue."""
    args = [x, w_q, s_x, s_w]
    if bias is not None:
        args.append(bias)
    if out_scale is not None:
        args.append(out_scale)
    return _linear_int8_p(*args, bits=int(bits), has_bias=bias is not None,
                          has_out_scale=out_scale is not None,
                          dynamic=bool(dynamic))


def _conv2d_int8_fn(x, w_q, s_x, s_w, *rest, bits=8, has_bias=False,
                    has_out_scale=False, dynamic=False, stride=(1, 1),
                    padding="VALID", dilation=(1, 1), groups=1,
                    channel_last=False):
    """x NCHW/NHWC fp; w_q OIHW int8; s_w [O] per-channel or scalar."""
    qmax = float(2 ** (bits - 1) - 1)
    rest = list(rest)
    bias = rest.pop(0) if has_bias else None
    out_scale = rest.pop(0) if has_out_scale else None
    if dynamic:
        s_x = jnp.max(jnp.abs(x))
    x_q, s_x = _quantize_act(x, s_x, qmax)
    if channel_last:
        w_q = jnp.transpose(w_q, (2, 3, 1, 0))          # OIHW -> HWIO
        specs = ("NHWC", "HWIO", "NHWC")
    else:
        specs = ("NCHW", "OIHW", "NCHW")
    dn = jax.lax.conv_dimension_numbers(x_q.shape, w_q.shape, specs)
    acc = jax.lax.conv_general_dilated(
        x_q, w_q, window_strides=stride, padding=padding,
        rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups, preferred_element_type=jnp.int32)
    cshape = (1, 1, 1, -1) if channel_last else (1, -1, 1, 1)
    s_w = jnp.reshape(s_w.astype(jnp.float32), cshape)
    if bias is not None:
        bias = jnp.reshape(bias, cshape)
    if out_scale is not None and out_scale.ndim:
        out_scale = jnp.reshape(out_scale, ())
    return _epilogue(acc, s_x, s_w, qmax, bias, out_scale, x.dtype)


_conv2d_int8_p = Primitive("conv2d_int8", _conv2d_int8_fn,
                           differentiable=False)


def conv2d_int8(x, w_q, s_x, s_w, bias=None, out_scale=None, bits=8,
                dynamic=False, stride=(1, 1), padding="VALID",
                dilation=(1, 1), groups=1, channel_last=False):
    """Frozen conv2d site (weights OIHW int8, per-output-channel scales)."""
    args = [x, w_q, s_x, s_w]
    if bias is not None:
        args.append(bias)
    if out_scale is not None:
        args.append(out_scale)
    return _conv2d_int8_p(
        *args, bits=int(bits), has_bias=bias is not None,
        has_out_scale=out_scale is not None, dynamic=bool(dynamic),
        stride=tuple(int(s) for s in stride), padding=padding,
        dilation=tuple(int(d) for d in dilation), groups=int(groups),
        channel_last=bool(channel_last))


def _matmul_int8_fn(a_q, b_q):
    return jax.lax.dot_general(
        a_q, b_q, dimension_numbers=(((a_q.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


_matmul_int8_p = Primitive("matmul_int8", _matmul_int8_fn,
                           differentiable=False)


def matmul_int8(a_q, b_q):
    """Raw int8×int8→int32 matmul (no epilogue) — the building block the
    frozen sites compose; exposed for custom int8 graphs."""
    return _matmul_int8_p(a_q, b_q)
