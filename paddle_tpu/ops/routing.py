"""Mesh routing primitives: static-cap owner bucketing + all-to-all row moves.

Reference parity: the HeterPS sparse-table shards
(framework/fleet/heter_ps/hashtable.h — per-GPU hash shards, ids routed to
the owning card before the gather) and the PS shard rule
(distributed/ps — ``id % shard_num`` picks the server).  TPU-first: there
is no RPC hop; the table is ONE array row-partitioned over a mesh axis
(``P(axis, None)``) and the id routing is a ``lax.all_to_all`` inside
``shard_map``, entirely inside the jitted step — steady state moves only
ICI bytes, zero host bytes.

Layout contract (every helper here shares it):

  * a table of ``vocab`` logical rows over ``n`` shards stores
    ``rps = ceil(vocab / n)`` real rows **plus one scratch row** per shard
    — global shape ``[(rps + 1) * n, dim]``, sharded ``P(axis, None)``.
    The scratch row (local index ``rps``) absorbs every padded/sentinel
    request, so masked routing never needs a select against a real row
    (duplicate-index scatter hazards collapse onto a row nobody reads).
  * logical id ``i`` lives on shard ``i // rps`` at local row ``i % rps``;
    :func:`storage_index` maps logical ids to rows of the global array.
  * request vectors carry sentinel ``-1`` for padding; their length must
    divide by ``n`` (each shard owns a ``U / n`` slice of the requests).

Bucketing is STATIC-shape: each shard packs its requests into an
``[n, cap]`` send buffer grouped by owner shard.  ``cap`` defaults to the
whole per-shard slice (overflow impossible); a smaller cap shrinks the
routed buffers and the pack reports ``overflow`` so callers can re-run an
octave up (the device-dedup protocol of ``rec.wide_deep``).
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:                                     # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:                      # pragma: no cover - version compat
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = [
    "PackPlan", "rows_per_shard", "storage_table_rows", "storage_index",
    "pad_requests", "pack_by_owner", "all_to_all_gather", "all_to_all_set",
    "all_to_all_apply_rule", "a2a_wire_bytes",
]


def rows_per_shard(vocab: int, n_shards: int) -> int:
    """Real rows each shard owns for a ``vocab``-row table."""
    return max(1, -(-int(vocab) // int(n_shards)))


def storage_table_rows(vocab: int, n_shards: int) -> int:
    """Global row count of the storage array (incl. per-shard scratch)."""
    return (rows_per_shard(vocab, n_shards) + 1) * int(n_shards)


def storage_index(ids, rps: int):
    """Logical id -> row of the ``[(rps+1)*n, D]`` storage array (works on
    numpy and jnp arrays; ids must be >= 0)."""
    owner = ids // rps
    return owner * (rps + 1) + (ids - owner * rps)


def pad_requests(n: int, n_shards: int, pad) -> int:
    """Octave-pad a request count AND round up to a shard multiple, so the
    padded vector splits evenly over the routing axis.  ``pad`` is the
    octave function (``pad_adaptive``-style); compile count stays bounded
    by the octave ladder."""
    base = max(int(n_shards), int(pad(max(1, n))))
    return -(-base // n_shards) * n_shards


class PackPlan(NamedTuple):
    """One shard's static-shape owner bucketing of its request slice."""

    send_ids: jnp.ndarray    # [n*cap] int32, grouped by owner, -1 padding
    pos: jnp.ndarray         # [u] int32 slot of each request (-1 = dropped)
    counts: jnp.ndarray      # [n] int32 per-owner request counts
    overflow: jnp.ndarray    # bool: some owner's count exceeded cap


def pack_by_owner(ids, *, n_shards: int, rps: int, cap: int) -> PackPlan:
    """Group a request slice by owner shard into a ``[n*cap]`` send buffer.

    ``ids`` is ``[u]`` int (sentinel ``< 0`` entries are excluded and never
    consume cap).  Pure jnp — usable outside any mesh for tests, and
    traced inside shard_map bodies for the real thing.
    """
    u = ids.shape[0]
    ids = ids.astype(jnp.int32)
    valid = ids >= 0
    # sentinels sort AFTER every real owner so the grouped prefix is dense
    owner = jnp.where(valid, ids // rps, n_shards)
    order = jnp.argsort(owner)
    so = owner[order]
    rank = jnp.arange(u, dtype=jnp.int32) - jnp.searchsorted(
        so, so, side="left").astype(jnp.int32)
    ok = (so < n_shards) & (rank < cap)
    # the +1 tail slot absorbs every dropped write (OOB-free scatter)
    slot = jnp.where(ok, so.astype(jnp.int32) * cap + rank, n_shards * cap)
    send = jnp.full((n_shards * cap + 1,), -1, jnp.int32).at[slot].set(
        ids[order])[:-1]
    pos = jnp.full((u,), -1, jnp.int32).at[order].set(
        jnp.where(ok, slot, -1).astype(jnp.int32))
    counts = jax.ops.segment_sum(valid.astype(jnp.int32),
                                 jnp.clip(owner, 0, n_shards - 1),
                                 num_segments=n_shards)
    return PackPlan(send, pos, counts, jnp.max(counts) > cap)


def _scatter_to_slots(values, pos, n_slots):
    """Place per-request rows at their send-buffer slots (pos -1 dropped)."""
    width = values.shape[1:]
    buf = jnp.zeros((n_slots + 1,) + width, values.dtype)
    slot = jnp.where(pos >= 0, pos, n_slots)
    return buf.at[slot].set(values)[:-1]


def _local_rows(req, rps: int, axis: str):
    """Received request ids -> local row indices (scratch for sentinels)."""
    me = lax.axis_index(axis)
    return jnp.where(req >= 0, req - me * rps, rps)


# ---------------------------------------------------------------------------
# shard_map bodies
# ---------------------------------------------------------------------------

def _gather_body(ids_loc, *arrs_loc, axis, n, rps, cap):
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)                  # [n, cap] asks for MY rows
    local = _local_rows(req, rps, axis)
    outs = []
    for a in arrs_loc:                                # each [rps+1, D]
        rows = a[local]                               # [n, cap, D]
        back = lax.all_to_all(rows, axis, 0, 0, tiled=True)
        flat = back.reshape((n * cap,) + back.shape[2:])
        got = flat[jnp.clip(plan.pos, 0, n * cap - 1)]
        outs.append(jnp.where((plan.pos >= 0).reshape(
            (-1,) + (1,) * (got.ndim - 1)), got, 0))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf,) + tuple(outs)


def _set_body(ids_loc, rows_and_tables, axis, n, rps, cap, n_arrays):
    rows_loc = rows_and_tables[:n_arrays]
    arrs_loc = rows_and_tables[n_arrays:]
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)
    local = _local_rows(req, rps, axis)
    outs = []
    for a, r in zip(arrs_loc, rows_loc):
        buf = _scatter_to_slots(r, plan.pos, n * cap)
        recv = lax.all_to_all(buf.reshape((n, cap) + buf.shape[1:]),
                              axis, 0, 0, tiled=True)
        outs.append(a.at[local].set(recv))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf,) + tuple(outs)


def _apply_body(ids_loc, grads_loc, table_loc, *state_loc, axis, n, rps,
                cap, opt, hyper, state_names):
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)
    local = _local_rows(req, rps, axis)
    gbuf = _scatter_to_slots(grads_loc, plan.pos, n * cap)
    grecv = lax.all_to_all(gbuf.reshape((n, cap) + gbuf.shape[1:]),
                           axis, 0, 0, tiled=True)
    flat_local = local.reshape(-1)
    rows = table_loc[flat_local]
    st = {k: s[flat_local] for k, s in zip(state_names, state_loc)}
    from ..distributed.ps.device_cache import apply_rule_device
    new_rows, new_st = apply_rule_device(
        opt, rows, st, grecv.reshape((n * cap,) + grecv.shape[2:]), **hyper)
    # scratch entries carry zero grads: the rule is a no-op there, and
    # duplicate scratch writes all land the same (irrelevant) value
    new_table = table_loc.at[flat_local].set(new_rows)
    new_state = tuple(state_loc[i].at[flat_local].set(new_st[k])
                      for i, k in enumerate(state_names))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf, new_table) + new_state


# ---------------------------------------------------------------------------
# public wrappers
# ---------------------------------------------------------------------------

def _route_params(mesh, axis: str, n_ids: int, cap: Optional[int]):
    n = int(dict(mesh.shape)[axis])
    if n_ids % n:
        raise ValueError(
            f"routing over axis {axis!r} (size {n}) needs the request "
            f"vector length ({n_ids}) divisible by the axis size — pad "
            f"with sentinel -1 (ops.routing.pad_requests)")
    u = n_ids // n
    cap = u if not cap else min(int(cap), u)
    return n, cap


def all_to_all_gather(arrays: Sequence, ids, *, mesh, axis: str, rps: int,
                      cap: Optional[int] = None):
    """Routed multi-array row lookup.

    ``arrays``: sharded ``[(rps+1)*n, D_i]`` storage arrays (rows +
    optimizer-state planes travel in ONE routed exchange of ids).
    ``ids``: ``[U]`` logical ids (sentinel -1), ``U % n == 0``.
    Returns ``(rows_list, overflow)`` — each ``[U, D_i]`` aligned with
    ``ids`` (zeros at sentinel slots), overflow an int32 scalar (>0 when
    some shard's per-owner count exceeded ``cap``).
    """
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)
    body = functools.partial(_gather_body, axis=axis, n=n, rps=rps, cap=cap)
    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) + (P(axis, None),) * len(arrays),
        out_specs=(P(),) + (P(axis, None),) * len(arrays),
        check_rep=False)
    out = fn(ids, *arrays)
    return list(out[1:]), out[0]


def all_to_all_set(arrays: Sequence, ids, rows: Sequence, *, mesh,
                   axis: str, rps: int, cap: Optional[int] = None):
    """Routed row import: write ``rows[i]`` (``[U, D_i]``, aligned with
    ``ids``) into each storage array at the owner shards.  Sentinel ids
    land on the owner's scratch row.  Returns ``(new_arrays, overflow)``.
    """
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)

    def wrapped(ids_loc, *packed):
        return _set_body(ids_loc, packed, axis=axis, n=n, rps=rps, cap=cap,
                         n_arrays=len(arrays))

    fn = _shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(axis),) + (P(axis, None),) * len(arrays)
        + (P(axis, None),) * len(arrays),
        out_specs=(P(),) + (P(axis, None),) * len(arrays),
        check_rep=False)
    out = fn(ids, *rows, *arrays)
    return list(out[1:]), out[0]


def all_to_all_apply_rule(table, state: dict, ids, grads, *, opt: str,
                          hyper: dict, mesh, axis: str, rps: int,
                          cap: Optional[int] = None):
    """Routed sparse-optimizer update: route ``(id, grad)`` pairs to the
    owner shards, apply the on-chip rule (``device_cache.DEVICE_RULES``)
    to the local rows + state, scatter in place.  The backward leg of the
    all-to-all lookup: updates touch ONLY the owning shard's slice.
    Returns ``(new_table, new_state, overflow)``."""
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)
    names = tuple(sorted(state))
    body = functools.partial(_apply_body, axis=axis, n=n, rps=rps, cap=cap,
                             opt=opt, hyper=dict(hyper), state_names=names)
    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis, None)) + (P(axis, None),) * (1 + len(names)),
        out_specs=(P(),) + (P(axis, None),) * (1 + len(names)),
        check_rep=False)
    out = fn(ids, grads, table, *[state[k] for k in names])
    new_state = {k: out[2 + i] for i, k in enumerate(names)}
    return out[1], new_state, out[0]


def a2a_wire_bytes(n_requests: int, dim: int, n_shards: int, cap: int,
                   itemsize: int = 4, n_planes: int = 1) -> int:
    """Ring-model per-device interconnect bytes of one routed gather:
    ids out + ids' worth of row planes back (and the same shape again for
    a set/update leg).  ``(n-1)/n`` of an all-to-all buffer actually
    crosses the wire."""
    n = int(n_shards)
    if n <= 1:
        return 0
    buf_ids = n * cap * 4
    buf_rows = n * cap * dim * itemsize * n_planes
    return int((buf_ids + buf_rows) * (n - 1) / n)
