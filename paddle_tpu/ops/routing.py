"""Mesh routing primitives: static-cap owner bucketing + all-to-all row moves.

Reference parity: the HeterPS sparse-table shards
(framework/fleet/heter_ps/hashtable.h — per-GPU hash shards, ids routed to
the owning card before the gather) and the PS shard rule
(distributed/ps — ``id % shard_num`` picks the server).  TPU-first: there
is no RPC hop; the table is ONE array row-partitioned over a mesh axis
(``P(axis, None)``) and the id routing is a ``lax.all_to_all`` inside
``shard_map``, entirely inside the jitted step — steady state moves only
ICI bytes, zero host bytes.

Layout contract (every helper here shares it):

  * a table of ``vocab`` logical rows over ``n`` shards stores
    ``rps = ceil(vocab / n)`` real rows **plus one scratch row** per shard
    — global shape ``[(rps + 1) * n, dim]``, sharded ``P(axis, None)``.
    The scratch row (local index ``rps``) absorbs every padded/sentinel
    request, so masked routing never needs a select against a real row
    (duplicate-index scatter hazards collapse onto a row nobody reads).
  * logical id ``i`` lives on shard ``i // rps`` at local row ``i % rps``;
    :func:`storage_index` maps logical ids to rows of the global array.
  * request vectors carry sentinel ``-1`` for padding; their length must
    divide by ``n`` (each shard owns a ``U / n`` slice of the requests).

Bucketing is STATIC-shape: each shard packs its requests into an
``[n, cap]`` send buffer grouped by owner shard.  ``cap`` defaults to the
whole per-shard slice (overflow impossible); a smaller cap shrinks the
routed buffers and the pack reports ``overflow`` so callers can re-run an
octave up (the device-dedup protocol of ``rec.wide_deep``).
"""
from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

try:                                     # jax >= 0.6
    from jax import shard_map as _shard_map
except ImportError:                      # pragma: no cover - version compat
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = [
    "PackPlan", "rows_per_shard", "storage_table_rows", "storage_index",
    "pad_requests", "pack_by_owner", "all_to_all_gather", "all_to_all_set",
    "all_to_all_apply_rule", "a2a_wire_bytes",
    "ExpertPlan", "moe_capacity", "expert_dispatch_plan",
    "all_to_all_experts", "local_experts", "moe_a2a_wire_bytes",
]


def rows_per_shard(vocab: int, n_shards: int) -> int:
    """Real rows each shard owns for a ``vocab``-row table."""
    return max(1, -(-int(vocab) // int(n_shards)))


def storage_table_rows(vocab: int, n_shards: int) -> int:
    """Global row count of the storage array (incl. per-shard scratch)."""
    return (rows_per_shard(vocab, n_shards) + 1) * int(n_shards)


def storage_index(ids, rps: int):
    """Logical id -> row of the ``[(rps+1)*n, D]`` storage array (works on
    numpy and jnp arrays; ids must be >= 0)."""
    owner = ids // rps
    return owner * (rps + 1) + (ids - owner * rps)


def pad_requests(n: int, n_shards: int, pad) -> int:
    """Octave-pad a request count AND round up to a shard multiple, so the
    padded vector splits evenly over the routing axis.  ``pad`` is the
    octave function (``pad_adaptive``-style); compile count stays bounded
    by the octave ladder."""
    base = max(int(n_shards), int(pad(max(1, n))))
    return -(-base // n_shards) * n_shards


class PackPlan(NamedTuple):
    """One shard's static-shape owner bucketing of its request slice."""

    send_ids: jnp.ndarray    # [n*cap] int32, grouped by owner, -1 padding
    pos: jnp.ndarray         # [u] int32 slot of each request (-1 = dropped)
    counts: jnp.ndarray      # [n] int32 per-owner request counts
    overflow: jnp.ndarray    # bool: some owner's count exceeded cap


def pack_by_owner(ids, *, n_shards: int, rps: int, cap: int,
                  with_send: bool = True) -> PackPlan:
    """Group a request slice by owner shard into a ``[n*cap]`` send buffer.

    ``ids`` is ``[u]`` int (sentinel ``< 0`` entries are excluded and never
    consume cap).  Pure jnp — usable outside any mesh for tests, and
    traced inside shard_map bodies for the real thing.  Callers that only
    need the slot/count bookkeeping (expert_dispatch_plan) pass
    ``with_send=False`` and get ``send_ids=None``/``overflow=None`` —
    the send-buffer scatter and the overflow reduction would otherwise
    be built and thrown away every step (such callers count drops from
    ``pos`` directly).
    """
    u = ids.shape[0]
    ids = ids.astype(jnp.int32)
    valid = ids >= 0
    # sentinels sort AFTER every real owner so the grouped prefix is dense
    owner = jnp.where(valid, ids // rps, n_shards)
    order = jnp.argsort(owner)
    so = owner[order]
    rank = jnp.arange(u, dtype=jnp.int32) - jnp.searchsorted(
        so, so, side="left").astype(jnp.int32)
    ok = (so < n_shards) & (rank < cap)
    # the +1 tail slot absorbs every dropped write (OOB-free scatter)
    slot = jnp.where(ok, so.astype(jnp.int32) * cap + rank, n_shards * cap)
    send = None
    if with_send:
        send = jnp.full((n_shards * cap + 1,), -1, jnp.int32).at[slot].set(
            ids[order])[:-1]
    pos = jnp.full((u,), -1, jnp.int32).at[order].set(
        jnp.where(ok, slot, -1).astype(jnp.int32))
    counts = jax.ops.segment_sum(valid.astype(jnp.int32),
                                 jnp.clip(owner, 0, n_shards - 1),
                                 num_segments=n_shards)
    overflow = (jnp.max(counts) > cap) if with_send else None
    return PackPlan(send, pos, counts, overflow)


def _scatter_to_slots(values, pos, n_slots):
    """Place per-request rows at their send-buffer slots (pos -1 dropped)."""
    width = values.shape[1:]
    buf = jnp.zeros((n_slots + 1,) + width, values.dtype)
    slot = jnp.where(pos >= 0, pos, n_slots)
    return buf.at[slot].set(values)[:-1]


def _local_rows(req, rps: int, axis: str):
    """Received request ids -> local row indices (scratch for sentinels)."""
    me = lax.axis_index(axis)
    return jnp.where(req >= 0, req - me * rps, rps)


# ---------------------------------------------------------------------------
# shard_map bodies
# ---------------------------------------------------------------------------

def _gather_body(ids_loc, *arrs_loc, axis, n, rps, cap):
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)                  # [n, cap] asks for MY rows
    local = _local_rows(req, rps, axis)
    outs = []
    for a in arrs_loc:                                # each [rps+1, D]
        rows = a[local]                               # [n, cap, D]
        back = lax.all_to_all(rows, axis, 0, 0, tiled=True)
        flat = back.reshape((n * cap,) + back.shape[2:])
        got = flat[jnp.clip(plan.pos, 0, n * cap - 1)]
        outs.append(jnp.where((plan.pos >= 0).reshape(
            (-1,) + (1,) * (got.ndim - 1)), got, 0))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf,) + tuple(outs)


def _set_body(ids_loc, rows_and_tables, axis, n, rps, cap, n_arrays):
    rows_loc = rows_and_tables[:n_arrays]
    arrs_loc = rows_and_tables[n_arrays:]
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)
    local = _local_rows(req, rps, axis)
    outs = []
    for a, r in zip(arrs_loc, rows_loc):
        buf = _scatter_to_slots(r, plan.pos, n * cap)
        recv = lax.all_to_all(buf.reshape((n, cap) + buf.shape[1:]),
                              axis, 0, 0, tiled=True)
        outs.append(a.at[local].set(recv))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf,) + tuple(outs)


def _apply_body(ids_loc, grads_loc, table_loc, *state_loc, axis, n, rps,
                cap, opt, hyper, state_names):
    plan = pack_by_owner(ids_loc, n_shards=n, rps=rps, cap=cap)
    req = lax.all_to_all(plan.send_ids.reshape(n, cap), axis, 0, 0,
                         tiled=True)
    local = _local_rows(req, rps, axis)
    gbuf = _scatter_to_slots(grads_loc, plan.pos, n * cap)
    grecv = lax.all_to_all(gbuf.reshape((n, cap) + gbuf.shape[1:]),
                           axis, 0, 0, tiled=True)
    flat_local = local.reshape(-1)
    rows = table_loc[flat_local]
    st = {k: s[flat_local] for k, s in zip(state_names, state_loc)}
    from ..distributed.ps.device_cache import apply_rule_device
    new_rows, new_st = apply_rule_device(
        opt, rows, st, grecv.reshape((n * cap,) + grecv.shape[2:]), **hyper)
    # scratch entries carry zero grads: the rule is a no-op there, and
    # duplicate scratch writes all land the same (irrelevant) value
    new_table = table_loc.at[flat_local].set(new_rows)
    new_state = tuple(state_loc[i].at[flat_local].set(new_st[k])
                      for i, k in enumerate(state_names))
    ovf = lax.pmax(plan.overflow.astype(jnp.int32), axis)
    return (ovf, new_table) + new_state


# ---------------------------------------------------------------------------
# public wrappers
# ---------------------------------------------------------------------------

def _route_params(mesh, axis: str, n_ids: int, cap: Optional[int]):
    n = int(dict(mesh.shape)[axis])
    if n_ids % n:
        raise ValueError(
            f"routing over axis {axis!r} (size {n}) needs the request "
            f"vector length ({n_ids}) divisible by the axis size — pad "
            f"with sentinel -1 (ops.routing.pad_requests)")
    u = n_ids // n
    cap = u if not cap else min(int(cap), u)
    return n, cap


def all_to_all_gather(arrays: Sequence, ids, *, mesh, axis: str, rps: int,
                      cap: Optional[int] = None):
    """Routed multi-array row lookup.

    ``arrays``: sharded ``[(rps+1)*n, D_i]`` storage arrays (rows +
    optimizer-state planes travel in ONE routed exchange of ids).
    ``ids``: ``[U]`` logical ids (sentinel -1), ``U % n == 0``.
    Returns ``(rows_list, overflow)`` — each ``[U, D_i]`` aligned with
    ``ids`` (zeros at sentinel slots), overflow an int32 scalar (>0 when
    some shard's per-owner count exceeded ``cap``).
    """
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)
    body = functools.partial(_gather_body, axis=axis, n=n, rps=rps, cap=cap)
    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis),) + (P(axis, None),) * len(arrays),
        out_specs=(P(),) + (P(axis, None),) * len(arrays),
        check_rep=False)
    out = fn(ids, *arrays)
    return list(out[1:]), out[0]


def all_to_all_set(arrays: Sequence, ids, rows: Sequence, *, mesh,
                   axis: str, rps: int, cap: Optional[int] = None):
    """Routed row import: write ``rows[i]`` (``[U, D_i]``, aligned with
    ``ids``) into each storage array at the owner shards.  Sentinel ids
    land on the owner's scratch row.  Returns ``(new_arrays, overflow)``.
    """
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)

    def wrapped(ids_loc, *packed):
        return _set_body(ids_loc, packed, axis=axis, n=n, rps=rps, cap=cap,
                         n_arrays=len(arrays))

    fn = _shard_map(
        wrapped, mesh=mesh,
        in_specs=(P(axis),) + (P(axis, None),) * len(arrays)
        + (P(axis, None),) * len(arrays),
        out_specs=(P(),) + (P(axis, None),) * len(arrays),
        check_rep=False)
    out = fn(ids, *rows, *arrays)
    return list(out[1:]), out[0]


def all_to_all_apply_rule(table, state: dict, ids, grads, *, opt: str,
                          hyper: dict, mesh, axis: str, rps: int,
                          cap: Optional[int] = None):
    """Routed sparse-optimizer update: route ``(id, grad)`` pairs to the
    owner shards, apply the on-chip rule (``device_cache.DEVICE_RULES``)
    to the local rows + state, scatter in place.  The backward leg of the
    all-to-all lookup: updates touch ONLY the owning shard's slice.
    Returns ``(new_table, new_state, overflow)``."""
    n, cap = _route_params(mesh, axis, ids.shape[0], cap)
    names = tuple(sorted(state))
    body = functools.partial(_apply_body, axis=axis, n=n, rps=rps, cap=cap,
                             opt=opt, hyper=dict(hyper), state_names=names)
    fn = _shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis, None)) + (P(axis, None),) * (1 + len(names)),
        out_specs=(P(),) + (P(axis, None),) * (1 + len(names)),
        check_rep=False)
    out = fn(ids, grads, table, *[state[k] for k in names])
    new_state = {k: out[2 + i] for i, k in enumerate(names)}
    return out[1], new_state, out[0]


# ---------------------------------------------------------------------------
# expert-parallel token routing (Mixture-of-Experts, ISSUE 14)
#
# The embedding movers above route *ids* to the shard that OWNS a table
# row; MoE routes *token vectors* to the shard that owns an expert,
# computes there, and routes the results back — the same static-cap
# owner bucketing with owner = expert, ``rps = 1`` (each "row" of the
# virtual table is one expert), and TWO all_to_alls per layer: tokens
# expert-ward, results token-ward.  Buffers are ``[E, cap]`` slots per
# source shard, so wire bytes scale with capacity, never with vocab or
# d_model beyond the row width.
# ---------------------------------------------------------------------------


def moe_capacity(tokens_per_group: int, top_k: int, n_experts: int,
                 capacity_factor: float) -> int:
    """Static per-(source shard, expert) slot count: each of the ``G``
    token groups (one per shard of the routing axis) may park at most
    ``cap`` of its ``tokens * k`` assignments on any one expert; the
    rest drop (residual passthrough).  ``capacity_factor`` 1.0 is the
    exactly-balanced budget; 1.25 is the usual head-room."""
    t = int(tokens_per_group) * int(top_k)
    return max(1, -(-int(t * float(capacity_factor)) // int(n_experts)))


class ExpertPlan(NamedTuple):
    """Per-group static dispatch plan (pure function of the expert ids,
    shared verbatim by the routed mover and the dense-dispatch
    control so both drop the same assignments)."""

    pos: jnp.ndarray      # [G, S] slot in the per-group [E*cap] buffer
    counts: jnp.ndarray   # [G, E] pre-drop per-expert demand
    dropped: jnp.ndarray  # [G] int32 assignments past capacity (dropped)


def expert_dispatch_plan(expert_ids, *, n_experts: int,
                         cap: int) -> ExpertPlan:
    """Owner-bucket each group's assignment slice ``expert_ids [G, S]``
    (entries in ``[0, E)``; sentinel ``< 0`` never consumes cap) into
    per-group ``[E * cap]`` send buffers — :func:`pack_by_owner` with
    owner = expert (``rps = 1``), vmapped over the group axis."""
    eids = jnp.asarray(expert_ids, jnp.int32)
    plan = jax.vmap(functools.partial(
        pack_by_owner, n_shards=int(n_experts), rps=1, cap=int(cap),
        with_send=False))(eids)
    kept = jnp.sum((plan.pos >= 0).astype(jnp.int32), axis=1)
    valid = jnp.sum((eids >= 0).astype(jnp.int32), axis=1)
    return ExpertPlan(plan.pos, plan.counts, valid - kept)


def _expert_body(x_loc, pos_loc, *w_loc, axis, n, n_experts, cap, expert_fn):
    """Per-shard leg of the routed expert exchange: scatter my ``S``
    token rows into the ``[E, cap]`` dispatch buffer, all_to_all so each
    shard receives every group's slots for ITS experts, run the local
    expert stack, all_to_all the results back, gather rows to token
    order (dropped slots read zero)."""
    E, eps = n_experts, n_experts // n
    tail = x_loc.shape[1:]                                # feature dims (D,)
    perm = (1, 0, 2) + tuple(range(3, 3 + len(tail)))
    pos = pos_loc.reshape(-1)
    buf = _scatter_to_slots(x_loc, pos, E * cap)          # [E*cap, D]
    buf = buf.reshape((n, eps * cap) + tail)
    recv = lax.all_to_all(buf, axis, 0, 0, tiled=True)    # [n, eps*cap, D]
    rows = recv.reshape((n, eps, cap) + tail).transpose(perm)
    rows = rows.reshape((eps, n * cap) + tail)            # [eps, n*cap, D]
    out = expert_fn(rows, *w_loc)                         # [eps, n*cap, D]
    out = out.reshape((eps, n, cap) + tail).transpose(perm)
    out = out.reshape((n, eps * cap) + tail)
    back = lax.all_to_all(out, axis, 0, 0, tiled=True)
    flat = back.reshape((E * cap,) + tail)
    got = flat[jnp.clip(pos, 0, E * cap - 1)]
    return jnp.where((pos >= 0).reshape((-1,) + (1,) * (got.ndim - 1)),
                     got, 0)


def all_to_all_experts(x_dup, pos, expert_params: Sequence, expert_fn, *,
                       mesh, axis: str, n_experts: int, cap: int):
    """Routed expert application: move token rows to the shard owning
    their expert, apply the expert stack there, move results back.

    ``x_dup``: ``[G*S, D]`` token rows (one row per (token, top-k slot)
    assignment; ``G`` = routing-axis size, each shard owns a contiguous
    ``S`` slice).  ``pos``: ``[G, S]`` dispatch plan from
    :func:`expert_dispatch_plan`.  ``expert_params``: stacked
    ``[E, ...]`` arrays sharded ``P(axis, ...)`` — each shard holds its
    ``E / n`` experts.  ``expert_fn(rows [e, m, D], *params_local)``
    must be expert-row-independent (a stacked FFN).  Returns
    ``[G*S, D]`` result rows aligned with ``x_dup`` (zeros at dropped
    slots).  Exactly TWO all_to_alls.
    """
    n = int(dict(mesh.shape)[axis])
    if n_experts % n:
        raise ValueError(
            f"expert routing over axis {axis!r} (size {n}) needs the "
            f"expert count ({n_experts}) divisible by the axis size")
    body = functools.partial(_expert_body, axis=axis, n=n,
                             n_experts=int(n_experts), cap=int(cap),
                             expert_fn=expert_fn)
    specs = tuple(P(axis, *([None] * (w.ndim - 1))) for w in expert_params)
    fn = _shard_map(body, mesh=mesh,
                    in_specs=(P(axis), P(axis, None)) + specs,
                    out_specs=P(axis), check_rep=False)
    return fn(x_dup, pos, *expert_params)


def local_experts(x_dup, pos, expert_params: Sequence, expert_fn, *,
                  n_experts: int, cap: int):
    """Meshless (single-shard) expert application — the same scatter →
    stacked-expert compute → gather as :func:`all_to_all_experts` with
    the two all_to_alls elided (``G = n = 1``); the decode/serving path
    when no expert axis is live."""
    E = int(n_experts)
    p = jnp.asarray(pos).reshape(-1)
    buf = _scatter_to_slots(x_dup, p, E * cap)
    rows = buf.reshape((E, cap) + buf.shape[1:])
    out = expert_fn(rows, *expert_params)
    flat = out.reshape((E * cap,) + out.shape[2:])
    got = flat[jnp.clip(p, 0, E * cap - 1)]
    return jnp.where((p >= 0).reshape((-1,) + (1,) * (got.ndim - 1)),
                     got, 0)


def moe_a2a_wire_bytes(n_experts: int, cap: int, dim: int, n_shards: int,
                       itemsize: int = 4) -> int:
    """Ring-model per-device interconnect bytes of one MoE layer's two
    all_to_alls (tokens out + results back): each leg moves the
    ``[E, cap, D]`` dispatch buffer, of which ``(n-1)/n`` crosses the
    wire.  Wire bytes scale with capacity (∝ tokens routed), never with
    vocab."""
    n = int(n_shards)
    if n <= 1:
        return 0
    leg = int(n_experts) * int(cap) * int(dim) * int(itemsize)
    return int(2 * leg * (n - 1) / n)


def a2a_wire_bytes(n_requests: int, dim: int, n_shards: int, cap: int,
                   itemsize: int = 4, n_planes: int = 1) -> int:
    """Ring-model per-device interconnect bytes of one routed gather:
    ids out + ids' worth of row planes back (and the same shape again for
    a set/update leg).  ``(n-1)/n`` of an all-to-all buffer actually
    crosses the wire."""
    n = int(n_shards)
    if n <= 1:
        return 0
    buf_ids = n * cap * 4
    buf_rows = n * cap * dim * itemsize * n_planes
    return int((buf_ids + buf_rows) * (n - 1) / n)
