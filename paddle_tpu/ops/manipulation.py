"""Shape/layout manipulation ops.

Reference parity: reshape_op.cc, transpose_op.cc, concat_op.cc, split_op.cc,
stack_op.cc, squeeze/unsqueeze, expand_v2, tile, slice_op.cc, gather/scatter,
where_op, cast_op, pad3d, flip, roll, index_select and
python/paddle/tensor/manipulation.py. All static shape parameters travel as
jit-static attrs so XLA sees fixed shapes (TPU requirement); tensor-valued
indices travel as array args.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import convert_dtype, index_dtype as _idt
from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(x) for x in v.tolist())
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    return tuple(int(unwrap(x)) if not isinstance(x, (int, np.integer)) else int(x)
                 for x in v)


_cast_prims = {}


def cast(x, dtype):
    dt = convert_dtype(dtype)
    key = str(dt)
    if key not in _cast_prims:
        _cast_prims[key] = Primitive(f"cast[{key}]", lambda v, _dt=dt: v.astype(_dt))
    return _cast_prims[key](x)


_reshape = Primitive("reshape2", lambda x, shape=(): jnp.reshape(x, shape))


def reshape(x, shape, name=None):
    shape = _ints(shape)
    return _reshape(x, shape=shape)


_transpose = Primitive("transpose2", lambda x, perm=(): jnp.transpose(x, perm))


def transpose(x, perm, name=None):
    return _transpose(x, perm=_ints(perm))


def _concat_fn(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


_concat = Primitive("concat", _concat_fn)


def concat(x, axis=0, name=None):
    from ..framework.tensor_array import BoundedTensorArray
    if isinstance(x, BoundedTensorArray):
        if int(unwrap(axis)) != 0:
            raise ValueError("concat over a BoundedTensorArray supports "
                             "axis=0 only")
        from ..framework.tensor import Tensor
        return Tensor(x.concat())
    axis = int(unwrap(axis))
    return _concat(*x, axis=axis)


def _split_fn(x, num_or_indices=(), axis=0):
    kind, val = num_or_indices
    if kind == "num":
        return tuple(jnp.split(x, val, axis=axis))
    return tuple(jnp.split(x, list(np.cumsum(val))[:-1], axis=axis))


_split = Primitive("split", _split_fn, multi_output=True)


def split(x, num_or_sections, axis=0, name=None):
    axis = int(unwrap(axis))
    if isinstance(num_or_sections, int):
        spec = ("num", num_or_sections)
    else:
        secs = list(_ints(num_or_sections))
        dim = (x.shape if isinstance(x, Tensor) else list(jnp.shape(unwrap(x))))[axis]
        n_unknown = sum(1 for s in secs if s < 0)
        if n_unknown:
            known = int(np.sum([s for s in secs if s >= 0]))
            secs = [s if s >= 0 else dim - known for s in secs]
        spec = ("secs", tuple(secs))
    return list(_split(x, num_or_indices=spec, axis=axis))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def _stack_fn(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


_stack = Primitive("stack", _stack_fn)


def stack(x, axis=0, name=None):
    from ..framework.tensor_array import BoundedTensorArray
    if isinstance(x, BoundedTensorArray):
        # dy2static list lowering: the buffer IS the stacked array
        # ([capacity, ...]; valid prefix = [:x.length()])
        if int(axis) != 0:
            raise ValueError("stack over a BoundedTensorArray supports "
                             "axis=0 only")
        from ..framework.tensor import Tensor
        return Tensor(x.stack())
    return _stack(*x, axis=int(axis))


def _unstack_fn(x, axis=0, num=0):
    return tuple(jnp.squeeze(s, axis=axis)
                 for s in jnp.split(x, num, axis=axis))


_unstack = Primitive("unstack", _unstack_fn, multi_output=True)


def unstack(x, axis=0, num=None, name=None):
    n = num or x.shape[axis]
    return list(_unstack(x, axis=int(axis), num=int(n)))


def unbind(x, axis=0, name=None):
    return unstack(x, axis)


_squeeze = Primitive("squeeze2", lambda x, axes=None: jnp.squeeze(x, axis=axes))


def squeeze(x, axis=None, name=None):
    if axis is None:
        return _squeeze(x, axes=None)
    axes = _ints(axis)
    shape = x.shape if isinstance(x, Tensor) else list(jnp.shape(unwrap(x)))
    axes = tuple(a for a in axes if shape[a] == 1)
    return _squeeze(x, axes=axes)


_unsqueeze = Primitive("unsqueeze2", lambda x, axes=(): jnp.expand_dims(x, axes))


def unsqueeze(x, axis, name=None):
    return _unsqueeze(x, axes=_ints(axis))


def _flatten_fn(x, start=0, stop=-1):
    shape = x.shape
    nd = len(shape)
    stop = stop % nd
    new = shape[:start] + (int(np.prod(shape[start:stop + 1]) or 1),) + shape[stop + 1:]
    return jnp.reshape(x, new)


_flatten = Primitive("flatten_contiguous_range", _flatten_fn)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start=int(start_axis), stop=int(stop_axis))


_expand = Primitive("expand_v2", lambda x, shape=(): jnp.broadcast_to(x, shape))


def expand(x, shape, name=None):
    shape = list(_ints(shape))
    xshape = x.shape if isinstance(x, Tensor) else list(jnp.shape(unwrap(x)))
    # paddle semantics: -1 means keep dim
    offset = len(shape) - len(xshape)
    for i, s in enumerate(shape):
        if s == -1 and i >= offset:
            shape[i] = xshape[i - offset]
    return _expand(x, shape=tuple(shape))


broadcast_to = expand


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_tensors(inputs, name=None):
    arrs = [unwrap(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [expand(t, shape) for t in inputs]


_tile = Primitive("tile", lambda x, reps=(): jnp.tile(x, reps))


def tile(x, repeat_times, name=None):
    return _tile(x, reps=_ints(repeat_times))


def repeat_interleave(x, repeats, axis=None, name=None):
    return Tensor(jnp.repeat(unwrap(x), unwrap(repeats), axis=axis))


_builtin_slice = slice    # the ``slice`` op below shadows the builtin


def _slice_fn(x, spec=()):
    idx = tuple(_builtin_slice(*s) if isinstance(s, tuple) else s
                for s in spec)
    return x[idx]


_slice = Primitive("slice", _slice_fn)


def slice(x, axes, starts, ends, name=None):
    axes, starts, ends = _ints(axes), _ints(starts), _ints(ends)
    nd = x.ndim if isinstance(x, Tensor) else jnp.ndim(unwrap(x))
    spec = [(None, None, None)] * nd
    for a, s, e in zip(axes, starts, ends):
        spec[a] = (s, e, None)
    return _slice(x, spec=tuple(spec))


_dynslice_p = Primitive(
    "slice_dynamic",
    lambda x, start, size=1, axis=0:
    jax.lax.dynamic_slice_in_dim(x, start, size, axis))


def dynamic_slice(x, start, size, axis=0, name=None):
    """Fixed-``size`` window at a runtime (possibly traced) ``start`` —
    slice_op.cc's StartsTensor leg: the reference takes starts as a
    tensor at run time while the extent stays static.  Lowers to
    lax.dynamic_slice, so the start clamps to [0, dim-size] (the
    reference's slice clamps the same way) and the VJP is a
    dynamic_update_slice, not a scatter.  The dy2static getitem converter
    routes traced-bound ``x[i:i+k]`` here."""
    return _dynslice_p(x, start, size=int(size), axis=int(axis))


_dynupdate_p = Primitive(
    "set_slice_dynamic",
    lambda x, v, start, axis=0:
    jax.lax.dynamic_update_slice_in_dim(x, v.astype(x.dtype), start, axis))


def dynamic_update_slice(x, value, start, axis=0, name=None):
    """Functional ``x[start:start+len(value)] = value`` with a runtime
    start (set_value_op StartsTensorList parity); dual of
    ``dynamic_slice``."""
    return _dynupdate_p(x, value, start, axis=int(axis))


def strided_slice(x, axes, starts, ends, strides, name=None):
    axes, starts, ends, strides = map(_ints, (axes, starts, ends, strides))
    nd = x.ndim if isinstance(x, Tensor) else jnp.ndim(unwrap(x))
    spec = [(None, None, None)] * nd
    for a, s, e, st in zip(axes, starts, ends, strides):
        spec[a] = (s, e, st)
    return _slice(x, spec=tuple(spec))


def crop(x, shape, offsets, name=None):
    shape, offsets = _ints(shape), _ints(offsets)
    return _slice(x, spec=tuple((o, o + s, None) for o, s in zip(offsets, shape)))


_gather = Primitive("gather", lambda x, idx, axis=0: jnp.take(x, idx, axis=axis))


def gather(x, index, axis=0, name=None):
    return _gather(x, index, axis=int(unwrap(axis)))


_gather_nd = Primitive("gather_nd", lambda x, idx: x[tuple(jnp.moveaxis(idx, -1, 0))])


def gather_nd(x, index, name=None):
    return _gather_nd(x, index)


_take_along = Primitive("take_along_axis",
                        lambda x, idx, axis=0: jnp.take_along_axis(x, idx, axis=axis))


def take_along_axis(x, indices, axis, name=None):
    return _take_along(x, indices, axis=int(axis))


def _scatter_fn(x, idx, updates, overwrite=True):
    if overwrite:
        return x.at[idx].set(updates)
    return x.at[idx].add(updates)


_scatter = Primitive("scatter", _scatter_fn)


def scatter(x, index, updates, overwrite=True, name=None):
    return _scatter(x, index, updates, overwrite=bool(overwrite))


def _scatter_nd_add_fn(x, idx, updates):
    return x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(updates)


_scatter_nd_add = Primitive("scatter_nd_add", _scatter_nd_add_fn)


def scatter_nd_add(x, index, updates, name=None):
    return _scatter_nd_add(x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=updates.dtype if isinstance(updates, Tensor) else None)
    return _scatter_nd_add(z, index, updates)


_put_along = Primitive("put_along_axis", lambda x, idx, v, axis=0, reduce="assign":
                       jnp.put_along_axis(x, idx, v, axis=axis, inplace=False)
                       if reduce == "assign"
                       else x.at[...].set(x))


def put_along_axis(x, indices, values, axis, reduce="assign", name=None):
    return _put_along(x, indices, values, axis=int(axis), reduce=reduce)


_index_select = Primitive("index_select",
                          lambda x, idx, axis=0: jnp.take(x, idx, axis=axis))


def index_select(x, index, axis=0, name=None):
    return _index_select(x, index, axis=int(axis))


def index_sample(x, index):
    return _take_along(x, index, axis=1)


def masked_select(x, mask, name=None):
    # dynamic output shape: eager-only (host round-trip), like Paddle's CPU path
    xv, mv = unwrap(x), unwrap(mask)
    return Tensor(xv[np.asarray(mv)])


_where = Primitive("where", lambda c, x, y: jnp.where(c, x, y))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return _where(condition, x, y)


def nonzero(x, as_tuple=False):
    xv = np.asarray(unwrap(x))
    idx = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i)) for i in idx)
    return Tensor(jnp.asarray(np.stack(idx, axis=1)))


_flip = Primitive("flip", lambda x, axis=(): jnp.flip(x, axis=axis))


def flip(x, axis, name=None):
    return _flip(x, axis=_ints(axis))


_roll = Primitive("roll", lambda x, shifts=(), axis=None: jnp.roll(x, shifts, axis=axis))


def roll(x, shifts, axis=None, name=None):
    return _roll(x, shifts=_ints(shifts) if not isinstance(shifts, int) else (shifts,),
                 axis=_ints(axis) if axis is not None else None)


def _rot90_fn(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


_rot90 = Primitive("rot90", _rot90_fn)


def rot90(x, k=1, axes=(0, 1), name=None):
    return _rot90(x, k=int(k), axes=_ints(axes))


_pad_p = Primitive("pad", lambda x, pads=(), mode="constant", value=0.0:
                   jnp.pad(x, pads, mode=mode, constant_values=value)
                   if mode == "constant" else jnp.pad(x, pads, mode=mode))


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    """functional.pad parity (pad3d_op.cc). ``pad`` is flat [lo,hi] pairs over
    trailing dims (paddle layout) or full ndim*2."""
    pads = _ints(pad)
    nd = x.ndim if isinstance(x, Tensor) else jnp.ndim(unwrap(x))
    jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    if len(pads) == 2 * nd:
        width = [(pads[2 * i], pads[2 * i + 1]) for i in range(nd)]
    else:
        # paddle convention: pads cover the LAST len(pads)//2 spatial dims,
        # innermost-first, e.g. NCHW with pad=[l,r,t,b] -> W then H
        npairs = len(pads) // 2
        width = [(0, 0)] * nd
        for i in range(npairs):
            dim = nd - 1 - i
            width[dim] = (pads[2 * i], pads[2 * i + 1])
    return _pad_p(x, pads=tuple(width), mode=jmode, value=float(value))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    x = unwrap(input)
    per = index_num // nshards
    lo, hi = shard_id * per, (shard_id + 1) * per
    ok = (x >= lo) & (x < hi)
    return Tensor(jnp.where(ok, x - lo, ignore_value))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    xv = np.asarray(unwrap(x))
    out = np.unique(xv, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(out, tuple):
        return tuple(Tensor(jnp.asarray(o)) for o in out)
    return Tensor(jnp.asarray(out))


_as_real = Primitive("as_real", lambda x: jnp.stack([jnp.real(x), jnp.imag(x)], -1))


def moveaxis(x, source, destination, name=None):
    return Tensor(jnp.moveaxis(unwrap(x), source, destination))


def swapaxes(x, axis1, axis2, name=None):
    nd = x.ndim
    perm = list(range(nd))
    perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
    return transpose(x, perm)


def as_complex(x, name=None):
    xv = unwrap(x)
    return Tensor(jax.lax.complex(xv[..., 0], xv[..., 1]))


def as_real(x, name=None):
    return _as_real(x)


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(x.shape)) if x.shape else 1,
                              dtype=_idt()))


def shape(x):
    return Tensor(jnp.asarray(x.shape, dtype=jnp.int32))


def one_hot(x, num_classes, name=None):
    p = _one_hot
    return p(x, num_classes=int(num_classes))


_one_hot = Primitive("one_hot_v2", lambda x, num_classes=0:
                     jax.nn.one_hot(x, num_classes), differentiable=False)
