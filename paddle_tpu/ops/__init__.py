"""Op library: the TPU-native operator surface.

Reference parity: the union of paddle/fluid/operators registrations surfaced
through python/paddle/tensor/*. Importing this package patches Tensor methods
(math_op_patch.py parity).
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .linalg import (  # noqa: F401
    norm, cholesky, inverse, det, slogdet, matrix_power, svd, eig, eigh,
    eigvals, eigvalsh, qr, lstsq, solve, triangular_solve, matrix_rank, pinv,
    cond, multi_dot, cross, bincount,
)
# NB: control_flow.cond is deliberately NOT star-exported — the public
# ``cond`` stays linalg's matrix condition number (reference has no top-level
# paddle.cond; control-flow cond lives at static.nn.cond / ops.control_flow.cond)
from .control_flow import (  # noqa: F401
    while_loop, case, switch_case,
    create_array, array_write, array_read, array_length,
)
from .math_ext import *  # noqa: F401,F403
from .sequence import *  # noqa: F401,F403
from .detection import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403
from .decode import (  # noqa: F401
    gather_tree, beam_search_step, beam_search_decode, beam_search,
    linear_chain_crf, crf_decoding, viterbi_decode, edit_distance,
)
from .linalg import cov, corrcoef  # noqa: F401
from .industrial import (  # noqa: F401
    batch_fc, fsp_matrix, shuffle_batch, hash_bucket, spp,
    positive_negative_pair, tdm_child, tdm_sampler, nce_loss,
    attention_lstm, filter_by_instag, match_matrix_tensor,
    sequence_topk_avg_pooling, var_conv_2d,
)
from .int8 import (  # noqa: F401
    linear_int8, conv2d_int8, matmul_int8,
)
from . import routing  # noqa: F401  (mesh all-to-all row routing, ISSUE 10)
from .longtail import (  # noqa: F401
    rank_attention, pyramid_hash, tree_conv, correlation, prroi_pool,
    similarity_focus, deformable_psroi_pooling, roi_perspective_transform,
    bilateral_slice, multi_gru,
)
from . import (  # noqa: F401
    creation, math, manipulation, linalg, control_flow, math_ext, sequence,
    detection, vision, decode, int8,
)
from .patch import apply_patches as _apply_patches

_apply_patches()
