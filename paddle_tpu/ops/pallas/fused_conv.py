"""Fused conv+BN(+ReLU) Pallas pipeline for the bandwidth-bound high-res
ResNet stages, plus the space-to-depth stem reorg.

Reference parity: the hand-fused conv kernels of
paddle/fluid/operators/conv_cudnn_op.cu + operators/fused/
(conv_fusion_op.cc, fused_batch_norm_act) — the reference's answer to the
same problem this module attacks (VERDICT r5 #1: ResNet-50 at 30% MFU,
stages 1–2 at ~72 ms against a 24–32 ms memory floor).

Why a FULL conv+BN+ReLU chain and not a BN epilogue: the round-4 BN-only
Pallas kernel measured 974 vs 1,971 img/s end-to-end — an opaque custom
call between XLA's conv and its epilogue breaks XLA's own conv fusion, so
the fix must own the whole chain.  Structure (streaming-tile discipline of
ops/pallas/flash_attention.py):

- ``_conv_stats``: ONE kernel computes the conv (sum of shifted matmuls on
  the MXU, f32 accumulators) AND the per-channel sum/sumsq of its output —
  the conv activation is written once and never re-read for the stats
  pass (XLA materializes the conv output, re-reads it for stats, and
  re-reads+writes for normalize: PERF.md round-3 "+4.5 ms on a 411 MB
  activation").
- apply: the normalize+affine+ReLU pass reuses fused_bn's `_apply` kernel
  (one read + one write of the activation).
- backward: dγ/dβ and the BN part of dX run through fused_bn's shared
  reduce/coefficient kernels on the saved conv output (one streaming pass
  each); the conv's own dX/dW transposes go through lax.conv (XLA's conv
  backward is compute-bound and healthy — 55/64 TFLOP/s measured r3 — the
  bandwidth win is the epilogue, not the conv transpose).

Space-to-depth stem: the 7×7/s2 C_in=3 stem uses ~2% of the MXU's input
lanes (19.2 ms measured, r3).  ``stem_s2d_*`` reorganizes the padded input
[N,230,230,3] → [N,115,115,12] and folds the 7×7/s2 weights into an
equivalent 4×4/s1 kernel over 12 channels — and unlike the rejected r3
s2d-at-XLA attempt (fwd 12.3 ms vs 8.4 plain: XLA's own im2col undid the
lane win), the reorged conv feeds THIS kernel directly.

Gating (the flash/fused_bn honesty rule): ships OFF by default —
``FLAGS_use_pallas_fused_conv`` / ``PADDLE_TPU_PALLAS_CONV=1`` opts in.
The default flips only with an end-to-end ResNet-50 win recorded on the
bench chip in PERF.md (this container has no chip; PERF.md round-6 records
the pending-measurement state).
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import fused_bn


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def enabled() -> bool:
    """Honest gate (see module docstring): opt-in via the flags registry
    (paddle.set_flags({"FLAGS_use_pallas_fused_conv": True}) or the env
    seed) or the PADDLE_TPU_PALLAS_CONV=1 env var."""
    from ...framework.flags import flag
    return bool(flag("use_pallas_fused_conv")) or \
        os.environ.get("PADDLE_TPU_PALLAS_CONV", "0") == "1"


# VMEM working-set cap for one grid step (per-image block + f32 accumulator
# + weights, double-buffered by the pipeline); ~16 MB/core on v5e
_VMEM_CAP_BYTES = 12 * 1024 * 1024


def _out_hw(h, w, kh, kw, stride, padding):
    ho = (h + 2 * padding - kh) // stride + 1
    wo = (w + 2 * padding - kw) // stride + 1
    return ho, wo


def supports(x_shape, w_shape, stride=1, padding=0, dilation=1, groups=1,
             channel_last=True) -> bool:
    """Static eligibility of the fused kernel for a conv+BN(+ReLU) site.

    NHWC, groups=1, dilation=1, stride 1 or 2, symmetric int padding,
    kernels ≤5 (the 7×7 stem goes through the s2d reorg instead — at
    C_in=3 a direct 49-tap kernel wastes the very lanes s2d reclaims),
    single device (pallas_call has no GSPMD partition rule), and the
    per-image working set must fit VMEM."""
    def _pair(v):
        return (v, v) if isinstance(v, int) else tuple(v)
    if not channel_last or groups != 1 or len(x_shape) != 4:
        return False
    if _pair(dilation) != (1, 1):
        return False
    s = _pair(stride)
    if s[0] != s[1] or s[0] not in (1, 2):
        return False
    if not isinstance(padding, int):
        if isinstance(padding, (tuple, list)) and len(padding) == 2 and \
                all(isinstance(p, int) for p in padding) and \
                padding[0] == padding[1]:
            padding = padding[0]
        else:
            return False
    n, h, w, cin = x_shape
    cout, cin_w, kh, kw = w_shape
    if cin_w != cin or kh > 5 or kw > 5:
        return False
    ho, wo = _out_hw(h, w, kh, kw, s[0], padding)
    if ho <= 0 or wo <= 0:
        return False
    if (n * ho * wo) % 8 != 0:
        return False         # apply/backward tiles ladder in units of 8
    if jax.device_count() > 1 and not _interpret():
        # compiled pallas_call has no GSPMD partition rule; interpret mode
        # lowers to plain jax ops and partitions like any jnp code, so the
        # CPU test mesh keeps exercising the fused path
        return False
    # per-image VMEM working set: padded input + f32 accumulator + stored
    # output + weights (f32 upper bound)
    hp = h + 2 * padding + (s[0] - 1)
    wp = w + 2 * padding + (s[0] - 1)
    vmem = 4 * (hp * wp * cin + 2 * ho * wo * cout + kh * kw * cin * cout)
    return vmem <= _VMEM_CAP_BYTES


# -- forward: conv with fused output statistics -------------------------------

def _conv_stats_kernel(x_ref, w_ref, y_ref, sum_ref, sq_ref, *, stride, kh,
                       kw, ho, wo):
    """One image per grid step: conv as the sum of kh·kw shifted matmuls
    (each tap is a [Ho·Wo, Cin] × [Cin, Cout] MXU contraction, f32
    accumulate), output written once, per-channel Σy/Σy² accumulated from
    the f32 accumulator before the store — the stats pass costs zero extra
    HBM traffic."""
    i = pl.program_id(0)
    x = x_ref[0]                                   # [Hp, Wp, Cin]
    cin = x.shape[-1]
    cout = y_ref.shape[-1]
    acc = jnp.zeros((ho * wo, cout), jnp.float32)
    for u in range(kh):
        for v in range(kw):
            if stride == 1:
                win = x[u:u + ho, v:v + wo, :]
            else:
                # strided window without a strided slice (Mosaic-safe):
                # take the dense [2·Ho, 2·Wo] slab, fold the stride into a
                # reshape and keep phase 0 (the caller padded one extra
                # row/col so the slab stays in bounds for every tap)
                slab = x[u:u + stride * ho, v:v + stride * wo, :]
                slab = slab.reshape(ho, stride, wo, stride, cin)
                win = slab[:, 0, :, 0, :]
            acc += jnp.dot(win.reshape(ho * wo, cin), w_ref[u, v],
                           preferred_element_type=jnp.float32)

    @pl.when(i == 0)
    def _():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    sum_ref[...] += jnp.sum(acc, axis=0)
    sq_ref[...] += jnp.sum(acc * acc, axis=0)
    y_ref[0] = acc.reshape(ho, wo, cout).astype(y_ref.dtype)


def _conv_stats(x, w, stride, padding):
    """Fused conv + output moments.  Returns (y_conv [N,Ho,Wo,Cout],
    mean, var, xp) — xp is the padded input saved for the backward."""
    n, h, w_, cin = x.shape
    cout, _, kh, kw = w.shape
    ho, wo = _out_hw(h, w_, kh, kw, stride, padding)
    extra = stride - 1        # high-side slack for the fold-stride slab
    xp = jnp.pad(x, ((0, 0), (padding, padding + extra),
                     (padding, padding + extra), (0, 0)))
    hp, wp = xp.shape[1], xp.shape[2]
    wk = jnp.transpose(w, (2, 3, 1, 0))            # [kh, kw, Cin, Cout]
    y, s, q = pl.pallas_call(
        functools.partial(_conv_stats_kernel, stride=stride, kh=kh, kw=kw,
                          ho=ho, wo=wo),
        grid=(n,),
        in_specs=[pl.BlockSpec((1, hp, wp, cin), lambda i: (i, 0, 0, 0)),
                  pl.BlockSpec((kh, kw, cin, cout),
                               lambda i: (0, 0, 0, 0))],
        out_specs=[pl.BlockSpec((1, ho, wo, cout), lambda i: (i, 0, 0, 0)),
                   pl.BlockSpec((cout,), lambda i: (0,)),
                   pl.BlockSpec((cout,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((n, ho, wo, cout), x.dtype),
                   jax.ShapeDtypeStruct((cout,), jnp.float32),
                   jax.ShapeDtypeStruct((cout,), jnp.float32)],
        interpret=_interpret(),
    )(xp, wk)
    m = n * ho * wo
    mean = s / m
    var = jnp.maximum(q / m - mean * mean, 0.0)
    return y, mean, var, xp


def _lax_conv(xp, wk, stride):
    """The mathematically-equal XLA conv on the already-padded input —
    differentiated in the backward for dX/dW (compute-bound, healthy)."""
    dn = jax.lax.conv_dimension_numbers(xp.shape, wk.shape,
                                        ("NHWC", "HWIO", "NHWC"))
    return jax.lax.conv_general_dilated(
        xp, wk, window_strides=(stride, stride), padding="VALID",
        dimension_numbers=dn).astype(xp.dtype)


# -- public fused op ----------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def fused_conv_bn_act(x, w, gamma, beta, stride=1, padding=0, eps=1e-5,
                      relu=True):
    """NHWC conv (Paddle OIHW weight, bias-free, groups=1, dilation=1) +
    train-mode BN over N·H·W + optional fused ReLU.  Returns
    (y, mean, var) — the batch_norm_train contract, so the Layer-side
    running-stat update is shared with the XLA path."""
    y, mean, var, *_ = _fwd_impl(x, w, gamma, beta, stride, padding, eps,
                                 relu)
    return y, mean, var


def _fwd_impl(x, w, gamma, beta, stride, padding, eps, relu):
    y_conv, mean, var, xp = _conv_stats(x, w, stride, padding)
    inv = jax.lax.rsqrt(var + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean * scale
    n, ho, wo, cout = y_conv.shape
    m = n * ho * wo
    tm = fused_bn._pick_tile(m, cout)
    if tm == 0:
        raise ValueError(f"fused_conv_bn_act: M={m} has no tile; "
                         f"pad N·Ho·Wo to a multiple of 8")
    out2d = fused_bn._apply(y_conv.reshape(m, cout), scale, shift, tm, relu)
    out = out2d.reshape(y_conv.shape)
    return out, mean, var, xp, y_conv, inv, scale, shift


def _fwd_rule(x, w, gamma, beta, stride, padding, eps, relu):
    out, mean, var, xp, y_conv, inv, scale, shift = _fwd_impl(
        x, w, gamma, beta, stride, padding, eps, relu)
    beta_tag = jnp.zeros((0,), beta.dtype)
    res = (xp, w, gamma, beta_tag, y_conv, mean, inv, scale, shift)
    return (out, mean, var), res


def _bwd_rule(stride, padding, eps, relu, res, cts):
    xp, w, gamma, beta_tag, y_conv, mean, inv, scale, shift = res
    dy, dmean, dvar = cts
    n, ho, wo, cout = y_conv.shape
    m = n * ho * wo
    # BN backward on the saved conv output: one streaming reduce pass
    # (dγ/dβ) + one fused multiply-add pass (coefficient-form dX of BN =
    # the conv's output cotangent), relu gate recomputed from y_conv
    y2d = y_conv.reshape(m, cout)
    dy2d = dy.reshape(m, cout)
    tm = fused_bn._pick_tile(m, cout)
    sum_dyx, dbeta = fused_bn.bn_bwd_reduce(y2d, dy2d, scale, shift, relu,
                                            tm)
    dgamma, a, b, cc = fused_bn.bn_dx_coeffs(gamma, inv, mean, dbeta,
                                             sum_dyx, m, dmean, dvar)
    dyc2d = fused_bn.bn_bwd_dx(y2d, dy2d, scale, shift, a, b, cc, relu, tm)
    dyc = dyc2d.reshape(y_conv.shape)
    # conv transposes through XLA (compute-bound; the bandwidth win above
    # is the epilogue): differentiate the equal lax conv.  The saved xp
    # carries a (stride-1) high-side slack row/col for the kernel's
    # fold-stride slab — the lax conv must see the slack-free pad or its
    # output gains a phantom row
    extra = stride - 1
    xpb = xp if extra == 0 else xp[:, :-extra, :-extra, :]
    wk = jnp.transpose(w, (2, 3, 1, 0))
    _, conv_vjp = jax.vjp(functools.partial(_lax_conv, stride=stride),
                          xpb, wk)
    dxp, dwk = conv_vjp(dyc)
    h = xpb.shape[1] - 2 * padding
    w_ = xpb.shape[2] - 2 * padding
    dx = dxp[:, padding:padding + h, padding:padding + w_, :]
    dw = jnp.transpose(dwk, (3, 2, 0, 1)).astype(w.dtype)
    return (dx, dw, dgamma.astype(gamma.dtype),
            dbeta.astype(beta_tag.dtype))


fused_conv_bn_act.defvjp(_fwd_rule, _bwd_rule)


# -- space-to-depth stem reorg ------------------------------------------------

STEM_BLOCK = 2


def stem_s2d_input(x):
    """[N,H,W,3] → pad-3 → space-to-depth(2) → [N,(H+6)/2,(W+6)/2,12].
    Channel order (dh, dw, c) — must match stem_s2d_weight."""
    n, h, w, c = x.shape
    b = STEM_BLOCK
    xp = jnp.pad(x, ((0, 0), (3, 3), (3, 3), (0, 0)))
    hp, wp = h + 6, w + 6
    x2 = xp.reshape(n, hp // b, b, wp // b, b, c)
    x2 = jnp.transpose(x2, (0, 1, 3, 2, 4, 5))
    return x2.reshape(n, hp // b, wp // b, b * b * c)


def stem_s2d_weight(w):
    """7×7/s2 OIHW weights [O,C,7,7] → the equivalent 4×4/s1 kernel over
    the s2d(2) channel layout, [O, 4·C, 4, 4].  Tap (2k+dh, 2l+dw) of the
    original lands at tap (k, l), channel (dh·2+dw)·C+c; the 8th tap row/
    col that stride-2 never reaches is zero-padded."""
    o, c, kh, kw = w.shape
    b = STEM_BLOCK
    wp = jnp.pad(w, ((0, 0), (0, 0), (0, 1), (0, 1)))        # 8×8 taps
    wr = wp.reshape(o, c, (kh + 1) // b, b, (kw + 1) // b, b)
    w2 = jnp.transpose(wr, (0, 3, 5, 1, 2, 4))   # [o, dh, dw, c, k, l]
    return w2.reshape(o, b * b * c, (kh + 1) // b, (kw + 1) // b)


def stem_supported(x_shape, w_shape) -> bool:
    """The s2d reorg applies to the canonical 7×7/s2/p3 NHWC stem with an
    even input size, and only when the reorged conv itself passes
    ``supports`` — s2d WITHOUT the fused kernel was measured slower at
    the XLA level (r3: fwd 12.3 vs 8.4 ms) and must not re-ship."""
    if len(x_shape) != 4 or len(w_shape) != 4:
        return False
    n, h, w, c = x_shape
    cout, cin, kh, kw = w_shape
    if (kh, kw) != (7, 7) or cin != c or h % 2 != 0 or w % 2 != 0:
        return False
    s2d_x = (n, (h + 6) // 2, (w + 6) // 2, 4 * c)
    s2d_w = (cout, 4 * c, 4, 4)
    return supports(s2d_x, s2d_w, stride=1, padding=0)
