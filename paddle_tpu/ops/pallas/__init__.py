"""Pallas TPU kernels (the fused-op family of the reference,
/root/reference/paddle/fluid/operators/fused/, rebuilt as on-chip kernels).

Exports ``flash_attention`` working on framework Tensors (tape-autograd via
the Primitive machinery; the kernel carries its own custom VJP) and the pure
array-level ``flash_attention_fn`` for compiled train steps.
"""
from __future__ import annotations

from ...framework.primitive import Primitive
from .flash_attention import (DEFAULT_BLOCK, flash_attention_fn, supports)


def _flash_nobias(q, k, v, *, causal=False, scale=None):
    return flash_attention_fn(q, k, v, None, causal=causal, scale=scale)


def _flash_bias(q, k, v, bias, *, causal=False, scale=None):
    return flash_attention_fn(q, k, v, bias, causal=causal, scale=scale)


_flash_prim = Primitive("flash_attention", _flash_nobias)
_flash_bias_prim = Primitive("flash_attention_bias", _flash_bias)


def flash_attention(q, k, v, bias=None, causal=False, scale=None):
    """Flash attention on (B, N, S, H) Tensors; additive ``bias`` optional."""
    if bias is None:
        return _flash_prim(q, k, v, causal=bool(causal), scale=scale)
    return _flash_bias_prim(q, k, v, bias, causal=bool(causal), scale=scale)


from .flash_decode import (  # noqa: E402
    decode_attention_reference, dequantize_kv, flash_decode_fn,
    flash_decode_quant_fn, supports_decode)

_flash_decode_prim = Primitive("flash_decode", flash_decode_fn,
                               differentiable=False)
_flash_decode_quant_prim = Primitive("flash_decode_quant",
                                     flash_decode_quant_fn,
                                     differentiable=False)


def flash_decode(q, k, v, start, end, scale=None):
    """Flash-decoding on Tensors: (B, N, 1, H) query vs (B, N, S, H)
    ring cache, valid window [start, end) per row (inference-only)."""
    return _flash_decode_prim(q, k, v, start, end, scale=scale)


def flash_decode_quant(q, k, v, k_scale, v_scale, start, end, scale=None):
    """Flash-decoding over an int8-quantized ring cache on Tensors: the
    per-(token, head) dequant is fused into the kernel's split-K loop
    (inference-only)."""
    return _flash_decode_quant_prim(q, k, v, k_scale, v_scale, start, end,
                                    scale=scale)


from . import fused_bn, fused_conv  # noqa: F401  (kernel families)

__all__ = ["flash_attention", "flash_attention_fn", "supports",
           "flash_decode", "flash_decode_fn", "supports_decode",
           "flash_decode_quant", "flash_decode_quant_fn", "dequantize_kv",
           "decode_attention_reference",
           "DEFAULT_BLOCK", "fused_bn", "fused_conv"]
