"""Flash-decoding: single-query attention over a long cached context.

The decode step of autoregressive generation issues ONE query row per
sequence against the whole KV cache — the flash-attention kernel's grid
(parallel over query blocks) collapses to a single program and leaves the
chip idle.  Flash-Decoding (Dao et al. 2023) recovers the parallelism by
splitting the CONTEXT axis instead: the cache is cut into K splits, each
split computes a partial softmax-attention (running max ``m``, normalizer
``l``, unnormalized accumulator ``acc``) independently, and a cheap final
merge rescales the partials into the exact softmax result:

    g      = max_s m_s
    out    = sum_s acc_s * exp(m_s - g)  /  sum_s l_s * exp(m_s - g)

The merge is mathematically the same online-softmax recombination the
flash forward kernel runs sequentially — here the splits are *parallel*
grid cells and the merge is a tiny O(splits * H) epilogue.

Validity window: the ring cache is left-padded per row, so row ``b``'s
valid columns are the contiguous ``[start[b], end[b])`` — the kernel
masks outside the window with a finite ``-1e30`` (exp underflows to
exactly 0), and fully-masked splits contribute ``l_s = 0`` so the merge
ignores them.

Layout: q ``(B, N, 1, H)``, cached k/v ``(B, N, S, H)``; internally
``(B*N, 8, H)`` (the query row broadcast over the 8 sublanes of one tile)
vs ``(B*N, S, H)``.  Decode is inference-only: no VJP.

Gated OFF behind ``FLAGS_use_flash_decode`` / ``PADDLE_TPU_FLASH_DECODE``
(no chip this round — PERF.md records the pending-measurement state); the
interpret-mode tests bit-match the XLA masked-attention reference.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _CompilerParams, _interpret, _pick_block

# split-K block: each grid cell streams this many cached keys through VMEM;
# S/bk splits run in parallel (vs the 1-program degenerate flash grid)
DEFAULT_BLOCK_K_DECODE = 512
_NEG_INF = -1e30  # finite mask value: exp(s - m) underflows to exactly 0
_SUBLANES = 8     # the query row is broadcast over one (8, 128) tile's rows


def supports_decode(q_shape, k_shape, block: int = 128) -> bool:
    """Shape gate: (B, N, 1, H) query vs (B, N, S, H) cache with S a
    multiple of the split block and H MXU-friendly.  Callers fall back to
    the XLA masked-attention path otherwise."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    if q_shape[-2] != 1:
        return False                      # single-query decode only
    if q_shape[0] != k_shape[0] or q_shape[1] != k_shape[1]:
        return False
    if q_shape[-1] != k_shape[-1] or q_shape[-1] not in (64, 128, 256):
        return False
    return k_shape[-2] % block == 0


def _decode_kernel(q_ref, k_ref, v_ref, s_ref, e_ref,
                   o_ref, m_ref, l_ref, *, scale, bk):
    """One (sequence*head, split) cell: partial attention over the split's
    ``bk`` cached columns, masked to the row's [start, end) window."""
    isplit = pl.program_id(1)
    q = q_ref[0]                                        # [8, H]
    k = k_ref[0]                                        # [bk, H]
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    col = lax.broadcasted_iota(jnp.int32, (_SUBLANES, bk), 1) + isplit * bk
    valid = (col >= s_ref[0, 0]) & (col < e_ref[0, 0])
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)              # [8, 1]
    # explicit zeroing (not just the -1e30 mask): a fully-masked split has
    # m == -1e30, where exp(s - m) == 1 would fake a live normalizer
    p = jnp.exp(s - m) * valid.astype(jnp.float32)
    l = jnp.sum(p, axis=-1, keepdims=True)              # [8, 1]
    acc = lax.dot_general(p.astype(v_ref.dtype), v_ref[0],
                          (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    o_ref[0, 0] = acc
    m_ref[0, 0] = jnp.broadcast_to(m, (_SUBLANES, 128))
    l_ref[0, 0] = jnp.broadcast_to(l, (_SUBLANES, 128))


def flash_decode_fn(q, k, v, start=None, end=None, *, scale=None,
                    block_k: int = DEFAULT_BLOCK_K_DECODE):
    """Pure-jax flash decoding.

    q ``(B, N, 1, H)``; k/v ``(B, N, S, H)``; ``start``/``end`` int32
    ``[B]`` bound the valid cache window per row (defaults: full cache).
    Returns ``(B, N, 1, H)`` in q's dtype.
    """
    B, N, Sq, H = q.shape
    S = k.shape[2]
    if Sq != 1:
        raise ValueError(f"flash_decode takes a single query row, got Sq={Sq}")
    if scale is None:
        scale = 1.0 / math.sqrt(H)
    bk = _pick_block(S, block_k)
    nsplit = S // bk
    BN = B * N
    q3 = jnp.broadcast_to(q.reshape(BN, 1, H), (BN, _SUBLANES, H))
    k3 = k.reshape(BN, S, H)
    v3 = v.reshape(BN, S, H)
    start2 = (jnp.zeros((B, 1), jnp.int32) if start is None
              else jnp.asarray(start, jnp.int32).reshape(B, 1))
    end2 = (jnp.full((B, 1), S, jnp.int32) if end is None
            else jnp.asarray(end, jnp.int32).reshape(B, 1))

    o_part, m_part, l_part = pl.pallas_call(
        functools.partial(_decode_kernel, scale=float(scale), bk=bk),
        grid=(BN, nsplit),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, H), lambda b, s: (b, 0, 0)),
            pl.BlockSpec((1, bk, H), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, bk, H), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, 1), lambda b, s, n=N: (b // n, 0)),
            pl.BlockSpec((1, 1), lambda b, s, n=N: (b // n, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, _SUBLANES, H), lambda b, s: (b, s, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, 128), lambda b, s: (b, s, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, 128), lambda b, s: (b, s, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, H), jnp.float32),
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, 128), jnp.float32),
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=4 * BN * S * H,
            bytes_accessed=(k3.size + v3.size + q3.size) * 2,
            transcendentals=BN * S),
        interpret=_interpret(),
    )(q3, k3, v3, start2, end2)

    # split-K merge: exact online-softmax recombination of the partials
    m = m_part[:, :, :, 0]                       # (BN, nsplit, 8)
    l = l_part[:, :, :, 0]
    g = jnp.max(m, axis=1)                       # (BN, 8)
    alpha = jnp.exp(m - g[:, None, :])           # empty split: l == 0 anyway
    l_tot = jnp.sum(l * alpha, axis=1)           # (BN, 8)
    o = jnp.sum(o_part * alpha[..., None], axis=1)
    l_safe = jnp.where(l_tot == 0.0, 1.0, l_tot)
    out = (o / l_safe[..., None]).astype(q.dtype)
    return out[:, :1, :].reshape(B, N, 1, H)


def _decode_kernel_quant(q_ref, k_ref, v_ref, ks_ref, vs_ref, s_ref, e_ref,
                         o_ref, m_ref, l_ref, *, scale, bk):
    """Quantized-KV variant of one (sequence*head, split) cell: the
    split's ``bk`` int8 cached rows dequantize INSIDE the split-K loop —
    ``int8 row * per-(token, head) f32 scale`` is a rank-1 broadcast
    against the (bk, H) block, so the f32 K/V tile exists only in VMEM
    for the lifetime of this cell and HBM traffic stays int8."""
    isplit = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                    # [8, H]
    k = k_ref[0].astype(jnp.float32) * ks_ref[0]        # fused dequant
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32) * scale
    col = lax.broadcasted_iota(jnp.int32, (_SUBLANES, bk), 1) + isplit * bk
    valid = (col >= s_ref[0, 0]) & (col < e_ref[0, 0])
    s = jnp.where(valid, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)              # [8, 1]
    p = jnp.exp(s - m) * valid.astype(jnp.float32)
    l = jnp.sum(p, axis=-1, keepdims=True)              # [8, 1]
    v = v_ref[0].astype(jnp.float32) * vs_ref[0]        # fused dequant
    acc = lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    o_ref[0, 0] = acc
    m_ref[0, 0] = jnp.broadcast_to(m, (_SUBLANES, 128))
    l_ref[0, 0] = jnp.broadcast_to(l, (_SUBLANES, 128))


def flash_decode_quant_fn(q, k, v, k_scale, v_scale, start=None, end=None,
                          *, scale=None,
                          block_k: int = DEFAULT_BLOCK_K_DECODE):
    """Pure-jax flash decoding over an int8-quantized KV ring cache.

    q ``(B, N, 1, H)`` float; k/v ``(B, N, S, H)`` int8 rows with
    ``k_scale``/``v_scale`` ``(B, N, S, 1)`` f32 per-(token, head)
    scales; ``start``/``end`` int32 ``[B]`` bound the valid window per
    row.  Must bit-match ``decode_attention_reference`` over the
    dequantized cache (``dequantize_kv`` below) — the dequant moves
    inside the kernel, the math does not change.  Returns
    ``(B, N, 1, H)`` in q's dtype.
    """
    B, N, Sq, H = q.shape
    S = k.shape[2]
    if Sq != 1:
        raise ValueError(f"flash_decode takes a single query row, got Sq={Sq}")
    if scale is None:
        scale = 1.0 / math.sqrt(H)
    bk = _pick_block(S, block_k)
    nsplit = S // bk
    BN = B * N
    q3 = jnp.broadcast_to(q.reshape(BN, 1, H), (BN, _SUBLANES, H))
    k3 = k.reshape(BN, S, H)
    v3 = v.reshape(BN, S, H)
    ks3 = k_scale.reshape(BN, S, 1)
    vs3 = v_scale.reshape(BN, S, 1)
    start2 = (jnp.zeros((B, 1), jnp.int32) if start is None
              else jnp.asarray(start, jnp.int32).reshape(B, 1))
    end2 = (jnp.full((B, 1), S, jnp.int32) if end is None
            else jnp.asarray(end, jnp.int32).reshape(B, 1))

    o_part, m_part, l_part = pl.pallas_call(
        functools.partial(_decode_kernel_quant, scale=float(scale), bk=bk),
        grid=(BN, nsplit),
        in_specs=[
            pl.BlockSpec((1, _SUBLANES, H), lambda b, s: (b, 0, 0)),
            pl.BlockSpec((1, bk, H), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, bk, H), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, bk, 1), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, bk, 1), lambda b, s: (b, s, 0)),
            pl.BlockSpec((1, 1), lambda b, s, n=N: (b // n, 0)),
            pl.BlockSpec((1, 1), lambda b, s, n=N: (b // n, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, _SUBLANES, H), lambda b, s: (b, s, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, 128), lambda b, s: (b, s, 0, 0)),
            pl.BlockSpec((1, 1, _SUBLANES, 128), lambda b, s: (b, s, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, H), jnp.float32),
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, 128), jnp.float32),
            jax.ShapeDtypeStruct((BN, nsplit, _SUBLANES, 128), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel")),
        cost_estimate=pl.CostEstimate(
            flops=4 * BN * S * H,
            # the point of the fused dequant: K/V stream at 1 byte/elt
            bytes_accessed=(k3.size + v3.size
                            + (ks3.size + vs3.size + q3.size) * 4),
            transcendentals=BN * S),
        interpret=_interpret(),
    )(q3, k3, v3, ks3, vs3, start2, end2)

    m = m_part[:, :, :, 0]                       # (BN, nsplit, 8)
    l = l_part[:, :, :, 0]
    g = jnp.max(m, axis=1)                       # (BN, 8)
    alpha = jnp.exp(m - g[:, None, :])
    l_tot = jnp.sum(l * alpha, axis=1)           # (BN, 8)
    o = jnp.sum(o_part * alpha[..., None], axis=1)
    l_safe = jnp.where(l_tot == 0.0, 1.0, l_tot)
    out = (o / l_safe[..., None]).astype(q.dtype)
    return out[:, :1, :].reshape(B, N, 1, H)


def dequantize_kv(q8, scales, dtype=jnp.float32):
    """Dequantize int8 KV rows with their per-(token, head) scales — the
    XLA fallback read, and the reference the fused kernel must match."""
    return (jnp.asarray(q8).astype(jnp.float32)
            * jnp.asarray(scales)).astype(dtype)


def decode_attention_reference(q, k, v, start=None, end=None, *, scale=None):
    """The XLA reference the kernel must match: one masked softmax
    attention over the full cache, f32 logits/accumulation (the same
    numerics contract as nn.functional's ``_sdpa_mask``)."""
    B, N, Sq, H = q.shape
    S = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(H)
    logits = jnp.einsum("bnsh,bnth->bnst", q, k,
                        preferred_element_type=jnp.float32) * scale
    col = jnp.arange(S, dtype=jnp.int32)
    lo = jnp.zeros((B,), jnp.int32) if start is None \
        else jnp.asarray(start, jnp.int32)
    hi = jnp.full((B,), S, jnp.int32) if end is None \
        else jnp.asarray(end, jnp.int32)
    valid = (col[None, :] >= lo[:, None]) & (col[None, :] < hi[:, None])
    logits = jnp.where(valid[:, None, None, :], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bnst,bnth->bnsh", probs.astype(q.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
