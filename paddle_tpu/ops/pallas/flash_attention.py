"""Block-tiled flash attention as a Pallas TPU kernel (fwd + custom-VJP bwd).

Fills the fused-attention slot of the reference's fused-op family
(/root/reference/paddle/fluid/operators/fused/, e.g.
fused_attention-style kernels): instead of materializing the (Sq, Sk)
probability matrix in HBM, both passes stream K/V blocks through VMEM with an
online softmax, so HBM traffic is O(S*H) rather than O(S^2) and the matmuls
stay on the MXU.

Layout: (B, N, S, H) batch/heads/seq/head_dim, internally collapsed to
(B*N, S, H).  Supports causal masking, an additive bias/mask broadcastable
over batch or heads, head_dim 64/128/256, and any Sq/Sk that are multiples of
the block size (128).  The bias input is non-differentiable (its VJP is
zero); the nn.functional dispatch gate routes trainable masks to the XLA
path instead.

Runs compiled on TPU and in interpret mode on CPU (used by the grad-check
tests against the plain XLA softmax-attention path).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams (~0.6); support both
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

DEFAULT_BLOCK = 128
# Measured on v5e (chained-dispatch, bf16): larger blocks feed the MXU much
# better — bq=512/bk=1024 reaches 64 TF/s at S=4096 vs 10 TF/s with 128x128
# blocks (and 16 TF/s for the materializing XLA path).
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
# Below this key length the materializing XLA softmax-attention is faster
# (dispatch- and bandwidth-bound regime); callers should prefer it.
MIN_SEQ_FOR_FLASH = 1024
_NEG_INF = -1e30  # finite mask value: exp(s - lse) underflows to exactly 0


def _pick_block(size: int, target: int) -> int:
    """Largest multiple of 128 that divides ``size`` and is <= target."""
    b = min(target, size)
    b -= b % 128
    while b > 128 and size % b:
        b -= 128
    return max(b, min(size, 128))


def _interpret() -> bool:
    try:
        return jax.devices()[0].platform == "cpu"
    except Exception:
        return True


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, offset):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # bottom-right-aligned causal (query row i sees keys <= i + offset,
    # offset = Sk - Sq >= 0): the last k block with any valid column for
    # this q block, and whether this (iq, ik) pair contributes at all
    last = jnp.minimum(nk - 1, ((iq + 1) * bq - 1 + offset) // bk) \
        if causal else nk - 1
    run = (ik <= last) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if b_ref is not None:
            s = s + b_ref[0, 0].astype(jnp.float32)
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            col = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
            s = jnp.where(row + offset >= col, s, _NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = alpha * l_scr[:, :1] + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ik == last)
    def _finish():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0, 1.0, l)
        o_ref[0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse = m_scr[:, 0] + jnp.log(l_safe[:, 0])
        lse_ref[0] = jnp.broadcast_to(lse[None, :], lse_ref.shape[1:])


def _bias_spec(bias_shape, n_heads, bq, bk, qmajor=True):
    """BlockSpec for a (Bb, Nb, Sq, Sk) bias under the collapsed (B*N) grid,
    broadcasting over batch/head dims of size 1.  ``qmajor`` selects whether
    grid dim 1 is the q-block (fwd/dq) or the k-block (dkv) index."""
    Bb, Nb, Sq, Sk = bias_shape
    rows = Sq > 1

    def idx(b, i, j):
        iq, ik = (i, j) if qmajor else (j, i)
        bb = (b // n_heads) if Bb > 1 else 0
        nb = (b % n_heads) if Nb > 1 else 0
        return (bb, nb, iq if rows else 0, ik)

    return pl.BlockSpec((1, 1, bq if rows else 1, bk), idx)


def _flash_fwd_call(q3, k3, v3, bias4, n_heads, scale, causal, bq, bk):
    BN, Sq, H = q3.shape
    Sk = k3.shape[1]
    nq, nk = Sq // bq, Sk // bk
    grid = (BN, nq, nk)
    offset = Sk - Sq

    in_specs = [
        pl.BlockSpec((1, bq, H), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, bk, H), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, bk, H), lambda b, i, j: (b, j, 0)),
    ]
    args = [q3, k3, v3]
    if bias4 is not None:
        in_specs.append(_bias_spec(bias4.shape, n_heads, bq, bk, qmajor=True))
        args.append(bias4)
        kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                                   bq=bq, bk=bk, offset=offset)
    else:
        kernel = functools.partial(
            lambda qr, kr, vr, o, ls, m, l, a, **kw: _fwd_kernel(
                qr, kr, vr, None, o, ls, m, l, a, **kw),
            scale=scale, causal=causal, bq=bq, bk=bk, offset=offset)

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, bq, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 8, bq), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, Sq, H), q3.dtype),
            # lse rows replicated over 8 sublanes: Mosaic requires the last
            # two block dims to tile as (8, 128)
            jax.ShapeDtypeStruct((BN, 8, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, H), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * BN * Sq * Sk * H // (2 if causal else 1),
            bytes_accessed=(2 * q3.size + k3.size + v3.size) * 2,
            transcendentals=BN * Sq * Sk),
        interpret=_interpret(),
    )(*args)
    return out, lse


# --------------------------------------------------------------------------
# backward
# --------------------------------------------------------------------------

def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, b_ref, dq_ref,
               dq_scr, *, scale, causal, bq, bk, offset):
    iq = pl.program_id(1)
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    last = jnp.minimum(nk - 1, ((iq + 1) * bq - 1 + offset) // bk) \
        if causal else nk - 1
    run = (ik <= last) if causal else (ik >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if b_ref is not None:
            s = s + b_ref[0, 0].astype(jnp.float32)
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            col = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
            s = jnp.where(row + offset >= col, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0, :][:, None])
        do = do_ref[0]
        dp = lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dd_ref[0, 0, :][:, None]) * scale
        dq_scr[:] = dq_scr[:] + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ik == last)
    def _finish():
        dq_ref[0] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dd_ref, b_ref,
                dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal, bq, bk,
                offset):
    ik = pl.program_id(1)
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    # this q block contributes iff its bottom row can see this k block
    run = ((iq + 1) * bq - 1 + offset >= ik * bk) if causal else (iq >= 0)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if b_ref is not None:
            s = s + b_ref[0, 0].astype(jnp.float32)
        if causal:
            row = lax.broadcasted_iota(jnp.int32, (bq, bk), 0) + iq * bq
            col = lax.broadcasted_iota(jnp.int32, (bq, bk), 1) + ik * bk
            s = jnp.where(row + offset >= col, s, _NEG_INF)
        p = jnp.exp(s - lse_ref[0, 0, :][:, None])
        do = do_ref[0]
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v_ref[0], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dd_ref[0, 0, :][:, None]) * scale
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[:].astype(dv_ref.dtype)


def _flash_bwd_call(q3, k3, v3, bias4, out3, lse, do3, n_heads, scale,
                    causal, bq, bk):
    BN, Sq, H = q3.shape
    Sk = k3.shape[1]
    nq, nk = Sq // bq, Sk // bk

    # D_i = rowsum(dO * O): one cheap fused elementwise+reduce in XLA,
    # replicated over 8 sublanes to match the lse tiling
    dd = jnp.sum(do3.astype(jnp.float32) * out3.astype(jnp.float32),
                 axis=-1)  # (BN, Sq)
    dd = jnp.broadcast_to(dd[:, None, :], (BN, 8, Sq))

    common = dict(scale=scale, causal=causal, bq=bq, bk=bk,
                  offset=Sk - Sq)
    interp = _interpret()

    def specs(qmajor):
        # index helpers: i is the "owner" block dim, j sweeps
        def qi(b, i, j):
            return (b, i, 0) if qmajor else (b, j, 0)

        def ki(b, i, j):
            return (b, j, 0) if qmajor else (b, i, 0)

        sp = [
            pl.BlockSpec((1, bq, H), qi),                     # q
            pl.BlockSpec((1, bk, H), ki),                     # k
            pl.BlockSpec((1, bk, H), ki),                     # v
            pl.BlockSpec((1, bq, H), qi),                     # do
            pl.BlockSpec((1, 8, bq), lambda b, i, j:
                         (b, 0, i) if qmajor else (b, 0, j)),  # lse
            pl.BlockSpec((1, 8, bq), lambda b, i, j:
                         (b, 0, i) if qmajor else (b, 0, j)),  # dd
        ]
        if bias4 is not None:
            sp.append(_bias_spec(bias4.shape, n_heads, bq, bk, qmajor=qmajor))
        return sp

    def wrap(kern):
        if bias4 is not None:
            return functools.partial(kern, **common)

        def no_bias(*refs, **kw):
            # insert b_ref=None after dd_ref (6 input refs without bias)
            return kern(*refs[:6], None, *refs[6:], **kw)
        return functools.partial(no_bias, **common)

    args = [q3, k3, v3, do3, lse, dd] + ([bias4] if bias4 is not None else [])

    dq = pl.pallas_call(
        wrap(_dq_kernel),
        grid=(BN, nq, nk),
        in_specs=specs(qmajor=True),
        out_specs=[pl.BlockSpec((1, bq, H), lambda b, i, j: (b, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((BN, Sq, H), q3.dtype)],
        scratch_shapes=[pltpu.VMEM((bq, H), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(*args)[0]

    dk, dv = pl.pallas_call(
        wrap(_dkv_kernel),
        grid=(BN, nk, nq),
        in_specs=specs(qmajor=False),
        out_specs=[
            pl.BlockSpec((1, bk, H), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, bk, H), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BN, Sk, H), k3.dtype),
            jax.ShapeDtypeStruct((BN, Sk, H), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, H), jnp.float32),
            pltpu.VMEM((bk, H), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(*args)
    return dq, dk, dv


# --------------------------------------------------------------------------
# custom-vjp wrapper
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _flash_core(n_heads, scale, causal, bq, bk, q3, k3, v3, bias4):
    out, _ = _flash_fwd_call(q3, k3, v3, bias4, n_heads, scale, causal,
                             bq, bk)
    return out


def _flash_core_fwd(n_heads, scale, causal, bq, bk, q3, k3, v3, bias4):
    out, lse = _flash_fwd_call(q3, k3, v3, bias4, n_heads, scale, causal,
                               bq, bk)
    return out, (q3, k3, v3, bias4, out, lse)


def _flash_core_bwd(n_heads, scale, causal, bq, bk, res, do3):
    q3, k3, v3, bias4, out, lse = res
    dq, dk, dv = _flash_bwd_call(q3, k3, v3, bias4, out, lse, do3,
                                 n_heads, scale, causal, bq, bk)
    dbias = None if bias4 is None else jnp.zeros_like(bias4)
    return dq, dk, dv, dbias


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def supports(q_shape, k_shape, bias_shape=None,
             block: int = DEFAULT_BLOCK, causal: bool = False) -> bool:
    """Shape gate: (B,N,S,H) with S multiples of the block and H MXU-friendly.
    Callers fall back to the plain XLA softmax-attention path otherwise."""
    if len(q_shape) != 4 or len(k_shape) != 4:
        return False
    Sq, H = q_shape[-2], q_shape[-1]
    Sk = k_shape[-2]
    if Sq % block or Sk % block:
        return False
    if causal and Sq > Sk:
        # bottom-right alignment would fully mask the top rows; semantics of
        # that corner differ between implementations — use the XLA path
        return False
    if H not in (64, 128, 256):
        return False
    if bias_shape is not None:
        if len(bias_shape) != 4 or bias_shape[-1] != Sk:
            return False
        if bias_shape[-2] not in (1, Sq):
            return False
        if bias_shape[0] not in (1, q_shape[0]):
            return False
        if bias_shape[1] not in (1, q_shape[1]):
            return False
    return True


def flash_attention_fn(q, k, v, bias=None, *, causal=False, scale=None,
                       block_q: int = DEFAULT_BLOCK_Q,
                       block_k: int = DEFAULT_BLOCK_K):
    """Pure-jax flash attention on (B, N, S, H) arrays (bias additive)."""
    B, N, Sq, H = q.shape
    Sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(H)
    if causal and Sq > Sk:
        raise ValueError(
            f"causal flash attention requires Sq <= Sk, got {Sq} > {Sk} "
            "(use the XLA attention path)")
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    if causal and bq != bk:
        # equal blocks that divide BOTH lengths (a divisor of gcd), so no
        # trailing q/k block is dropped by the grid floor-division
        bq = bk = _pick_block(math.gcd(Sq, Sk), min(bq, bk))
    q3 = q.reshape(B * N, Sq, H)
    k3 = k.reshape(B * N, Sk, H)
    v3 = v.reshape(B * N, Sk, H)
    bias4 = None
    if bias is not None:
        bias4 = jnp.asarray(bias)
        while bias4.ndim < 4:
            bias4 = bias4[None]
    out = _flash_core(N, float(scale), bool(causal), bq, bk, q3, k3, v3,
                      bias4)
    return out.reshape(B, N, Sq, H)
