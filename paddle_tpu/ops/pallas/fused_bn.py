"""Fused train-mode BatchNorm(+ReLU) Pallas kernels, fwd + custom VJP.

Reference parity: the conv+BN+act epilogue fusions the reference ships as
CUDA kernels (operators/fused/conv_fusion_op.cc, fused_batch_norm_act) —
here the epilogue around XLA's conv: one stats pass (read x, per-channel
sum/sumsq) and one apply pass (read x, normalize+affine+ReLU, write y),
with a two-kernel backward (reduce dgamma/dbeta, then apply dx).

Gating (VERDICT r4 item 2, measured honestly): these kernels microbench
within ±10% of XLA's own fused BN epilogue (stats 1.2 ms + apply 6.1 ms vs
XLA 7.5 ms on a [256·56·56, 256] bf16 activation), and the DECISIVE
end-to-end measurement is ResNet-50 at 974 img/s with them ON vs 1,971
OFF — opaque customs break XLA's conv-epilogue fusion (round-5 note: the
chip's streaming bound re-measured at ~630 GB/s, PERF.md round-5; the
e2e verdict is bandwidth-estimate-independent and stands).  They ship
OFF by default and enable
via ``FLAGS_use_pallas_fused_bn`` (flags registry / paddle.set_flags;
legacy ``PADDLE_TPU_PALLAS_BN=1`` also honored) — the same honesty as
ops/pallas/flash_attention.py, recorded so a future chip/toolchain with a
wider HBM gap can flip the default with one env probe.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.default_backend() == "cpu"


def enabled() -> bool:
    """Honest gate: measured SLOWER than XLA end-to-end on the bench chip,
    so the pallas path is opt-in — through the flags registry
    (paddle.set_flags({"FLAGS_use_pallas_fused_bn": True}) or the
    FLAGS_use_pallas_fused_bn env seed), with the legacy
    PADDLE_TPU_PALLAS_BN=1 env var still honored."""
    from ...framework.flags import flag
    return bool(flag("use_pallas_fused_bn")) or \
        os.environ.get("PADDLE_TPU_PALLAS_BN", "0") == "1"


def _pick_tile(m: int, c: int) -> int:
    """Largest ladder tile dividing m whose [tm, c] block fits VMEM with
    the backward's TWO input streams + f32 temps double-buffered
    (~16 MB/core on v5e): cap tm·c at 128K elements."""
    cap = max(8, (128 * 1024) // max(c, 1))
    for tm in (8192, 4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8):
        if tm <= cap and m % tm == 0:
            return tm
    return 0


# -- forward kernels ---------------------------------------------------------

def _stats_kernel(x_ref, sum_ref, sq_ref):
    i = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)

    @pl.when(i == 0)
    def _():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    sum_ref[...] += jnp.sum(xf, axis=0)
    sq_ref[...] += jnp.sum(xf * xf, axis=0)


def _apply_kernel(x_ref, scale_ref, shift_ref, o_ref, *, relu):
    xf = x_ref[...].astype(jnp.float32)
    y = xf * scale_ref[...] + shift_ref[...]
    if relu:
        y = jnp.maximum(y, 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


def _moments(x2d, tm):
    m, c = x2d.shape
    s, q = pl.pallas_call(
        _stats_kernel,
        grid=(m // tm,),
        in_specs=[pl.BlockSpec((tm, c), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((c,), lambda i: (0,)),
                   pl.BlockSpec((c,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((c,), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32)],
        interpret=_interpret(),
    )(x2d)
    mean = s / m
    var = jnp.maximum(q / m - mean * mean, 0.0)
    return mean, var


def _apply(x2d, scale, shift, tm, relu):
    m, c = x2d.shape
    return pl.pallas_call(
        functools.partial(_apply_kernel, relu=relu),
        grid=(m // tm,),
        in_specs=[pl.BlockSpec((tm, c), lambda i: (i, 0)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,))],
        out_specs=pl.BlockSpec((tm, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=_interpret(),
    )(x2d, scale, shift)


# -- backward kernels --------------------------------------------------------

def _bwd_reduce_kernel(x_ref, dy_ref, scale_ref, shift_ref, dg_ref, db_ref,
                       *, relu):
    """Per-channel Σdy' and Σdy'·x̂ (dy' = dy masked by the relu gate,
    recomputed from x so y never needs storing)."""
    i = pl.program_id(0)
    xf = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if relu:
        gate = (xf * scale_ref[...] + shift_ref[...]) > 0.0
        dy = jnp.where(gate, dy, 0.0)

    @pl.when(i == 0)
    def _():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    db_ref[...] += jnp.sum(dy, axis=0)
    # accumulate Σ dy'·x; the caller finishes
    # dgamma = inv·(Σdy'·x − mean·Σdy')
    dg_ref[...] += jnp.sum(dy * xf, axis=0)


def _bwd_dx_kernel(x_ref, dy_ref, scale_ref, shift_ref, a_ref, b_ref,
                   c_ref, o_ref, *, relu):
    """dx = a·dy' + b·x + c (per-channel coefficient form of the BN
    backward, so the kernel is one fused multiply-add pass)."""
    xf = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    if relu:
        gate = (xf * scale_ref[...] + shift_ref[...]) > 0.0
        dy = jnp.where(gate, dy, 0.0)
    o_ref[...] = (a_ref[...] * dy + b_ref[...] * xf +
                  c_ref[...]).astype(o_ref.dtype)


# -- public functional -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def fused_bn_act(x2d, gamma, beta, eps=1e-5, relu=True):
    """Train-mode BN over axis 0 of a [M, C] activation, optional fused
    ReLU.  Returns (y, mean, var) — the same contract as the
    batch_norm_train primitive after flattening N·spatial→M (NHWC)."""
    y, mean, var, *_ = _fwd_impl(x2d, gamma, beta, eps, relu)
    return y, mean, var


def _fwd_impl(x2d, gamma, beta, eps, relu):
    tm = _pick_tile(*x2d.shape)
    if tm == 0:
        raise ValueError(f"fused_bn_act: M={x2d.shape[0]} has no tile; "
                         f"pad M to a multiple of 8")
    mean, var = _moments(x2d, tm)
    inv = jax.lax.rsqrt(var + eps)
    scale = inv * gamma.astype(jnp.float32)
    shift = beta.astype(jnp.float32) - mean * scale
    y = _apply(x2d, scale, shift, tm, relu)
    return y, mean, var, inv, scale, shift


def _fwd_rule(x2d, gamma, beta, eps, relu):
    y, mean, var, inv, scale, shift = _fwd_impl(x2d, gamma, beta, eps, relu)
    # beta's dtype rides as a zero-size array (residuals must be JAX types)
    beta_tag = jnp.zeros((0,), beta.dtype)
    return (y, mean, var), (x2d, gamma, beta_tag, mean, inv, scale, shift)


def bn_bwd_reduce(x2d, dy, scale, shift, relu, tm=None):
    """Per-channel (Σdy'·x, Σdy') over a [M, C] activation, dy' masked by
    the recomputed relu gate.  One streaming read of (x, dy) — shared by
    the fused-BN and fused-conv backward passes (fused_conv.py reuses it
    on the conv output)."""
    m, c = x2d.shape
    tm = tm or _pick_tile(m, c)
    return pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, relu=relu),
        grid=(m // tm,),
        in_specs=[pl.BlockSpec((tm, c), lambda i: (i, 0)),
                  pl.BlockSpec((tm, c), lambda i: (i, 0)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,))],
        out_specs=[pl.BlockSpec((c,), lambda i: (0,)),
                   pl.BlockSpec((c,), lambda i: (0,))],
        out_shape=[jax.ShapeDtypeStruct((c,), jnp.float32),
                   jax.ShapeDtypeStruct((c,), jnp.float32)],
        interpret=_interpret(),
    )(x2d, dy, scale, shift)


def bn_bwd_dx(x2d, dy, scale, shift, a, b, cc, relu, tm=None):
    """dx = a·dy' + b·x + c as one fused multiply-add pass (the
    per-channel coefficient form of the BN backward; also shared with
    fused_conv.py)."""
    m, c = x2d.shape
    tm = tm or _pick_tile(m, c)
    return pl.pallas_call(
        functools.partial(_bwd_dx_kernel, relu=relu),
        grid=(m // tm,),
        in_specs=[pl.BlockSpec((tm, c), lambda i: (i, 0)),
                  pl.BlockSpec((tm, c), lambda i: (i, 0)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,)),
                  pl.BlockSpec((c,), lambda i: (0,))],
        out_specs=pl.BlockSpec((tm, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=_interpret(),
    )(x2d, dy, scale, shift, a, b, cc)


def bn_dx_coeffs(gamma, inv, mean, dbeta, sum_dyx, m, dmean=None, dvar=None):
    """(dgamma, a, b, c) of the coefficient-form BN backward.

    dx = γ·inv·dy' − γ·inv/M·dbeta − γ·inv/M·x̂·dgamma  =  a·dy' + b·x + c
      a = γ·inv,  b = −γ·inv²·dgamma/M,  c = −γ·inv·dbeta/M − b·mean
    Cotangents THROUGH the returned statistics (∂mean/∂x = 1/M,
    ∂var/∂x = 2(x−mean)/M) fold into the same coefficient form."""
    # dgamma = Σ dy'·x̂ = inv·(Σdy'·x − mean·Σdy')
    dgamma = inv * (sum_dyx - mean * dbeta)
    g = gamma.astype(jnp.float32)
    a = g * inv
    b = -(g * inv) * (inv * dgamma) / m
    cc = -(g * inv) * (dbeta / m) - b * mean
    if dvar is not None:
        dvar = dvar.astype(jnp.float32)
        b = b + 2.0 * dvar / m
        cc = cc - 2.0 * dvar * mean / m
    if dmean is not None:
        cc = cc + dmean.astype(jnp.float32) / m
    return dgamma, a, b, cc


def _bwd_rule(eps, relu, res, cts):
    x2d, gamma, beta_tag, mean, inv, scale, shift = res
    dy, dmean, dvar = cts
    m, c = x2d.shape
    tm = _pick_tile(m, c)
    sum_dyx, dbeta = bn_bwd_reduce(x2d, dy, scale, shift, relu, tm)
    dgamma, a, b, cc = bn_dx_coeffs(gamma, inv, mean, dbeta, sum_dyx, m,
                                    dmean, dvar)
    dx = bn_bwd_dx(x2d, dy, scale, shift, a, b, cc, relu, tm)
    # cotangent dtypes must match the PRIMAL inputs (custom_vjp contract);
    # dbeta follows beta's dtype, not gamma's
    return dx, dgamma.astype(gamma.dtype), dbeta.astype(beta_tag.dtype)


fused_bn_act.defvjp(_fwd_rule, _bwd_rule)
