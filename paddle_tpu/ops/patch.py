"""Monkey-patch operator methods onto Tensor.

Reference parity: python/paddle/fluid/dygraph/math_op_patch.py and
varbase_patch_methods.py -- Paddle itself patches arithmetic dunders and tensor
methods onto VarBase at import; we do the same so framework/tensor.py stays
free of op imports (no circular deps).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap
from . import creation, manipulation, math as m


def _coerce(other, like):
    if isinstance(other, Tensor):
        return other
    return other  # jnp weak-type promotion keeps paddle scalar semantics


# ---- indexing ----------------------------------------------------------------

_getitem_cache = {}


def _encode_index(idx, nd):
    """Encode a (possibly nested) index into a hashable static spec; tensor
    indices are returned separately as dynamic args."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    spec, dynamic = [], []
    for it in idx:
        if isinstance(it, Tensor) or type(it).__name__ == "Variable":
            if it.dtype == jnp.bool_:
                spec.append(("mask",))
            else:
                spec.append(("arr",))
            dynamic.append(unwrap(it) if isinstance(it, Tensor) else it)
        elif isinstance(it, (np.ndarray, list)):
            arr = jnp.asarray(np.asarray(it))
            spec.append(("mask",) if arr.dtype == jnp.bool_ else ("arr",))
            dynamic.append(arr)
        elif isinstance(it, (jax.Array, jax.core.Tracer)):
            # raw traced index (e.g. a dy2static loop carry): dynamic arg
            spec.append(("mask",) if it.dtype == jnp.bool_ else ("arr",))
            dynamic.append(it)
        elif isinstance(it, builtins_slice):
            spec.append(("slice", _slice_bound(it.start),
                         _slice_bound(it.stop), _slice_bound(it.step)))
        elif it is None:
            spec.append(("none",))
        elif it is Ellipsis:
            spec.append(("ellipsis",))
        else:
            spec.append(("int", int(it)))
    return tuple(spec), dynamic


builtins_slice = slice


def _slice_bound(v):
    """Normalize a slice bound into the hashable static spec.  Concrete
    tensors collapse to ints; a TRACED bound has no static window size at
    this level and must go through the dy2static converter (which carries
    the syntactic ``i:i+k`` size) or ops.manipulation.dynamic_slice."""
    if v is None or isinstance(v, (int, np.integer)):
        return None if v is None else int(v)
    u = unwrap(v) if isinstance(v, Tensor) else v
    if isinstance(u, jax.core.Tracer):
        raise TypeError(
            "slice bounds cannot be traced values at the tensor level: "
            "the window size would be dynamic. Use paddle.slice/"
            "dynamic_slice with a static size, or write x[i:i+k] with a "
            "constant k inside @to_static (slice_op.cc StartsTensor)")
    return int(u)


def _decode_index(spec, dynamic):
    out, di = [], 0
    for s in spec:
        kind = s[0]
        if kind in ("mask", "arr"):
            out.append(dynamic[di]); di += 1
        elif kind == "slice":
            out.append(builtins_slice(s[1], s[2], s[3]))
        elif kind == "none":
            out.append(None)
        elif kind == "ellipsis":
            out.append(Ellipsis)
        else:
            out.append(s[1])
    return tuple(out)


def _scalar_int_index(x, spec, dynamic):
    """True for ``x[i]`` with a single scalar integer index — the case
    that lowers to lax.dynamic_slice instead of a gather (slice_op.cc
    StartsTensor parity): same value, but the VJP becomes a
    dynamic_update_slice rather than a serialized TPU scatter."""
    if len(spec) != 1 or spec[0][0] != "arr" or len(dynamic) != 1:
        return False
    d = dynamic[0]
    return (jnp.ndim(x) >= 1 and hasattr(d, "dtype")
            and jnp.issubdtype(d.dtype, jnp.integer) and jnp.ndim(d) == 0)


def _getitem_fn(x, *dynamic, spec=()):
    if _scalar_int_index(x, spec, dynamic):
        d = dynamic[0]
        i = jnp.where(d < 0, d + x.shape[0], d)
        return jax.lax.dynamic_index_in_dim(x, i, axis=0, keepdims=False)
    return x[_decode_index(spec, list(dynamic))]


_getitem = Primitive("getitem", _getitem_fn)


def _tensor_getitem(self, idx):
    spec, dynamic = _encode_index(idx, self.ndim)
    if any(s[0] == "mask" for s in spec):
        if not isinstance(self, Tensor) or \
                any(not isinstance(d, (Tensor, jnp.ndarray, np.ndarray))
                    and hasattr(d, "shape") for d in dynamic):
            raise TypeError(
                "boolean-mask indexing has a data-dependent shape and "
                "cannot be recorded in a static program; use "
                "paddle.masked_select with a fixed-size fallback or index "
                "eagerly")
        # boolean masking has a data-dependent shape: eager numpy path
        full = _decode_index(spec, dynamic)
        return Tensor(jnp.asarray(np.asarray(self.numpy()[
            tuple(np.asarray(d) if hasattr(d, "shape") else d for d in full)])))
    return _getitem(self, *dynamic, spec=spec)


def _setitem_fn(x, v, *dynamic, spec=()):
    if _scalar_int_index(x, spec, dynamic):
        d = dynamic[0]
        i = jnp.where(d < 0, d + x.shape[0], d)
        vv = jnp.broadcast_to(jnp.asarray(v, x.dtype), x.shape[1:])
        return jax.lax.dynamic_update_index_in_dim(x, vv, i, axis=0)
    return x.at[_decode_index(spec, list(dynamic))].set(v)


_setitem = Primitive("setitem", _setitem_fn)


def _old_version(s):
    """Snapshot the pre-mutation version of a non-leaf tensor for in-place
    ops: the recorded op must consume the OLD (node, out_index) edge, not
    the tensor object that is about to be re-pointed at the new node —
    GradNode captures edges at record time, so earlier consumers keep the
    pre-mutation version and this op sees it too. Leaves need no snapshot:
    their edge is (None, ·) and gradient accumulation targets the tensor
    object itself."""
    from ..framework.tensor import Tensor
    old = Tensor(s._value, stop_gradient=s.stop_gradient)
    old._node = s._node
    old._out_index = s._out_index
    old.is_leaf = s.is_leaf
    return old


def _adopt(s, out):
    """Point s at the freshly computed version (in-place surface). The
    version bump makes a later backward through PRE-mutation consumers of
    a leaf raise instead of applying stale gradients (inplace version
    check parity). The mutating op ITSELF legitimately consumed the old
    value, so its own edge is re-stamped to the new version."""
    boundary = s._node   # pre-mutation lineage tip (delta-walk wall below)
    s._value = out._value
    s._node = out._node
    s._out_index = out._out_index
    s._version += 1
    if out._node is not None:
        # Backward's version check reads edge versions only on LEAF
        # (None, ·) edges, so the only edges ever needing a re-stamp are
        # leaf edges to s held by nodes inside the mutation's own lineage
        # — i.e. former mutating ops of s (their primals captured the
        # consumed value, so replay is always valid; chained x.add_();
        # x.add_() must not false-positive).  Those edges are stamped with
        # a permanent None exemption, ONCE, so they never re-qualify.
        # Unrelated pre-mutation consumers keep the stale version and the
        # leaf check still fires for them.
        targets = set()
        if s._consumers:
            live = []
            for ref in s._consumers:
                c = ref()
                if c is not None and c.inputs is not None:
                    live.append(ref)
                    if any(t is s and p is None and v is not None
                           for t, (p, oi, v) in
                           zip(c.inputs, c.input_edges)):
                        targets.add(id(c))
            s._consumers = live or None
        if targets:
            # delta walk: ancestors of the previous tip were searched (for
            # these same still-unresolved targets) by earlier adoptions,
            # so stop at the boundary node — each region of the graph is
            # visited at most once across a chain of in-place ops
            seen = set()
            stack = [out._node]
            while stack and targets:
                node = stack.pop()
                if id(node) in seen or node is boundary or \
                        node.inputs is None:
                    continue
                seen.add(id(node))
                if id(node) in targets:
                    targets.discard(id(node))
                    node.input_edges = tuple(
                        (p, oi, None) if (t is s and p is None)
                        else (p, oi, v)
                        for t, (p, oi, v) in
                        zip(node.inputs, node.input_edges))
                for (p, _, _) in node.input_edges:
                    if p is not None:
                        stack.append(p)
        s.stop_gradient = False
        s.is_leaf = False
    return s


def _tensor_setitem(self, idx, value):
    spec, dynamic = _encode_index(idx, self.ndim)
    v = unwrap(value)
    if not hasattr(v, "dtype"):
        v = jnp.asarray(v, self.dtype)
    from ..framework import core
    if core.grad_enabled() and self._node is not None:
        out = _setitem(_old_version(self), v, *dynamic, spec=spec)
    else:
        out = _setitem(self, v, *dynamic, spec=spec)
    # functional update with in-place surface semantics (paddle __setitem__)
    _adopt(self, out)


def apply_patches(T=None, eager=True):
    """Install operator methods. Called with the eager Tensor at import and
    with the static Variable class by paddle_tpu.static (the math_op_patch
    dual of framework.py's static Variable operator overloads)."""
    if T is None:
        T = Tensor
    # arithmetic
    T.__add__ = lambda s, o: m.add(s, _coerce(o, s))
    T.__radd__ = lambda s, o: m.add(_coerce(o, s), s)
    T.__sub__ = lambda s, o: m.subtract(s, _coerce(o, s))
    T.__rsub__ = lambda s, o: m.subtract(_coerce(o, s), s)
    T.__mul__ = lambda s, o: m.multiply(s, _coerce(o, s))
    T.__rmul__ = lambda s, o: m.multiply(_coerce(o, s), s)
    T.__truediv__ = lambda s, o: m.divide(s, _coerce(o, s))
    T.__rtruediv__ = lambda s, o: m.divide(_coerce(o, s), s)
    T.__floordiv__ = lambda s, o: m.floor_divide(s, _coerce(o, s))
    T.__mod__ = lambda s, o: m.mod(s, _coerce(o, s))
    T.__pow__ = lambda s, o: m.pow(s, _coerce(o, s))
    T.__rpow__ = lambda s, o: m.pow(_coerce(o, s), s)
    T.__neg__ = lambda s: m.neg(s)
    T.__abs__ = lambda s: m.abs(s)
    T.__matmul__ = lambda s, o: m.matmul(s, o)
    T.__rmatmul__ = lambda s, o: m.matmul(o, s)
    # comparisons
    T.__eq__ = lambda s, o: m.equal(s, _coerce(o, s))
    T.__ne__ = lambda s, o: m.not_equal(s, _coerce(o, s))
    T.__lt__ = lambda s, o: m.less_than(s, _coerce(o, s))
    T.__le__ = lambda s, o: m.less_equal(s, _coerce(o, s))
    T.__gt__ = lambda s, o: m.greater_than(s, _coerce(o, s))
    T.__ge__ = lambda s, o: m.greater_equal(s, _coerce(o, s))
    T.__invert__ = lambda s: m.logical_not(s)
    T.__and__ = lambda s, o: m.logical_and(s, o) if s.dtype == jnp.bool_ else m.bitwise_and(s, o)
    T.__or__ = lambda s, o: m.logical_or(s, o) if s.dtype == jnp.bool_ else m.bitwise_or(s, o)
    T.__xor__ = lambda s, o: m.logical_xor(s, o) if s.dtype == jnp.bool_ else m.bitwise_xor(s, o)
    # indexing (in-place setitem is eager-only; static programs are SSA)
    T.__getitem__ = _tensor_getitem
    if eager:
        T.__setitem__ = _tensor_setitem

    # methods: math
    for name in ["add", "subtract", "multiply", "divide", "pow", "mod",
                 "maximum", "minimum", "matmul", "mm", "bmm", "dot", "exp",
                 "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "abs",
                 "sin", "cos", "tan", "tanh", "floor", "ceil", "round",
                 "sign", "reciprocal", "square", "erf", "neg", "sum", "mean",
                 "prod", "max", "min", "std", "var", "logsumexp", "all",
                 "any", "cumsum", "cumprod", "argmax", "argmin", "argsort",
                 "sort", "topk", "clip", "scale", "equal", "not_equal",
                 "greater_than", "greater_equal", "less_than", "less_equal",
                 "logical_and", "logical_or", "logical_not", "isnan", "isinf",
                 "isfinite", "allclose", "equal_all", "trace", "kron",
                 "lerp", "outer", "inner", "t", "nan_to_num", "atan", "asin",
                 "acos", "sinh", "cosh", "expm1", "trunc", "frac", "angle"]:
        setattr(T, name, _method(getattr(m, name)))
    # methods: manipulation
    for name in ["reshape", "transpose", "concat", "split", "chunk", "squeeze",
                 "unsqueeze", "flatten", "expand", "expand_as", "broadcast_to",
                 "tile", "gather", "gather_nd", "scatter", "scatter_nd_add",
                 "index_select", "masked_select", "flip", "roll", "unbind",
                 "unstack", "where", "take_along_axis", "put_along_axis",
                 "moveaxis", "swapaxes", "unique", "repeat_interleave",
                 "rot90", "index_sample"]:
        setattr(T, name, _method(getattr(manipulation, name)))
    T.cast = lambda s, dtype: manipulation.cast(s, dtype)
    T.astype = lambda s, dtype: manipulation.cast(s, dtype)
    T.masked_fill = _method(m.masked_fill)
    if eager:
        T.fill_ = lambda s, v: s.set_value(jnp.full_like(s._value, float(v)))
        T.zero_ = lambda s: s.set_value(jnp.zeros_like(s._value))
        # in-place arithmetic (math_op_patch add_/subtract_/scale_ family):
        # functional update with in-place surface semantics — the recorded
        # op consumes the OLD version and the tensor adopts the new node,
        # so the mutation stays on the tape without a graph cycle
        def _inplace(compute):
            def run(s, *args, **kwargs):
                from ..framework import core
                src = _old_version(s) if (core.grad_enabled() and
                                          s._node is not None) else s
                return _adopt(s, compute(src, *args, **kwargs))
            return run

        T.add_ = _inplace(lambda s, o: s + _coerce(o, s))
        T.subtract_ = _inplace(lambda s, o: s - _coerce(o, s))
        T.multiply_ = _inplace(lambda s, o: s * _coerce(o, s))
        T.scale_ = _inplace(
            lambda s, scale=1.0, bias=0.0, bias_after_scale=True:
            m.scale(s, scale=scale, bias=bias,
                    bias_after_scale=bias_after_scale))
        T.clip_ = _inplace(lambda s, min=None, max=None: m.clip(s, min, max))
    T.norm = _method_norm
    # misc method parity (varbase_patch_methods)
    T.ndimension = lambda s: len(s.shape)
    T.rank = lambda s: len(s.shape)
    T.element_size = lambda s: jnp.dtype(s.dtype).itemsize
    T.contiguous = lambda s: s                 # XLA arrays are always dense
    T.is_contiguous = lambda s: True
    T.slice = lambda s, axes, starts, ends: manipulation.slice(
        s, axes, starts, ends)
    if eager:
        T.gradient = lambda s: (None if s.grad is None
                                else s.grad.numpy())


def _method(fn):
    def bound(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    bound.__name__ = fn.__name__
    return bound


def _method_norm(self, p=2, axis=None, keepdim=False, name=None):
    from . import linalg
    return linalg.norm(self, p=p, axis=axis, keepdim=keepdim)
