"""Sequence decoding ops: beam search, gather_tree, CRF, edit distance.

Reference parity: paddle/fluid/operators/ — beam_search_op.cc,
beam_search_decode_op.cc, gather_tree_op.cc, linear_chain_crf_op.{cc,h},
crf_decoding_op.cc, edit_distance_op.cc, plus the 2.x
paddle.text.viterbi_decode / ViterbiDecoder API.

TPU-first: the reference implements these as CPU-only LoD walkers (beam
search literally builds std::vector sentence trees,
beam_search_decode_op.h). Here every op is a fixed-shape ``lax.scan``:

* beam search keeps a dense [batch, beam] frontier and selects with one
  top-k over beam*vocab per step — no sorting of LoD levels;
* gather_tree / beam_search_decode is a reverse scan chasing parent
  pointers with ``take_along_axis``;
* linear-chain CRF runs the forward algorithm as a logsumexp scan over
  time (the reference's hand-rolled L1-normalised recursion,
  linear_chain_crf_op.h:172-224, is numerically the same thing), so the
  gradient falls out of autodiff instead of a hand-written backward
  (linear_chain_crf_grad);
* Viterbi is the same scan with max/argmax and a reverse backtrace scan;
* edit distance scans the Levenshtein DP row-by-row under vmap.

All ops take padded dense tensors + a ``length``/``lengths`` vector — the
TPU replacement for LoD (SURVEY §2.1 LoDTensor).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.primitive import Primitive
from ..framework.tensor import Tensor, unwrap


# -- gather_tree / beam_search_decode -----------------------------------------

def _gather_tree_fn(ids, parents):
    """[T, B, beam] ids/parents -> full beams (gather_tree_op.cc:61 doc)."""
    T, B, beam = ids.shape
    init = jnp.broadcast_to(jnp.arange(beam, dtype=parents.dtype), (B, beam))

    def step(cursor, xs):
        ids_t, par_t = xs
        out_t = jnp.take_along_axis(ids_t, cursor, axis=1)
        nxt = jnp.take_along_axis(par_t, cursor, axis=1)
        return nxt, out_t

    _, out_rev = lax.scan(step, init, (ids[::-1], parents[::-1]))
    return out_rev[::-1]


_gather_tree = Primitive("gather_tree", _gather_tree_fn, differentiable=False)


def gather_tree(ids, parents):
    """Backtrace full beam-search paths from per-step ids + parent indices.

    ids, parents: int tensors [max_time, batch, beam_size].
    """
    return _gather_tree(ids, parents)


# -- beam search ---------------------------------------------------------------

def _beam_search_step_fn(pre_ids, pre_scores, probs, beam_size=4, end_id=0,
                         is_accumulated=False):
    """One decode step (beam_search_op.cc).

    pre_ids     [B, beam] int   — tokens selected last step
    pre_scores  [B, beam] float — accumulated log-probs
    probs       [B, beam, V]    — this step's distribution per live beam
                                  (log-probs if is_accumulated else probs)
    Returns (ids [B, beam], scores [B, beam], parents [B, beam]).
    Finished beams (pre_id == end_id) only propose end_id at unchanged
    score, matching the reference's pruning of ended branches.
    """
    B, beam, V = probs.shape
    logp = probs if is_accumulated else jnp.log(jnp.maximum(probs, 1e-20))
    total = pre_scores[..., None] + logp            # [B, beam, V]
    finished = pre_ids == end_id                     # [B, beam]
    # a finished beam keeps exactly one candidate: end_id at its own score
    neg_inf = jnp.asarray(-jnp.inf, total.dtype)
    only_end = jnp.full((V,), False).at[end_id].set(True)
    total = jnp.where(
        finished[..., None],
        jnp.where(only_end, pre_scores[..., None], neg_inf),
        total)
    flat = total.reshape(B, beam * V)
    top_scores, top_idx = lax.top_k(flat, beam)      # [B, beam]
    parents = (top_idx // V).astype(pre_ids.dtype)
    tokens = (top_idx % V).astype(pre_ids.dtype)
    return tokens, top_scores, parents


_beam_search_step = Primitive("beam_search", _beam_search_step_fn,
                              multi_output=True, differentiable=False)


def beam_search_step(pre_ids, pre_scores, probs, beam_size=4, end_id=0,
                     is_accumulated=False):
    return _beam_search_step(pre_ids, pre_scores, probs,
                             beam_size=beam_size, end_id=end_id,
                             is_accumulated=is_accumulated)


def beam_parent_gather(x, parents):
    """Reorder beam-parallel state rows by the selected beam parents.

    ``x [B*K, ...]`` carries per-beam state (hidden state, KV cache);
    ``parents [B, K]`` are the parent beam indices ``beam_search_step``
    selected.  Row ``(b, k)`` of the result is row ``(b, parents[b, k])``
    of ``x`` — the reference's sequence_expand/LoD beam reorder collapsed
    to ONE gather (the incubate BeamSearchDecoder state reorder and the
    generate() beam KV-cache reorder share this exact semantics)."""
    B, K = parents.shape
    flat = (jnp.arange(B, dtype=parents.dtype)[:, None] * K
            + parents).reshape(-1)
    return jnp.take(x, flat, axis=0)


def _beam_search_decode_fn(step_ids, step_parents, step_scores, end_id=0):
    """Assemble final sentences from per-step selections
    (beam_search_decode_op.cc). Returns (sentences [T, B, beam],
    sentence_scores [B, beam]): full paths via gather_tree, each padded
    with end_id after the first end_id token."""
    paths = _gather_tree_fn(step_ids, step_parents)  # [T, B, beam]
    ended = jnp.cumsum((paths == end_id).astype(jnp.int32), axis=0) > 1
    sentences = jnp.where(ended, jnp.asarray(end_id, paths.dtype), paths)
    return sentences, step_scores[-1]


_beam_search_decode = Primitive("beam_search_decode", _beam_search_decode_fn,
                                multi_output=True, differentiable=False)


def beam_search_decode(step_ids, step_parents, step_scores, end_id=0):
    return _beam_search_decode(step_ids, step_parents, step_scores,
                               end_id=end_id)


def beam_search(init_ids, init_scores, step_fn, max_len, beam_size=4,
                end_id=0):
    """Whole-decode driver: repeatedly call ``step_fn(ids) -> probs`` and
    beam-select, then backtrace. Runs as a Python loop of jitted steps in
    eager mode (each step is one XLA program); the static path is
    jit.to_static over the caller's loop.

    init_ids [B, beam] int, init_scores [B, beam] float.
    step_fn: callable [B, beam] ids -> [B, beam, V] probs.
    Returns (sentences [T, B, beam], final_scores [B, beam]).
    """
    ids, scores = unwrap(init_ids), unwrap(init_scores)
    all_ids, all_parents, all_scores = [], [], []
    for _ in range(max_len):
        probs = unwrap(step_fn(Tensor(ids)))
        ids_t, scores_t, parents_t = _beam_search_step(
            ids, scores, probs, beam_size=beam_size, end_id=end_id)
        ids, scores = unwrap(ids_t), unwrap(scores_t)
        all_ids.append(ids)
        all_parents.append(unwrap(parents_t))
        all_scores.append(scores)
    return _beam_search_decode(
        jnp.stack(all_ids), jnp.stack(all_parents), jnp.stack(all_scores),
        end_id=end_id)


# -- linear-chain CRF ----------------------------------------------------------

def _crf_potentials(transition):
    """Split the reference transition layout (linear_chain_crf_op.h:183-186):
    row 0 = start weights a, row 1 = end weights b, rows 2: = pairwise w."""
    return transition[0], transition[1], transition[2:]


def _crf_log_norm(emission, transition, length):
    """log Z per sequence via forward-algorithm logsumexp scan.
    emission [B, T, C], transition [C+2, C], length [B] -> [B]."""
    a, b, w = _crf_potentials(transition)
    B, T, C = emission.shape
    alpha0 = a[None, :] + emission[:, 0, :]                      # [B, C]

    def step(alpha, xs):
        em_t, t = xs                                             # [B, C], ()
        nxt = jax.scipy.special.logsumexp(
            alpha[:, :, None] + w[None, :, :], axis=1) + em_t
        valid = (t < length)[:, None]
        alpha = jnp.where(valid, nxt, alpha)
        return alpha, None

    ts = jnp.arange(1, T)
    alpha, _ = lax.scan(step, alpha0,
                        (jnp.swapaxes(emission[:, 1:, :], 0, 1), ts))
    return jax.scipy.special.logsumexp(alpha + b[None, :], axis=1)


def _crf_gold_score(emission, transition, label, length):
    """Score of the labeled path (linear_chain_crf_op.h:214-222)."""
    a, b, w = _crf_potentials(transition)
    B, T, C = emission.shape
    t_idx = jnp.arange(T)[None, :]
    valid = t_idx < length[:, None]                              # [B, T]
    em = jnp.take_along_axis(emission, label[..., None], axis=2)[..., 0]
    em_score = jnp.sum(jnp.where(valid, em, 0.0), axis=1)
    trans = w[label[:, :-1], label[:, 1:]]                       # [B, T-1]
    trans_valid = (t_idx[:, 1:] < length[:, None])
    trans_score = jnp.sum(jnp.where(trans_valid, trans, 0.0), axis=1)
    last = jnp.take_along_axis(label, (length - 1)[:, None], axis=1)[:, 0]
    return a[label[:, 0]] + em_score + trans_score + b[last]


def _linear_chain_crf_fn(emission, transition, label, length):
    """Negative log-likelihood (the reference's LogLikelihood output is the
    cost trainers minimise, linear_chain_crf_op.h:191-222)."""
    ll = _crf_gold_score(emission, transition, label, length)
    return (_crf_log_norm(emission, transition, length) - ll)[:, None]


_linear_chain_crf = Primitive("linear_chain_crf", _linear_chain_crf_fn)


def linear_chain_crf(emission, transition, label, length):
    """CRF negative log-likelihood [B, 1].

    emission [B, T, C] unnormalised emission scores; transition [C+2, C]
    with rows (start, end, pairwise...); label [B, T] int; length [B] int.
    Gradients flow to emission and transition via autodiff (replacing the
    hand-written linear_chain_crf_grad kernel).
    """
    return _linear_chain_crf(emission, transition,
                             unwrap(label).astype(jnp.int32),
                             unwrap(length).astype(jnp.int32))


def _viterbi_fwd(emission, w, start, length):
    """Max-product forward scan; returns (final alpha [B,C], bp [T-1,B,C])."""
    B, T, C = emission.shape
    alpha0 = start[None, :] + emission[:, 0, :]

    def step(alpha, xs):
        em_t, t = xs
        cand = alpha[:, :, None] + w[None, :, :]                 # [B, C, C]
        best = jnp.max(cand, axis=1) + em_t
        bp = jnp.argmax(cand, axis=1).astype(jnp.int32)          # [B, C]
        valid = (t < length)[:, None]
        alpha = jnp.where(valid, best, alpha)
        bp = jnp.where(valid, bp, jnp.arange(C, dtype=jnp.int32)[None, :])
        return alpha, bp

    ts = jnp.arange(1, T)
    alpha, bps = lax.scan(step, alpha0,
                          (jnp.swapaxes(emission[:, 1:, :], 0, 1), ts))
    return alpha, bps


def _viterbi_backtrace(last_tag, bps):
    """Follow backpointers [T-1, B, C] from last_tag [B] -> path [B, T]."""
    def step(tag, bp_t):
        prev = jnp.take_along_axis(bp_t, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first, tags_rev = lax.scan(step, last_tag, bps[::-1])
    path = jnp.concatenate([first[:, None],
                            jnp.swapaxes(tags_rev[::-1], 0, 1)], axis=1)
    return path


def _crf_decoding_fn(emission, transition, length):
    """Viterbi path [B, T] int64 under the (C+2, C) transition layout
    (crf_decoding_op.cc). Positions beyond length are 0."""
    a, b, w = _crf_potentials(transition)
    B, T, C = emission.shape
    alpha, bps = _viterbi_fwd(emission, w, a, length)
    last = jnp.argmax(alpha + b[None, :], axis=1).astype(jnp.int32)
    path = _viterbi_backtrace(last, bps)
    valid = jnp.arange(T)[None, :] < length[:, None]
    return jnp.where(valid, path, 0).astype(jnp.int64)


_crf_decoding = Primitive("crf_decoding", _crf_decoding_fn,
                          differentiable=False)


def crf_decoding(emission, transition, length):
    return _crf_decoding(emission, transition,
                         unwrap(length).astype(jnp.int32))


def _viterbi_decode_fn(potentials, transitions, lengths,
                       include_bos_eos_tag=True):
    """2.x paddle.text.viterbi_decode: transitions [C, C]; when
    include_bos_eos_tag, tag C-2 is BOS (favoured into step 0) and C-1 is
    EOS (favoured out of the last step). Returns (scores [B], paths [B,T])."""
    B, T, C = potentials.shape
    if include_bos_eos_tag:
        start = transitions[C - 2]
        end = transitions[:, C - 1]
    else:
        start = jnp.zeros((C,), potentials.dtype)
        end = jnp.zeros((C,), potentials.dtype)
    alpha, bps = _viterbi_fwd(potentials, transitions, start, lengths)
    final = alpha + end[None, :]
    scores = jnp.max(final, axis=1)
    last = jnp.argmax(final, axis=1).astype(jnp.int32)
    path = _viterbi_backtrace(last, bps)
    valid = jnp.arange(T)[None, :] < lengths[:, None]
    return scores, jnp.where(valid, path, 0).astype(jnp.int64)


_viterbi_decode = Primitive("viterbi_decode", _viterbi_decode_fn,
                            multi_output=True, differentiable=False)


def viterbi_decode(potentials, transitions, lengths,
                   include_bos_eos_tag=True, name=None):
    return _viterbi_decode(potentials, transitions,
                           unwrap(lengths).astype(jnp.int32),
                           include_bos_eos_tag=include_bos_eos_tag)


# -- edit distance -------------------------------------------------------------

def _edit_distance_one(hyp, ref, hyp_len, ref_len):
    """Levenshtein DP for one padded pair; scan over hyp tokens carrying
    the DP row, then read dp[hyp_len][ref_len] (edit_distance_op.h)."""
    T2 = ref.shape[0]
    cols = jnp.arange(T2 + 1)
    row0 = cols.astype(jnp.float32)

    def step(prev_row, xs):
        h_tok, i = xs                                 # scalar, 1-based row
        sub = prev_row[:-1] + (ref != h_tok)          # [T2]
        dele = prev_row[1:] + 1.0

        def inner(left, xs2):
            s, d = xs2
            v = jnp.minimum(jnp.minimum(s, d), left + 1.0)
            return v, v

        _, rest = lax.scan(inner, i.astype(jnp.float32), (sub, dele))
        row = jnp.concatenate([i.astype(jnp.float32)[None], rest])
        return row, row

    _, rows = lax.scan(step, row0, (hyp, jnp.arange(1, hyp.shape[0] + 1)))
    dp = jnp.concatenate([row0[None], rows], axis=0)  # [T1+1, T2+1]
    return dp[hyp_len, ref_len]


def _edit_distance_fn(hyps, refs, hyp_lens, ref_lens, normalized=False):
    d = jax.vmap(_edit_distance_one)(hyps, refs, hyp_lens, ref_lens)
    if normalized:
        d = d / jnp.maximum(ref_lens.astype(d.dtype), 1.0)
    return d[:, None]


_edit_distance = Primitive("edit_distance", _edit_distance_fn,
                           differentiable=False)


def edit_distance(hyps, refs, hyp_lens, ref_lens, normalized=False,
                  name=None):
    """Batched Levenshtein distance [B, 1] over padded id sequences."""
    return _edit_distance(unwrap(hyps), unwrap(refs),
                          unwrap(hyp_lens).astype(jnp.int32),
                          unwrap(ref_lens).astype(jnp.int32),
                          normalized=normalized)
