"""Parked-session KV store: multi-turn conversations without re-prefill.

A multi-turn chat spends most of its life idle between turns.  Keeping
the conversation's ring-cache planes pinned in a decode slot for that
idle time wastes the scarcest resource (slot HBM); re-prefilling the
whole history on the next turn wastes the second scarcest (prefill
compute).  The session store takes the third road, the KVHandoff
discipline applied to conversations:

  * **park** — when a turn completes (or a replica drains), the slot
    loop pulls the row's valid columns ``[start, pos)`` to host RAM as a
    :class:`SessionSnapshot`: the token transcript, the resume payload
    (next-token logits for the plain loop, committed next token for the
    speculative loop), the remaining budget, and the raw KV planes
    (bf16 and int8+scales move as exact storage bytes).
  * **restore** — the next turn looks the session id up, pushes the
    snapshot's planes back into a joining row's validity window (the
    PR-7 relative-position invariance makes the columns bit-portable
    across slot rows and window shifts) and chunk-prefills only the NEW
    turn's tokens.  Decoding continues bit-identically to a full
    re-prefill of the whole history.
  * **spill** — with ``FLAGS_session_store_dir`` set, snapshots write to
    disk under the sha256-atomic-manifest discipline (PR 3/13):
    ``atomic_write_bytes`` + a manifest JSON recording the digest, so a
    torn write is detected (CheckpointCorrupt → treated as absent, the
    turn falls back to plain prefill) and a replica restarted after
    SIGKILL finds its parked sessions intact.  ``park_after_ms == 0``
    writes through at park time (the mode that survives SIGKILL);
    ``> 0`` keeps hot sessions in RAM and lazily spills the idle tail.

The store is the unit of migration too: ``export_bytes`` /
``import_bytes`` move a session between replicas through the Router
when the owner drains (cluster/router.py session affinity), and a
shared spill directory doubles as a zero-copy migration transport.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ..checkpoint.atomic import (CheckpointCorruptError, atomic_write_bytes,
                                 sha256_file)
from ..profiler.metrics import default_registry as _registry
from .cluster.handoff import deserialize_session, serialize_session

__all__ = ["SessionSnapshot", "SessionStore"]

SESSION_PARK = _registry().counter(
    "session_park_total",
    "Conversations parked into the session store (turn-complete parks "
    "and drain-time mid-generation parks alike).")
SESSION_RESTORE = _registry().counter(
    "session_restore_total",
    "Parked conversations restored into a decode slot (KV planes pushed "
    "back instead of re-prefilling the transcript).")
SESSION_STORE_BYTES = _registry().gauge(
    "session_store_bytes",
    "Bytes currently held by the session store (host-RAM snapshots plus "
    "disk-spilled blobs); the capacity side of the ≥1000-parked-sessions "
    "claim in bench.py prefix_cache.")


def _tree_nbytes(tree) -> int:
    if tree is None:
        return 0
    if isinstance(tree, (list, tuple)):
        return sum(_tree_nbytes(x) for x in tree)
    return int(np.asarray(tree).nbytes)


@dataclass
class SessionSnapshot:
    """One parked conversation, complete enough to resume bit-exactly.

    ``tokens`` is the committed transcript (prompt ++ emitted so far);
    ``planes`` the host KV pytree for columns ``[0, len(tokens))`` in
    relative position (None when the validity window was narrower than
    one chunk — the restore path then falls back to re-prefill, still
    bit-exact).  ``remaining > 0`` marks a mid-generation park (drain):
    the restore resumes decoding with that budget; ``remaining == 0`` is
    a completed turn awaiting a follow-up.  ``logits`` (plain loop) /
    ``cur`` (speculative loop) carry the resume payload the slot loop's
    activation would otherwise derive from a final prefill chunk.
    """

    session_id: str
    model: str
    tokens: List[int]
    remaining: int = 0
    emitted: List[int] = field(default_factory=list)
    planes: Any = None
    logits: Optional[np.ndarray] = None
    cur: Optional[int] = None
    kv_dtype: str = "bfloat16"
    spec: bool = False
    t_park: float = 0.0
    meta: dict = field(default_factory=dict)

    def nbytes(self) -> int:
        n = _tree_nbytes(self.planes)
        if self.logits is not None:
            n += int(np.asarray(self.logits).nbytes)
        return n + 8 * len(self.tokens)

    def to_payload(self) -> dict:
        return {
            "session_id": self.session_id, "model": self.model,
            "tokens": [int(t) for t in self.tokens],
            "remaining": int(self.remaining),
            "emitted": [int(t) for t in self.emitted],
            "cur": None if self.cur is None else int(self.cur),
            "kv_dtype": self.kv_dtype, "spec": bool(self.spec),
            "t_park": float(self.t_park), "meta": dict(self.meta),
            "planes": self.planes, "logits": self.logits,
        }

    @classmethod
    def from_payload(cls, p: dict) -> "SessionSnapshot":
        logits = p.get("logits")
        return cls(session_id=p["session_id"], model=p["model"],
                   tokens=[int(t) for t in p["tokens"]],
                   remaining=int(p.get("remaining", 0)),
                   emitted=[int(t) for t in p.get("emitted", ())],
                   planes=p.get("planes"),
                   logits=None if logits is None
                   else np.asarray(logits, np.float32),
                   cur=p.get("cur"),
                   kv_dtype=p.get("kv_dtype", "bfloat16"),
                   spec=bool(p.get("spec", False)),
                   t_park=float(p.get("t_park", 0.0)),
                   meta=dict(p.get("meta") or {}))

    def to_bytes(self) -> bytes:
        return serialize_session(self.to_payload())

    @classmethod
    def from_bytes(cls, blob: bytes) -> "SessionSnapshot":
        return cls.from_payload(deserialize_session(blob))


class SessionStore:
    """Host-RAM session snapshots with optional sha256-manifested disk
    spill.  Thread-safe; a snapshot has exactly one consumer (``take``
    removes it from RAM and disk — the restoring slot either completes
    the turn, which re-parks, or fails, which re-prefills next time)."""

    def __init__(self, spill_dir: str = "", park_after_ms: int = 0):
        self._dir = str(spill_dir or "")
        self._park_after_ms = int(park_after_ms)
        self._ram: Dict[str, SessionSnapshot] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._ram_bytes = 0                         # guarded-by: _lock
        self._disk_bytes: Dict[str, int] = {}       # guarded-by: _lock
        if self._dir:
            os.makedirs(self._dir, exist_ok=True)
            self._scan_disk()

    # -- naming / manifests --------------------------------------------------
    def _stem(self, sid: str) -> str:
        return hashlib.sha256(sid.encode()).hexdigest()[:32]

    def _paths(self, sid: str):
        stem = self._stem(sid)
        return (os.path.join(self._dir, stem + ".ptss"),
                os.path.join(self._dir, stem + ".json"))

    def _scan_disk(self):
        for name in os.listdir(self._dir):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._dir, name)) as f:
                    man = json.load(f)
                self._disk_bytes[man["session_id"]] = int(man["bytes"])
            except (OSError, ValueError, KeyError):
                continue
        self._publish_bytes()

    def _publish_bytes(self):
        SESSION_STORE_BYTES.set(self._ram_bytes
                                + sum(self._disk_bytes.values()))

    # -- spill ---------------------------------------------------------------
    def _spill_locked(self, sid: str, snap: SessionSnapshot,
                      drop_ram: bool) -> None:
        blob = snap.to_bytes()
        blob_path, man_path = self._paths(sid)
        digest = atomic_write_bytes(blob_path, blob)
        man = json.dumps({"session_id": sid,
                          "file": os.path.basename(blob_path),
                          "sha256": digest, "bytes": len(blob),
                          "t_park": snap.t_park}).encode()
        atomic_write_bytes(man_path, man)
        self._disk_bytes[sid] = len(blob)
        if drop_ram and sid in self._ram:
            self._ram_bytes -= self._ram.pop(sid).nbytes()

    def _drop_disk_locked(self, sid: str) -> None:
        blob_path, man_path = self._paths(sid)
        for p in (blob_path, man_path):
            try:
                os.unlink(p)
            except OSError:
                pass
        self._disk_bytes.pop(sid, None)

    def _load_disk_locked(self, sid: str) -> Optional[SessionSnapshot]:
        blob_path, man_path = self._paths(sid)
        try:
            with open(man_path) as f:
                man = json.load(f)
            if sha256_file(blob_path) != man["sha256"]:
                raise CheckpointCorruptError(
                    f"session spill {os.path.basename(blob_path)} does "
                    "not match its manifest digest")
            with open(blob_path, "rb") as f:
                return SessionSnapshot.from_bytes(f.read())
        except (OSError, ValueError, KeyError, CheckpointCorruptError):
            # a torn or missing spill is a cache miss, never a crash —
            # the turn falls back to a plain (bit-identical) re-prefill
            self._drop_disk_locked(sid)
            return None

    def _sweep_locked(self) -> None:
        if not self._dir or self._park_after_ms <= 0:
            return
        now = time.time()
        idle = [sid for sid, s in self._ram.items()
                if (now - s.t_park) * 1000.0 >= self._park_after_ms]
        for sid in idle:
            self._spill_locked(sid, self._ram[sid], drop_ram=True)

    # -- public API ----------------------------------------------------------
    def put(self, snap: SessionSnapshot) -> None:
        """Park a snapshot.  Write-through mode (``park_after_ms == 0``
        with a spill dir) persists immediately AND keeps the RAM copy
        hot — the disk blob is the SIGKILL survivor, the RAM copy the
        fast path; lazy mode spills older parks on each put."""
        with self._lock:
            sid = snap.session_id
            if sid in self._ram:
                self._ram_bytes -= self._ram[sid].nbytes()
            snap.t_park = snap.t_park or time.time()
            self._ram[sid] = snap
            self._ram_bytes += snap.nbytes()
            if self._dir and self._park_after_ms == 0:
                self._spill_locked(sid, snap, drop_ram=False)
            else:
                self._sweep_locked()
            self._publish_bytes()
        SESSION_PARK.inc()

    def take(self, sid: str) -> Optional[SessionSnapshot]:
        """Claim a parked session for restore (removes every copy)."""
        with self._lock:
            snap = self._ram.pop(sid, None)
            if snap is not None:
                self._ram_bytes -= snap.nbytes()
            elif self._dir:
                snap = self._load_disk_locked(sid)
            if self._dir:
                self._drop_disk_locked(sid)
            self._publish_bytes()
        if snap is not None:
            SESSION_RESTORE.inc()
        return snap

    def peek_ids(self) -> List[str]:
        with self._lock:
            return sorted(set(self._ram) | set(self._disk_bytes))

    def __contains__(self, sid: str) -> bool:
        with self._lock:
            return sid in self._ram or sid in self._disk_bytes

    def __len__(self) -> int:
        with self._lock:
            return len(set(self._ram) | set(self._disk_bytes))

    def nbytes(self) -> int:
        with self._lock:
            return self._ram_bytes + sum(self._disk_bytes.values())

    # -- migration transport -------------------------------------------------
    def export_bytes(self, sid: str) -> Optional[bytes]:
        """Move semantics: serialize-and-remove, for router-driven
        migration off a draining replica."""
        with self._lock:
            snap = self._ram.pop(sid, None)
            if snap is not None:
                self._ram_bytes -= snap.nbytes()
            elif self._dir:
                snap = self._load_disk_locked(sid)
            if self._dir:
                self._drop_disk_locked(sid)
            self._publish_bytes()
        return None if snap is None else snap.to_bytes()

    def import_bytes(self, blob: bytes) -> Optional[str]:
        """Ingest a migrated session.  Keep-newer: an already-parked
        copy with a later ``t_park`` wins (a stale migration replay must
        not clobber a fresher turn)."""
        snap = SessionSnapshot.from_bytes(blob)
        with self._lock:
            prev = self._ram.get(snap.session_id)
            if prev is not None and prev.t_park > snap.t_park:
                return None
        self.put(snap)
        return snap.session_id


# -- declared protocol: the parked-session state machine ---------------------
# put/take above are ``park``/``restore``; export_bytes/import_bytes the
# ``export``/``import`` migration legs (move semantics + the t_park
# keep-newer rule).  Verified by analysis/protocol: exactly one owner
# (RAM copy, wire blob or decode slot) at all times, and an import
# never clobbers a fresher park.
from ..analysis.protocol.spec import ProtocolSpec, register_protocol

SESSION_SPEC = register_protocol(ProtocolSpec(
    name="session",
    description="A multi-turn conversation across park, restore, and "
                "router-driven migration between replicas.",
    module=__name__,
    states=("active", "parked", "migrating", "restored"),
    initial="active",
    transitions=(
        ("active", "park", "parked"),
        ("parked", "restore", "restored"),
        ("restored", "park", "parked"),
        ("parked", "export", "migrating"),
        ("migrating", "import", "parked"),
    ),
    invariants=(
        ("one-owner",
         "a session never has two owners, and loses its last owner "
         "only through documented SIGKILL degradation"),
        ("no-stale-clobber",
         "an import never overwrites a fresher parked copy"),
    ),
))
