"""In-process multi-tenant Predictor server.

Reference parity: the deployment story of the reference stack is
``AnalysisPredictor`` + ``Clone()`` fan-out (analysis_predictor.h:82,214)
behind an application-owned server.  The TPU production shape adds what a
CPU/GPU server never had to think about: batch shape IS compile shape, so
the server owns batching — a request queue feeding an Orca-style
continuous batcher into a fixed bucket ladder, AOT warm-up of every
(model, bucket) executable before traffic is admitted, and a steady-state
zero-recompile invariant proven through the recompile ledger.

Layering:

  * :class:`ModelSpec` / :func:`export_for_serving` — the deploy
    artifact contract (shape-polymorphic export when the model allows,
    per-bucket sibling exports when it does not, ``.serving.json``
    manifest either way);
  * :class:`_ModelRuntime` — one served model: predictor(s), bucket
    ladder, per-bucket AOT executables, lint-gated admission, metrics;
  * :class:`_Worker` — serving thread with its own ``Predictor.clone()``
    (shared weights/executables, per-clone IO buffers) and an in-flight
    pipeline: H2D + dispatch of batch N+1 overlap execution of batch N;
  * :class:`Server` — registry + scheduler + workers + stats.

Everything is gated by ``FLAGS_serving_*``; the graph-lint admission gate
rides ``FLAGS_graph_lint`` (off-path = one branch, PR-5 discipline).
"""
from __future__ import annotations

import json
import os
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..framework import flags as _flags
from ..framework.enforce import (InvalidArgumentError, NotFoundError,
                                 PreconditionNotMetError, UnavailableError)
from ..profiler import ledger as _ledger
from ..profiler import span as _span
from ..profiler import tracing as _tracing
from ..profiler.metrics import LatencyWindow, RateMeter
from ..utils.monitor import stat_add


def _trace_batch(batch, name, t0, t1, **attrs):
    """Emit one ``name`` child span [t0, t1] onto every traced request of
    a batch (batch phases are shared work: each request's waterfall shows
    the phase it rode).  One branch per request when tracing is off."""
    for r in batch.requests:
        if r.trace is not None:
            _tracing.child(r.trace, name, t0, t1, **attrs)


def _first_trace(batch):
    """The batch's ambient span target: the first traced request (ledger
    compile events attach there while the batch executes)."""
    for r in batch.requests:
        if r.trace is not None:
            return r.trace
    return None
from .bucketing import BucketLadder, pad_to_bucket
from .decode import DecodeModelSpec, DecodeRequest, _DecodeRuntime
from .scheduler import Batch, Request, RequestQueue


# ---------------------------------------------------------------------------
# Deploy artifact contract
# ---------------------------------------------------------------------------

@dataclass
class ModelSpec:
    """One served model: a saved artifact + its serving shape contract.

    ``path`` is a jit.save prefix (``m`` for ``m.pdmodel``), a model dir,
    or a static save_inference_model dir.  ``buckets`` defaults to
    FLAGS_serving_buckets; ``input_specs`` (``[(shape, dtype), ...]`` with
    None leading dim) is required for executor-backed models whose feeds
    carry no shape metadata.
    """

    name: str
    path: str
    buckets: Optional[Sequence[int]] = None
    input_specs: Optional[Sequence[Tuple[Sequence[Optional[int]], Any]]] = None
    optim_cache_dir: Optional[str] = None


def _manifest_path(prefix: str) -> str:
    return prefix + ".serving.json"


def export_for_serving(layer, prefix: str, input_spec, buckets=None,
                       int8: bool = False) -> dict:
    """Export ``layer`` for the serving engine and write the
    ``<prefix>.serving.json`` manifest the registry discovers.

    Tries a shape-polymorphic export first (batch dim symbolic — ONE
    artifact serves every bucket); models that defeat shape polymorphism
    (e.g. an attention mask compare) fall back to one sibling export per
    bucket (``<prefix>.b<k>``), which is exactly the bucket ladder made
    durable.  With ``int8`` the artifacts are frozen int8 exports
    (quantization.save_int8_model) and the Predictor's
    FLAGS_use_int8_inference path picks them up unchanged.
    """
    from ..static import InputSpec

    ladder = BucketLadder.from_flag(buckets)

    def norm(spec):
        if isinstance(spec, InputSpec):
            return list(spec.shape), spec.dtype
        shape, dtype = spec
        return list(shape), dtype

    rests = [(list(shape[1:]), dtype) for shape, dtype in map(norm, input_spec)]

    def save(pfx, lead):
        spec = [InputSpec([lead] + rest, dtype=dtype)
                for rest, dtype in rests]
        if int8:
            from ..quantization import save_int8_model
            save_int8_model(layer, pfx, input_spec=spec)
        else:
            from .. import jit as _jit
            _jit.save(layer, pfx, input_spec=spec)

    def verify(pfx, bucket):
        # abstract lowering only (no backend compile): catches call-time
        # shape-refinement failures that a clean export can still hide
        import jax
        from .. import jit as _jit
        tl = _jit.load(pfx + (".int8" if int8 else ""))
        avals = [jax.ShapeDtypeStruct((bucket,) + tuple(r), np.dtype(d))
                 for r, d in rests]
        pavals = [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in tl._params]

        def call(*args):
            return tl._exported.call(*args)

        jax.jit(call).lower(*avals, *pavals)

    mode = "poly"
    try:
        save(prefix, None)
        verify(prefix, ladder.buckets[0])
    except Exception:
        mode = "per_bucket"
        for b in ladder:
            save(f"{prefix}.b{b}", b)
    manifest = {"mode": mode, "buckets": ladder.buckets, "int8": bool(int8),
                "input_specs": [[[None] + rest, str(np.dtype(dtype))]
                                for rest, dtype in rests]}
    with open(_manifest_path(prefix), "w") as f:
        json.dump(manifest, f)
    return manifest


# ---------------------------------------------------------------------------
# One served model
# ---------------------------------------------------------------------------

class _BucketExec:
    """AOT-compiled executable for one (model, bucket): positional device
    inputs + the model's device-resident params as explicit trailing args
    (explicit so every bucket shares ONE set of param buffers instead of
    baking per-bucket constant copies)."""

    __slots__ = ("compiled", "params_dev", "n_inputs")

    def __init__(self, compiled, params_dev, n_inputs):
        self.compiled = compiled
        self.params_dev = params_dev
        self.n_inputs = n_inputs

    def __call__(self, dev_inputs):
        return self.compiled(*dev_inputs, *self.params_dev)


class _ModelRuntime:
    """Loaded model + bucket executables + serving metrics."""

    def __init__(self, spec: ModelSpec):
        self.spec = spec
        self.name = spec.name
        self.site = f"serving:{spec.name}"
        self.ladder = BucketLadder.from_flag(spec.buckets)
        self.backend = None            # "jit" | "jit_per_bucket" | "executor"
        self.primary = None            # clone() target for workers
        self.predictors: Dict[int, Any] = {}   # per-bucket (per_bucket mode)
        self.executables: Dict[int, Optional[_BucketExec]] = {}
        self.templates: List[Tuple[Tuple[int, ...], Any]] = []  # (rest, dtype)
        self.n_outputs = 0
        self.admitted = False
        self.latency = LatencyWindow(int(_flags.flag("serving_metrics_window")))
        self.rate = RateMeter()
        self._mlock = threading.Lock()
        self.counters = {"requests": 0, "completed": 0,  # guarded-by: _mlock
                         "errors": 0,
                         "batches": 0, "rows": 0, "padded_rows": 0,
                         "steady_compiles": 0}

    def bump(self, **kw):
        with self._mlock:
            for k, v in kw.items():
                self.counters[k] += v

    # -- loading -------------------------------------------------------------
    def load(self):
        from ..inference import Config, Predictor

        def make_predictor(path):
            cfg = Config(path)
            if self.spec.optim_cache_dir:
                cfg.set_optim_cache_dir(self.spec.optim_cache_dir)
            return Predictor(cfg)

        manifest = None
        mpath = _manifest_path(self.spec.path)
        if os.path.isfile(mpath):
            with open(mpath) as f:
                manifest = json.load(f)
        if manifest is not None and manifest.get("mode") == "per_bucket":
            self.backend = "jit_per_bucket"
            buckets = [b for b in manifest["buckets"] if b in self.ladder]
            if not buckets:
                raise PreconditionNotMetError(
                    f"serving model {self.name!r}: per-bucket export "
                    f"{manifest['buckets']} shares no bucket with the "
                    f"requested ladder {self.ladder.buckets}")
            self.ladder = BucketLadder(buckets)
            for b in self.ladder:
                self.predictors[b] = make_predictor(f"{self.spec.path}.b{b}")
            self.primary = self.predictors[self.ladder.buckets[0]]
            self._init_templates_from_manifest(manifest)
        else:
            self.primary = make_predictor(self.spec.path)
            if self.primary._translated is not None:
                self.backend = "jit"
                self._init_templates_from_avals()
            else:
                self.backend = "executor"
                self._init_templates_from_spec(manifest)
        self.n_inputs = len(self.templates)

    def _init_templates_from_avals(self):
        tl = self.primary._translated
        avals = tl._exported.in_avals[:tl.num_inputs]
        fixed_batch = None
        for i, av in enumerate(avals):
            lead, rest = av.shape[0], av.shape[1:]
            if any(not isinstance(d, (int, np.integer)) for d in rest):
                raise PreconditionNotMetError(
                    f"serving model {self.name!r}: input {i} has a "
                    f"non-leading symbolic dim {av.shape} — only the "
                    "batch dim may be dynamic under bucketed serving")
            if isinstance(lead, (int, np.integer)):
                fixed_batch = int(lead)
            self.templates.append((tuple(int(d) for d in rest),
                                   np.dtype(av.dtype)))
        if fixed_batch is not None:
            # fixed-batch export with no per-bucket siblings: the ladder
            # collapses to the one batch the artifact can run
            self.ladder = BucketLadder([fixed_batch])

    def _init_templates_from_manifest(self, manifest):
        for shape, dtype in manifest["input_specs"]:
            self.templates.append((tuple(int(d) for d in shape[1:]),
                                   np.dtype(dtype)))

    def _init_templates_from_spec(self, manifest):
        specs = self.spec.input_specs
        if specs is None and manifest is not None:
            specs = [(s, d) for s, d in manifest.get("input_specs", [])]
        if specs is None:
            raise PreconditionNotMetError(
                f"serving model {self.name!r} is executor-backed (static "
                "save_inference_model dir): register it with "
                "ModelSpec(input_specs=[(shape, dtype), ...]) — feeds "
                "carry no shape metadata to bucket on")
        from ..static import InputSpec
        for s in specs:
            if isinstance(s, InputSpec):
                shape, dtype = list(s.shape), s.dtype
            else:
                shape, dtype = list(s[0]), s[1]
            self.templates.append((tuple(int(d) for d in shape[1:]),
                                   np.dtype(dtype)))
        if len(self.templates) != len(self.primary._feed_names):
            raise InvalidArgumentError(
                f"serving model {self.name!r}: {len(self.templates)} "
                f"input_specs for {len(self.primary._feed_names)} feeds "
                f"({self.primary._feed_names})")

    # -- abstract view (lint + AOT avals) ------------------------------------
    def _avals(self, bucket):
        import jax
        return [jax.ShapeDtypeStruct((bucket,) + rest, dt)
                for rest, dt in self.templates]

    def _abstract_callable(self, bucket):
        """(fn, avals) such that ``fn(*avals_like)`` is the served
        program at ``bucket`` — the lint and AOT-compile surface."""
        avals = self._avals(bucket)
        if self.backend in ("jit", "jit_per_bucket"):
            import jax
            tl = (self.primary if self.backend == "jit"
                  else self.predictors[bucket])._translated
            pavals = [jax.ShapeDtypeStruct(p.shape, p.dtype)
                      for p in tl._params]

            def call(*args):
                out = tl._exported.call(*args)
                return tuple(out) if isinstance(out, (list, tuple)) \
                    else (out,)

            return call, avals + pavals, tl
        # executor: rebuild the compiled replay closure abstractly so the
        # pass suite sees the full op graph, not an opaque call
        from ..static.executor import _collect_persistables, global_scope
        p = self.primary
        exe, program = p._exe, p._program
        feed_names = sorted(p._feed_names)
        persist = exe._persistable_names(program)
        written = [n for n in persist
                   if any(n in op.output_names
                          for op in program.global_block().ops)]
        replay = exe._build_replay(program, feed_names,
                                   list(p._fetch_names), persist, written)
        pvals = _collect_persistables(program, global_scope(), persist)
        order = [sorted(p._feed_names).index(n) for n in p._feed_names]

        def call(*feeds):
            ordered = [None] * len(feeds)
            for slot, i in zip(order, range(len(feeds))):
                ordered[slot] = feeds[i]
            return replay(ordered, pvals)[0]

        return call, avals, None

    # -- admission: lint gate ------------------------------------------------
    def lint_gate(self, bucket):
        """Run the analysis PassManager over this bucket's program in
        abstract-eval mode; ERROR findings refuse admission (stricter
        than warn mode's compile-path behavior: a server must not admit a
        model it knows is hazardous).  Gated by FLAGS_graph_lint — the
        off-path is this one branch."""
        from .. import analysis
        if not analysis.lint_enabled():
            return
        import jax
        fn, avals, _ = self._abstract_callable(bucket)
        try:
            closed = jax.make_jaxpr(fn)(*avals)
        except Exception as e:   # noqa: BLE001 — lint must not mask load bugs
            import warnings
            warnings.warn(
                f"serving warm-up lint for {self.name!r} b{bucket} could "
                f"not abstract-eval the program: {type(e).__name__}: {e}",
                analysis.GraphLintWarning, stacklevel=2)
            return
        ctx = analysis.LintContext(
            site=self.site, kind="serving", closed_jaxpr=closed,
            cache_key=self._bucket_key(bucket),
            arg_paths=[f"inputs[{i}]" for i in range(len(self.templates))])
        report = analysis.default_pass_manager().run(ctx)
        analysis.emit(report, mode="warn")     # gauges/JSONL/warnings
        errors = report.by_severity(analysis.Severity.ERROR)
        if errors:
            raise PreconditionNotMetError(
                f"serving refused to admit model {self.name!r}: graph "
                f"lint found {len(errors)} ERROR finding(s) at bucket "
                f"{bucket}:\n" + "\n".join("  " + str(d) for d in errors))

    def _bucket_key(self, bucket):
        return tuple([("arg:bucket", bucket)]
                     + [(f"arg:inputs[{i}]", (bucket,) + rest, str(dt))
                        for i, (rest, dt) in enumerate(self.templates)])

    def _artifact_identity(self, bucket):
        """Restart-stable program identity for the persistent executable
        cache: sha256 of the exported StableHLO bytes + param avals.
        The executable bakes no weights (params are trailing args), so
        every process serving the same ARTIFACT shares entries — which
        is exactly the one-host-compiles/N-hosts-load contract."""
        import hashlib
        tl = (self.primary if self.backend == "jit"
              else self.predictors[bucket])._translated
        blob = getattr(tl._exported, "mlir_module_serialized", None)
        if blob is None:
            blob = str(tl._exported.mlir_module()).encode()
        pav = tuple((tuple(int(d) for d in p.shape), str(p.dtype))
                    for p in tl._params)
        return ("serving_artifact",
                hashlib.sha256(blob).hexdigest(), pav)

    # -- warm-up: AOT compile every bucket -----------------------------------
    def warmup(self):
        import jax
        from ..jit import persistent_cache as _pcache
        for bucket in self.ladder:
            self.lint_gate(bucket)
            zeros = [np.zeros((bucket,) + rest, dt)
                     for rest, dt in self.templates]
            if self.backend == "executor":
                # the Executor's own cache + ledger own this compile
                # (including its persistent-cache seat)
                outs = self.primary.run(zeros)
                self.executables[bucket] = None
                self.n_outputs = len(outs)
                continue
            fn, avals, tl = self._abstract_callable(bucket)
            compiled, _loaded = _pcache.load_or_compile(
                lambda: jax.jit(fn).lower(*avals).compile(),
                site=self.site, kind="serving_aot",
                key=self._bucket_key(bucket),
                extra_key=self._artifact_identity(bucket),
                extra={"bucket": bucket, "model": self.name})
            params_dev = [jax.device_put(p) for p in tl._params]
            ex = _BucketExec(compiled, params_dev, len(self.templates))
            outs = ex([jax.device_put(z) for z in zeros])
            jax.block_until_ready(outs)
            self.executables[bucket] = ex
            self.n_outputs = len(outs)
        self.admitted = True

    # -- steady-state escape hatch -------------------------------------------
    def late_compile(self, bucket):
        """A bucket with no warm-up executable reached a worker.  Strict
        mode refuses; otherwise compile now, LEDGERED as a steady-state
        compile so the zero-recompile invariant visibly fails."""
        if bool(_flags.flag("serving_strict")):
            raise PreconditionNotMetError(
                f"serving model {self.name!r}: bucket {bucket} has no "
                "warm-up executable (FLAGS_serving_strict=True refuses "
                "steady-state compiles — extend the bucket ladder and "
                "re-warm instead)")
        import jax
        from ..jit import persistent_cache as _pcache
        fn, avals, tl = self._abstract_callable(bucket)
        # a cache hit still lands a ledger event at this site (kind
        # cache_load), so the zero-steady-state invariant stays visibly
        # violated — the load is merely cheaper than the compile
        compiled, _loaded = _pcache.load_or_compile(
            lambda: jax.jit(fn).lower(*avals).compile(),
            site=self.site, kind="serving_recompile",
            key=self._bucket_key(bucket),
            extra_key=self._artifact_identity(bucket),
            extra={"bucket": bucket, "model": self.name})
        ex = _BucketExec(compiled, [jax.device_put(p) for p in tl._params],
                         len(self.templates))
        stat_add("serving_steady_compiles")
        self.bump(steady_compiles=1)
        self.executables[bucket] = ex
        return ex

    def publish(self):
        self.latency.publish(f"serving_{self.name}")
        self.rate.publish(f"serving_{self.name}")


# ---------------------------------------------------------------------------
# Worker: clone-per-thread execution with async pipelining
# ---------------------------------------------------------------------------

class _Worker(threading.Thread):
    """One serving thread.  Owns a ``Predictor.clone()`` per model (the
    AnalysisPredictor::Clone seat: shared weights + compiled executables,
    per-clone feed/result buffers) and a bounded in-flight deque: a batch
    is dispatched (H2D + execute, both asynchronous) and only fenced when
    the pipeline is full or the queue runs dry — so host staging of batch
    N+1 overlaps device execution of batch N."""

    def __init__(self, server: "Server", idx: int):
        super().__init__(name=f"serving-worker-{idx}", daemon=True)
        self._server = server
        self.clones = {name: rt.primary.clone()
                       for name, rt in server._models.items()
                       if rt.primary is not None}
        self._depth = max(1, int(_flags.flag("serving_pipeline_depth")))
        self._inflight: deque = deque()

    # -- batch execution -----------------------------------------------------
    def _execute(self, batch: Batch):
        import jax
        rt = self._server._models[batch.model]
        if getattr(rt, "kind", None) == "decode":
            # prefill + scanned decode: one long device program — run it
            # synchronously (the scan IS the pipeline) and slice per
            # request, honoring each request's own max_new cap.  The
            # runtime emits prefill/decode spans (+ per-token events at
            # the scan boundary); an eventual escape-hatch compile lands
            # on the ambient request span
            with _tracing.use_span(_first_trace(batch)):
                toks = rt.execute(batch)
            now = time.perf_counter()
            t_r0 = time.monotonic()
            off = 0
            for r in batch.requests:
                # a parked session's future was already failed
                # (UnavailableError) by the slot loop's drain park —
                # don't double-resolve it
                if not r.future.done():
                    r.future.set_result(
                        [toks[off:off + r.rows, :r.max_new]])
                rt.latency.observe(now - r.t_enqueue)
                off += r.rows
            _trace_batch(batch, "reply", t_r0, time.monotonic())
            self._finish_traces(batch)
            rt.rate.add(len(batch.requests))
            rt.bump(completed=len(batch.requests), batches=1,
                    rows=batch.rows,
                    padded_rows=batch.bucket - batch.rows)
            stat_add("serving_completed_total", len(batch.requests))
            stat_add("serving_batches_total")
            stat_add("serving_padding_rows_total",
                     batch.bucket - batch.rows)
            rt.publish()
            return
        t_h0 = time.monotonic()
        host = [np.concatenate([r.inputs[i] for r in batch.requests], axis=0)
                if len(batch.requests) > 1 else batch.requests[0].inputs[i]
                for i in range(rt.n_inputs)]
        padded = pad_to_bucket(host, batch.rows, batch.bucket)
        ex = rt.executables.get(batch.bucket)
        if rt.backend == "executor":
            # synchronous path: the Executor fences internally; its cache
            # hit is the ledger proof that steady state never recompiles
            clone = self.clones[batch.model]
            t_e0 = time.monotonic()
            with _tracing.use_span(_first_trace(batch)):
                outs = clone.run(padded)
            t_e1 = time.monotonic()
            _trace_batch(batch, "h2d", t_h0, t_e0, bucket=batch.bucket)
            _trace_batch(batch, "execute", t_e0, t_e1,
                         bucket=batch.bucket, backend="executor")
            self._complete(batch, outs)
            return
        if ex is None:
            with _tracing.use_span(_first_trace(batch)):
                ex = rt.late_compile(batch.bucket)
        with _span("serving::h2d"):
            dev = [jax.device_put(a) for a in padded]
        t_e0 = time.monotonic()
        _trace_batch(batch, "h2d", t_h0, t_e0, bucket=batch.bucket)
        with _span("serving::dispatch"):
            outs = ex(dev)
        self._inflight.append((batch, outs, t_e0))
        while len(self._inflight) > self._depth:
            self._fence_oldest()

    def _fence_oldest(self):
        batch, outs, t_e0 = self._inflight.popleft()
        t_f0 = time.monotonic()
        with _span("serving::fence"):
            outs_np = [np.asarray(o) for o in outs]
        t_f1 = time.monotonic()
        # execute = dispatch → fence start (the async pipeline residency
        # window); d2h = the blocking fetch that fences it
        _trace_batch(batch, "execute", t_e0, t_f0, bucket=batch.bucket)
        _trace_batch(batch, "d2h", t_f0, t_f1)
        self._complete(batch, outs_np)

    def _drain(self):
        while self._inflight:
            self._fence_oldest()

    def _complete(self, batch: Batch, outs_np):
        rt = self._server._models[batch.model]
        now = time.perf_counter()
        t_r0 = time.monotonic()
        off = 0
        for r in batch.requests:
            r.future.set_result([o[off:off + r.rows] for o in outs_np])
            rt.latency.observe(now - r.t_enqueue)
            off += r.rows
        _trace_batch(batch, "reply", t_r0, time.monotonic())
        self._finish_traces(batch)
        rt.rate.add(len(batch.requests))
        rt.bump(completed=len(batch.requests), batches=1, rows=batch.rows,
                padded_rows=batch.bucket - batch.rows)
        stat_add("serving_completed_total", len(batch.requests))
        stat_add("serving_batches_total")
        stat_add("serving_padding_rows_total", batch.bucket - batch.rows)
        rt.publish()

    @staticmethod
    def _finish_traces(batch: Batch, error: Optional[str] = None):
        for r in batch.requests:
            if r.trace is not None:
                r.trace.set_attr(bucket=batch.bucket,
                                 batch_rows=batch.rows)
                if error is not None:
                    r.trace.set_attr(error=error)
                _tracing.finish(r.trace)

    def _fail(self, batch: Batch, exc: Exception):
        rt = self._server._models[batch.model]
        for r in batch.requests:
            if not r.future.done():
                r.future.set_exception(exc)
        self._finish_traces(batch, error=type(exc).__name__)
        rt.bump(errors=len(batch.requests))
        stat_add("serving_errors_total", len(batch.requests))

    # -- loop ----------------------------------------------------------------
    def run(self):
        q = self._server._dispatch_q
        while True:
            try:
                batch = q.get(timeout=0.02)
            except queue.Empty:
                # queue ran dry: latency beats pipelining — fence now
                self._drain()
                continue
            if batch is None:
                self._drain()
                return
            try:
                self._execute(batch)
            except Exception as e:   # noqa: BLE001 — fail the batch, not the server
                self._fail(batch, e)
            if q.empty():
                self._drain()


# ---------------------------------------------------------------------------
# The server
# ---------------------------------------------------------------------------

@dataclass
class ServingConfig:
    """Server-wide knobs; None fields fall back to FLAGS_serving_*."""

    workers: Optional[int] = None
    queue_capacity: Optional[int] = None
    batch_timeout_ms: Optional[float] = None
    pipeline_depth: Optional[int] = None
    buckets: Optional[Sequence[int]] = None
    optim_cache_dir: Optional[str] = None
    # model-artifact version stamp (rolling updates): published in the
    # replica's rendezvous entry and health report so the rollout
    # controller can tell old from new; None reads as "v0"
    version: Optional[str] = None


class Server:
    """In-process multi-tenant serving engine over inference.Predictor.

    Lifecycle::

        srv = serving.Server()
        srv.register("lenet", prefix, buckets=(1, 2, 4, 8))
        srv.start()                      # warm-up: lint + AOT every bucket
        fut = srv.submit("lenet", [x])   # x: [rows, ...] numpy
        outs = fut.result()              # per-request rows, padding removed
        srv.stop()

    ``start`` traces and compiles every (model, bucket) before a single
    request is admitted; after that the recompile ledger must stay silent
    — :meth:`assert_zero_steady_state_compiles` is the proof hook the
    bench and smoke tests call.
    """

    def __init__(self, config: Optional[ServingConfig] = None):
        self._config = config or ServingConfig()
        self._models: Dict[str, _ModelRuntime] = {}
        self._specs: List[ModelSpec] = []
        self._queue: Optional[RequestQueue] = None
        self._dispatch_q: Optional[queue.Queue] = None
        self._scheduler: Optional[threading.Thread] = None
        self._workers: List[_Worker] = []
        self._started = False
        self._stopped = False
        self._draining = False
        self._warmup_marks: Dict[str, int] = {}
        self._tenant_policies: Dict[str, dict] = {}
        self._session_store = None      # FLAGS_session_store, at start()

    def set_tenant_policy(self, tenant: str, max_pending: Optional[int]
                          = None, priority: Optional[int] = None) -> None:
        """Per-tenant admission knobs (quota + priority class); callable
        before start() — the policy is applied when the queue exists."""
        pol = self._tenant_policies.setdefault(str(tenant), {})
        if max_pending is not None:
            pol["max_pending"] = int(max_pending)
        if priority is not None:
            pol["priority"] = int(priority)
        if self._queue is not None:
            self._queue.set_tenant_policy(tenant, **pol)

    @property
    def version(self) -> str:
        """The served artifact version ("v0" unless configured)."""
        return str(self._config.version or "v0")

    @property
    def draining(self) -> bool:
        return self._draining

    # -- registry ------------------------------------------------------------
    def register(self, spec_or_name, path: Optional[str] = None,
                 **kw) -> ModelSpec:
        """Register a model (a ModelSpec, or name + path + ModelSpec
        kwargs).  Must happen before start()."""
        if self._started:
            raise PreconditionNotMetError(
                "register() after start(): the warm-up contract admits "
                "no un-warmed model — build a new Server")
        if isinstance(spec_or_name, ModelSpec) \
                or hasattr(spec_or_name, "make_runtime"):
            # ModelSpec, or any spec that builds its own runtime (the
            # cluster ShardedModelSpec seat) — duck-typed so server.py
            # never imports the cluster package
            spec = spec_or_name
        else:
            if path is None:
                raise InvalidArgumentError("register(name, path, ...)")
            kw.setdefault("buckets", self._config.buckets)
            kw.setdefault("optim_cache_dir", self._config.optim_cache_dir)
            spec = ModelSpec(name=str(spec_or_name), path=path, **kw)
        if spec.name in {s.name for s in self._specs}:
            raise InvalidArgumentError(
                f"model {spec.name!r} is already registered")
        self._specs.append(spec)
        return spec

    def register_decode(self, spec_or_name, layer=None, **kw
                        ) -> DecodeModelSpec:
        """Register an autoregressive-decode model (a DecodeModelSpec, or
        name + live layer + DecodeModelSpec kwargs).  Warm-up compiles
        the full (batch-bucket × prompt-bucket) prefill set and the
        (batch-bucket × cache-bucket) decode set; traffic goes through
        :meth:`submit_decode`."""
        if self._started:
            raise PreconditionNotMetError(
                "register_decode() after start(): the warm-up contract "
                "admits no un-warmed model — build a new Server")
        if isinstance(spec_or_name, DecodeModelSpec):
            spec = spec_or_name
        else:
            if layer is None:
                raise InvalidArgumentError(
                    "register_decode(name, layer, ...)")
            kw.setdefault("batch_buckets", self._config.buckets)
            spec = DecodeModelSpec(name=str(spec_or_name), layer=layer,
                                   **kw)
        if spec.name in {s.name for s in self._specs}:
            raise InvalidArgumentError(
                f"model {spec.name!r} is already registered")
        self._specs.append(spec)
        return spec

    def models(self) -> List[str]:
        return [s.name for s in self._specs]

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Server":
        """Load + lint + AOT-warm every registered model, snapshot the
        ledger, then open the doors (scheduler + worker threads)."""
        if self._started:
            raise PreconditionNotMetError("Server already started")
        if not self._specs:
            raise PreconditionNotMetError("no models registered")
        if bool(_flags.flag("session_store")):
            # one shared store per process: every slot-mode decode model
            # parks into and restores from it (cluster migration moves
            # sessions between these stores through the router)
            from .sessions import SessionStore
            self._session_store = SessionStore(
                spill_dir=str(_flags.flag("session_store_dir")),
                park_after_ms=int(_flags.flag("session_park_after_ms")))
        for spec in self._specs:
            if hasattr(spec, "make_runtime"):
                rt = spec.make_runtime()
            elif isinstance(spec, DecodeModelSpec):
                rt = _DecodeRuntime(spec)
            else:
                rt = _ModelRuntime(spec)
            if self._session_store is not None \
                    and hasattr(rt, "session_store"):
                rt.session_store = self._session_store
            rt.load()
            rt.warmup()
            rt.rate.reset()              # QPS clock starts with traffic
            self._models[spec.name] = rt
        # the zero-recompile invariant is measured from here: any compile
        # event at an owned site after this mark is a steady-state compile
        for site in self._owned_sites():
            self._warmup_marks[site] = len(_ledger.compile_events(site))
        n_workers = self._config.workers or int(_flags.flag("serving_workers"))
        cap = self._config.queue_capacity \
            or int(_flags.flag("serving_queue_capacity"))
        depth = self._config.pipeline_depth \
            or int(_flags.flag("serving_pipeline_depth"))
        self._queue = RequestQueue(cap)
        for tenant, pol in self._tenant_policies.items():
            self._queue.set_tenant_policy(tenant, **pol)
        self._dispatch_q = queue.Queue(maxsize=max(1, n_workers * depth))
        self._workers = [_Worker(self, i) for i in range(n_workers)]
        for w in self._workers:
            w.start()
        self._scheduler = threading.Thread(
            target=self._schedule_loop, name="serving-scheduler", daemon=True)
        self._scheduler.start()
        self._started = True
        return self

    def _owned_sites(self) -> List[str]:
        sites = []
        for rt in self._models.values():
            sites.append(rt.site)
            if rt.backend == "executor":
                sites.append(f"executor:{rt.primary._program._uid}")
        return sites

    def _schedule_loop(self):
        timeout_ms = self._config.batch_timeout_ms
        if timeout_ms is None:
            timeout_ms = float(_flags.flag("serving_batch_timeout_ms"))
        while True:
            batch = self._queue.next_batch(
                lambda m: self._models[m].ladder.max_rows,
                lambda m, rows: self._models[m].ladder.bucket_for(rows),
                timeout_ms / 1e3)
            if batch is None:
                break
            self._dispatch_q.put(batch)      # bounded: backpressure makes
        for _ in self._workers:              # queued requests batch bigger
            self._dispatch_q.put(None)

    def stop(self, drain: bool = True) -> None:
        """Stop accepting traffic; ``drain`` serves what is queued first,
        otherwise pending futures fail with UnavailableError."""
        if not self._started or self._stopped:
            self._stopped = True
            return
        if not drain:
            for r in self._queue.drain():
                if not r.future.done():
                    r.future.set_exception(UnavailableError(
                        "server stopped before this request was served"))
        self._queue.close()
        self._scheduler.join(timeout=30)
        for w in self._workers:
            w.join(timeout=30)
        for rt in self._models.values():
            close = getattr(rt, "close", None)
            if close is not None:
                close()
        self._stopped = True

    # -- graceful drain (cluster lifecycle) ----------------------------------
    def request_drain(self) -> None:
        """Flip to drain mode: new submissions bounce with
        UnavailableError (retry_after = the staleness window, so a
        router redirects and backs this replica off) while everything
        already admitted — queued batches and slot-loop rows — runs to
        completion.  Idempotent; the server keeps serving in-flight
        work until :meth:`drain` reports it empty."""
        self._draining = True

    def _reject_if_draining(self) -> None:
        if self._draining:
            raise UnavailableError(
                "replica is draining (graceful retirement in progress)",
                retry_after_s=float(_flags.flag("router_stale_after_s")))

    def pending_requests(self) -> int:
        """Requests admitted but not yet completed or failed, summed
        over models — slot-loop rows count until their batch future
        resolves, so 0 means every admitted token was served."""
        n = 0
        for rt in self._models.values():
            with rt._mlock:
                c = rt.counters
                n += c["requests"] - c["completed"] - c["errors"]
        return n

    def drain(self, timeout_s: Optional[float] = None) -> dict:
        """Graceful drain: stop admitting (see :meth:`request_drain`),
        then wait until the queue is empty and every admitted request
        has resolved — in-flight batches finish, slot-loop rows retire
        at token boundaries.  Returns a report dict; ``drained`` False
        means the timeout expired with work still pending (the caller's
        escalation path — evict — takes over)."""
        if timeout_s is None:
            timeout_s = float(_flags.flag("drain_timeout_s"))
        t0 = time.monotonic()
        self.request_drain()
        if not self._started or self._stopped:
            return {"drained": True, "pending": 0, "queue_depth": 0,
                    "waited_s": 0.0, "parked_sessions": 0}
        # session-stateful drain (FLAGS_session_store): live slot-loop
        # conversations PARK to the store instead of running their full
        # token budget out — their futures fail retryably (Unavailable)
        # and the router redispatches the turn to a surviving replica,
        # which restores the snapshot and resumes bit-identically
        parked = self.park_sessions(timeout_s=float(timeout_s))
        deadline = t0 + max(0.0, float(timeout_s))
        while True:
            pending = self.pending_requests()
            qdepth = self._queue.depth() if self._queue else 0
            if pending <= 0 and qdepth == 0:
                return {"drained": True, "pending": 0, "queue_depth": 0,
                        "waited_s": round(time.monotonic() - t0, 3),
                        "parked_sessions": parked}
            if time.monotonic() >= deadline:
                return {"drained": False, "pending": int(pending),
                        "queue_depth": int(qdepth),
                        "waited_s": round(time.monotonic() - t0, 3),
                        "parked_sessions": parked}
            time.sleep(min(0.02, max(0.001, timeout_s / 50.0)))

    def park_sessions(self, timeout_s: float = 30.0) -> int:
        """Park every live slot-loop conversation into the session store
        (no-op without FLAGS_session_store); returns sessions parked."""
        if self._session_store is None:
            return 0
        n = 0
        for rt in self._models.values():
            loop = getattr(rt, "_loop", None)
            if loop is not None:
                n += loop.park_sessions(timeout=timeout_s)
        return n

    @property
    def session_store(self):
        """The process-wide session store (None without
        FLAGS_session_store) — the cluster replica's migration seat."""
        return self._session_store

    def __enter__(self):
        if not self._started:
            self.start()
        return self

    def __exit__(self, *exc):
        self.stop(drain=not any(exc))

    # -- traffic -------------------------------------------------------------
    def _runtime(self, model: str) -> _ModelRuntime:
        rt = self._models.get(model)
        if rt is None or not rt.admitted:
            raise NotFoundError(
                f"model {model!r} is not admitted (registered: "
                f"{self.models()})")
        return rt

    def _put(self, rt, req):
        """Enqueue with honest rejection accounting: a backpressure
        rejection (UnavailableError, carrying the queue's machine-
        readable retry-after hint) closes the request's trace span and
        counts an error before propagating — the router reads the hint
        and backs off this replica instead of evicting it."""
        try:
            self._queue.put(req, timeout=req._put_timeout)
        except UnavailableError as e:
            if req.trace is not None:
                req.trace.set_attr(error="UnavailableError",
                                   retry_after_s=getattr(
                                       e, "retry_after_s", None))
                _tracing.finish(req.trace)
            rt.bump(errors=1)
            stat_add("serving_errors_total")
            raise

    def submit(self, model: str, inputs, timeout: Optional[float] = 5.0,
               trace_id: Optional[str] = None, tenant: str = "default",
               priority: Optional[int] = None) -> Future:
        """Enqueue one request of ``rows`` examples (rows = leading dim);
        returns a Future resolving to per-output numpy arrays with
        exactly ``rows`` rows (padding never leaks).  Blocks up to
        ``timeout`` under backpressure, then raises UnavailableError
        carrying the queue's retry-after hint.  ``trace_id`` joins this
        request to a caller-owned trace (the router's cross-process
        propagation seat)."""
        if not self._started or self._stopped:
            raise PreconditionNotMetError(
                "Server is not serving (start() it / already stopped)")
        self._reject_if_draining()
        rt = self._runtime(model)
        if getattr(rt, "kind", None) == "decode":
            raise InvalidArgumentError(
                f"model {model!r} is a decode model: use "
                "submit_decode(model, prompts, max_new_tokens=...)")
        if len(inputs) != rt.n_inputs:
            raise InvalidArgumentError(
                f"model {model!r} takes {rt.n_inputs} inputs, got "
                f"{len(inputs)}")
        arrs, rows = [], None
        for i, (a, (rest, dt)) in enumerate(zip(inputs, rt.templates)):
            a = np.asarray(a, dtype=dt)      # dtype pinned: signature-stable
            if a.ndim != len(rest) + 1 or tuple(a.shape[1:]) != rest:
                raise InvalidArgumentError(
                    f"model {model!r} input {i}: got shape "
                    f"{list(a.shape)}, served shape is [rows, "
                    f"{', '.join(map(str, rest))}]")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise InvalidArgumentError(
                    f"model {model!r}: inconsistent request rows "
                    f"({rows} vs {a.shape[0]} at input {i})")
            arrs.append(a)
        if rows == 0:
            raise InvalidArgumentError("empty request (0 rows)")
        rt.ladder.bucket_for(rows)           # raises OutOfRange early
        req = Request(model=model, inputs=tuple(arrs), rows=rows,
                      tenant=tenant, priority=priority,
                      trace=_tracing.start_span(
                          "request", trace_id=trace_id, model=model,
                          rows=rows, kind="dense"))
        rt.bump(requests=1)
        stat_add("serving_requests_total")
        req._put_timeout = timeout
        self._put(rt, req)
        return req.future

    def run(self, model: str, inputs, timeout: Optional[float] = 60.0):
        """Synchronous convenience: submit + wait."""
        return self.submit(model, inputs).result(timeout=timeout)

    def submit_decode(self, model: str, prompts,
                      max_new_tokens: Optional[int] = None,
                      timeout: Optional[float] = 5.0,
                      trace_id: Optional[str] = None,
                      tenant: str = "default",
                      priority: Optional[int] = None,
                      session_id: Optional[str] = None) -> Future:
        """Enqueue one decode request: ``prompts`` is a list of 1-D int
        token arrays (variable lengths — they left-pad to the prompt
        bucket at execution).  Resolves to ``[ids]`` where ids is an
        int32 array [len(prompts), max_new_tokens] of generated tokens.
        Rows of one request ride one batch; the continuous batcher packs
        concurrent requests exactly like dense traffic.

        ``session_id`` (FLAGS_session_store) names the conversation:
        single-prompt requests only, with ``prompts[0]`` the FULL
        transcript so far (history + new turn) — the slot loop restores
        the parked KV planes and prefills only the uncached suffix."""
        if not self._started or self._stopped:
            raise PreconditionNotMetError(
                "Server is not serving (start() it / already stopped)")
        self._reject_if_draining()
        rt = self._runtime(model)
        if getattr(rt, "kind", None) != "decode":
            raise InvalidArgumentError(
                f"model {model!r} is not a decode model: use submit()")
        if getattr(rt, "role", "both") != "both":
            raise PreconditionNotMetError(
                f"model {model!r}: this replica serves the "
                f"{rt.role!r} pool only (FLAGS_serving_role) — full "
                "decode requests need role 'both', or route "
                "prefill_handoff → decode_from_handoff across the pools")
        arrs, max_new = rt.validate(list(prompts), max_new_tokens)
        if session_id is not None and len(arrs) != 1:
            raise InvalidArgumentError(
                f"session_id={session_id!r} requires exactly one prompt "
                f"(one conversation = one row), got {len(arrs)}")
        rt.ladder.bucket_for(len(arrs))      # raises OutOfRange early
        req = DecodeRequest(model=model, prompts=arrs, rows=len(arrs),
                            max_new=max_new,
                            tenant=tenant, priority=priority,
                            session_id=None if session_id is None
                            else str(session_id),
                            trace=_tracing.start_span(
                                "request", trace_id=trace_id, model=model,
                                rows=len(arrs), kind="decode",
                                max_new=max_new))
        rt.bump(requests=1)
        stat_add("serving_requests_total")
        req._put_timeout = timeout
        self._put(rt, req)
        return req.future

    def run_decode(self, model: str, prompts,
                   max_new_tokens: Optional[int] = None,
                   timeout: Optional[float] = 60.0):
        """Synchronous convenience: submit_decode + wait."""
        return self.submit_decode(model, prompts, max_new_tokens) \
            .result(timeout=timeout)

    # -- disaggregated pools (serving/cluster) -------------------------------
    def _decode_runtime(self, model: str):
        rt = self._runtime(model)
        if getattr(rt, "kind", None) != "decode":
            raise InvalidArgumentError(
                f"model {model!r} is not a decode model — KV handoff is "
                "a prefill/decode-pool operation")
        return rt

    def prefill_handoff(self, model: str, prompts,
                        max_new_tokens: Optional[int] = None):
        """Prefill-pool entry: run ONLY the prefill phase and return the
        KVHandoff (device planes + logits + validity metadata) a decode
        pool resumes from — serialize with ``.to_bytes()`` to cross a
        process boundary."""
        if not self._started or self._stopped:
            raise PreconditionNotMetError(
                "Server is not serving (start() it / already stopped)")
        self._reject_if_draining()
        return self._decode_runtime(model).prefill_handoff(
            prompts, max_new_tokens)

    def decode_from_handoff(self, model: str, handoff):
        """Decode-pool entry: resume generation from a prefill pool's
        handoff (a KVHandoff, or its serialized bytes); returns ids
        [rows, max_new] bit-identical to the in-process generate()."""
        if not self._started or self._stopped:
            raise PreconditionNotMetError(
                "Server is not serving (start() it / already stopped)")
        self._reject_if_draining()
        if isinstance(handoff, (bytes, bytearray, memoryview)):
            from .cluster.handoff import deserialize_kv
            handoff = deserialize_kv(bytes(handoff))
        return self._decode_runtime(model).decode_from_handoff(handoff)

    # -- observability -------------------------------------------------------
    def compile_events_since_warmup(self) -> List[dict]:
        """Ledger compile events at server-owned sites recorded AFTER the
        warm-up mark — the steady-state window must keep this empty."""
        out = []
        for site, mark in self._warmup_marks.items():
            out.extend(_ledger.compile_events(site)[mark:])
        return out

    def assert_zero_steady_state_recompiles(self) -> None:
        evs = self.compile_events_since_warmup()
        if evs:
            raise PreconditionNotMetError(
                f"steady-state recompile(s) detected ({len(evs)}): "
                + "; ".join(f"{e['site']} {e.get('kind')} {e['diff']}"
                            for e in evs[:4]))

    def stats(self, model: Optional[str] = None) -> dict:
        """Serving health snapshot (the PERF.md serving schema): per-model
        qps / p50 / p99 / padding / steady_compiles, or all models."""
        if model is None:
            return {name: self.stats(name) for name in self._models}
        rt = self._runtime(model)
        with rt._mlock:
            c = dict(rt.counters)
        lat = rt.latency.snapshot()
        rows = max(1, c["rows"])
        return {
            "model": model, "backend": rt.backend,
            "buckets": rt.ladder.buckets,
            "requests": c["requests"], "completed": c["completed"],
            "errors": c["errors"], "batches": c["batches"],
            "qps": round(rt.rate.rate(), 2),
            "p50_ms": lat["p50_ms"], "p99_ms": lat["p99_ms"],
            "max_ms": lat["max_ms"],
            "avg_batch_rows": round(c["rows"] / max(1, c["batches"]), 2),
            "padding_ratio": round(c["padded_rows"] /
                                   (rows + c["padded_rows"]), 4),
            "queue_depth": self._queue.depth() if self._queue else 0,
            "steady_compiles": c["steady_compiles"],
            **({"slot_loop": rt._loop.stats()}
               if getattr(rt, "_loop", None) is not None else {}),
        }

    def signals(self) -> dict:
        """This process's autoscaling-signal snapshot (the per-replica
        leg of cluster/obs.ClusterSignals): queue depth + retry-after
        EWMA from the RequestQueue, average batch occupancy and the
        steady-state recompile count summed over models."""
        out = {"queue_depth": 0, "retry_after_s": 0.0,
               "drain_rate_rps": 0.0}
        if self._queue is not None:
            out.update(self._queue.signals())
        rows = batches = steady = 0
        for rt in self._models.values():
            c = rt.counters
            rows += c.get("rows", 0)
            batches += c.get("batches", 0)
            steady += c.get("steady_compiles", 0)
        out["batch_occupancy_rows"] = round(rows / batches, 3) \
            if batches else 0.0
        out["steady_compiles"] = steady
        # token-level decode-slot accounting (FLAGS_decode_slots):
        # occupancy is the max over slot-mode decode models, the
        # join/retire counters sum — absent entirely on the scanned path
        slot = [s for s in (getattr(rt, "slot_signals", lambda: None)()
                            for rt in self._models.values())
                if s is not None]
        if slot:
            out["decode_slot_occupancy_ratio"] = max(
                s["decode_slot_occupancy_ratio"] for s in slot)
            out["slots_joined_total"] = sum(
                s["slots_joined_total"] for s in slot)
            out["slots_retired_total"] = sum(
                s["slots_retired_total"] for s in slot)
            for k in ("prefix_cache_blocks", "prefix_cache_bytes"):
                if any(k in s for s in slot):
                    out[k] = sum(s.get(k, 0) for s in slot)
        if self._session_store is not None:
            out["sessions_parked"] = len(self._session_store)
            out["session_store_bytes"] = self._session_store.nbytes()
        out["models"] = self.models()
        out["version"] = self.version
        out["draining"] = self._draining
        return out


def create_server(config: Optional[ServingConfig] = None) -> Server:
    """Factory mirroring inference.create_predictor."""
    return Server(config)
