"""Batch buckets: the static-shape discipline of continuous batching.

TPU-first: XLA compiles one executable per input shape, so a server that
executes whatever batch size arrives recompiles under traffic — the one
thing steady-state serving must never do (the recompile ledger and the
graph-lint recompile-hazard pass exist to prove it).  The Orca-style
answer is a fixed ladder of batch buckets: every formed batch pads up to
the smallest bucket that holds it, warm-up compiles every bucket once,
and steady state replays those executables forever.

The ladder defaults to FLAGS_serving_buckets (``"1,2,4,8,16,32,64"``);
geometric spacing bounds padding waste at <2x worst case and keeps the
warm-up compile count logarithmic in the max batch.
"""
from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ..framework import flags as _flags
from ..framework.enforce import InvalidArgumentError, OutOfRangeError


class BucketLadder:
    """Sorted, de-duplicated set of batch buckets."""

    def __init__(self, buckets: Sequence[int]):
        bs = sorted({int(b) for b in buckets})
        if not bs or bs[0] <= 0:
            raise InvalidArgumentError(
                f"bucket ladder needs positive sizes, got {list(buckets)}")
        self._buckets = bs

    @classmethod
    def from_flag(cls, spec=None) -> "BucketLadder":
        """Parse ``spec`` (or FLAGS_serving_buckets) — "1,2,4,8"-style."""
        raw = spec if spec is not None else _flags.flag("serving_buckets")
        if isinstance(raw, (list, tuple)):
            return cls(raw)
        return cls([int(b) for b in str(raw).split(",") if b.strip()])

    @property
    def buckets(self) -> List[int]:
        return list(self._buckets)

    @property
    def max_rows(self) -> int:
        return self._buckets[-1]

    def bucket_for(self, rows: int) -> int:
        """Smallest bucket holding ``rows``; OutOfRange past the ladder."""
        for b in self._buckets:
            if rows <= b:
                return b
        raise OutOfRangeError(
            f"{rows} rows exceed the largest serving bucket "
            f"{self._buckets[-1]} (ladder {self._buckets})")

    def __iter__(self):
        return iter(self._buckets)

    def __len__(self):
        return len(self._buckets)

    def __contains__(self, b):
        return int(b) in self._buckets

    def __repr__(self):
        return f"BucketLadder({self._buckets})"


def pad_to_bucket(arrs: Sequence[np.ndarray], rows: int,
                  bucket: int) -> List[np.ndarray]:
    """Pad each array's leading dim from ``rows`` up to ``bucket`` with
    zeros (host-side, before the H2D copy).  Zero padding is safe for the
    per-example inference contract: padded rows are sliced away before
    results are returned, and no served output row depends on another
    row's input."""
    if bucket == rows:
        return list(arrs)
    out = []
    for a in arrs:
        pad = np.zeros((bucket - rows,) + a.shape[1:], dtype=a.dtype)
        out.append(np.concatenate([a, pad], axis=0))
    return out
