"""paddle_tpu.serving — production serving engine over inference.Predictor.

Reference parity: the reference deploys through AnalysisPredictor +
``Clone()`` fan-out (analysis_predictor.h:82,214) and leaves batching,
warm-up and multi-model management to the application.  On TPU those are
not application details — batch shape is compile shape — so this package
owns them:

  * **continuous batching into bucketed static shapes** (scheduler.py +
    bucketing.py): pending requests pack FIFO into the smallest ladder
    bucket that holds them and pad up; batch size adapts to load with no
    per-request recompiles (Orca-style, the TPU-idiomatic form);
  * **AOT-cache warm-up** (server.py): ``start()`` lints (graph-lint
    admission gate, FLAGS_graph_lint) and compiles every (model, bucket)
    executable before the first request is admitted, each compile
    recorded in the recompile ledger; after the warm-up mark the ledger
    must stay silent — ``assert_zero_steady_state_recompiles()`` proves
    the steady-state invariant;
  * **async host↔device pipelining**: workers keep up to
    FLAGS_serving_pipeline_depth batches in flight, so H2D + dispatch of
    batch N+1 overlap execution of batch N;
  * **clone-per-worker concurrency**: every worker thread serves through
    its own ``Predictor.clone()`` — shared weights and executables,
    per-clone IO buffers.

Gates: ``FLAGS_serving_*`` (framework/flags.py).  CLI: ``tools/serve.py``.
Bench: ``bench.py``'s ``serving`` block (sustained QPS + p50/p99 SLOs).
"""
from __future__ import annotations

from .bucketing import BucketLadder, pad_to_bucket  # noqa: F401
from .decode import DecodeModelSpec, DecodeRequest  # noqa: F401
from .scheduler import Batch, Request, RequestQueue, pack_fifo  # noqa: F401
from .server import (ModelSpec, Server, ServingConfig,  # noqa: F401
                     create_server, export_for_serving)
from . import cluster  # noqa: F401  (multi-host disaggregated serving)

__all__ = [
    "BucketLadder", "pad_to_bucket", "Batch", "Request", "RequestQueue",
    "pack_fifo", "ModelSpec", "Server", "ServingConfig", "create_server",
    "export_for_serving", "DecodeModelSpec", "DecodeRequest", "cluster",
]
