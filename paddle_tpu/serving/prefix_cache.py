"""Radix-trie prefix KV cache for the slot decode loop.

Thousands of requests sharing a system prompt should pay its prefill
once.  The PR-7 batch-invariance gate makes that sound: a cache column's
K/V content depends only on the token prefix and the RELATIVE position
``column − start``, so a prefilled prefix segment is bit-portable across
slot rows, pack compositions and window shifts.  This module indexes
those segments:

  * the trie is keyed by **blocks** of ``T`` tokens (``T`` = the prefill
    chunk width the slot loop runs) — a node's path from the root spells
    a token prefix of length ``depth·T``, and the node holds that
    block's device planes (the full slot-cache tree sliced to one row ×
    ``T`` columns, bf16 or int8+scales, target or (target, draft) pair);
  * ``lookup`` walks the longest cached chain and **pins** it
    (ref-counted) so a concurrent eviction can never free a block a
    joining row is about to restore;
  * ``publish`` inserts the blocks a completed prefill produced, deduped
    against what is already cached (the fetch callback runs only for
    missing blocks, so republishing a hot prefix costs nothing);
  * eviction is LRU, leaves-first, ``refs == 0`` only, until the cache
    fits ``FLAGS_prefix_cache_hbm_mb`` (0 = unbounded).

The slot loop (serving/slots.py) does the device work — this module is
pure host-side bookkeeping and never touches an executable.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ..profiler.metrics import default_registry as _registry

__all__ = ["PrefixCache"]

PREFIX_HIT_TOKENS = _registry().counter(
    "prefix_cache_hit_tokens_total",
    "Prompt tokens served from the prefix KV cache instead of being "
    "chunk-prefilled (the TTFT savings numerator).")
PREFIX_EVICTIONS = _registry().counter(
    "prefix_cache_evictions_total",
    "Prefix-cache blocks evicted, by reason (capacity = LRU under the "
    "FLAGS_prefix_cache_hbm_mb budget, clear = explicit reset).",
    labels=("reason",))
PREFIX_BYTES = _registry().gauge(
    "prefix_cache_bytes",
    "Device bytes currently held by the prefix KV cache across all "
    "cached blocks.")


class _Node:
    __slots__ = ("key", "parent", "children", "block", "refs", "last_use")

    def __init__(self, key, parent):
        self.key = key
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.block = None
        self.refs = 0
        self.last_use = 0


class PrefixCache:
    """Block-granular radix trie over token prefixes → device KV blocks.

    ``block_tokens`` is the chunk width ``T``; ``block_nbytes`` the
    device footprint of ONE cached block (every plane of the slot-cache
    tree, one row × T columns — precomputed from avals by the slot
    loop); ``hbm_budget_mb`` caps the total (0 = unbounded)."""

    def __init__(self, block_tokens: int, block_nbytes: int,
                 hbm_budget_mb: float = 0.0):
        self.T = int(block_tokens)
        self.block_nbytes = int(block_nbytes)
        self.budget_bytes = int(float(hbm_budget_mb) * 1024 * 1024)
        self._root = _Node(None, None)   # guarded-by: _lock
        self._nodes = 0                  # guarded-by: _lock
        self._tick = 0                   # guarded-by: _lock
        self._lock = threading.Lock()
        self._hits = 0                   # guarded-by: _lock
        self._misses = 0                 # guarded-by: _lock
        self._hit_tokens = 0             # guarded-by: _lock
        self._evictions = 0              # guarded-by: _lock

    # -- internals -----------------------------------------------------------
    def _touch(self, node: _Node) -> None:
        self._tick += 1
        node.last_use = self._tick

    def _nbytes_locked(self) -> int:
        return self._nodes * self.block_nbytes

    def nbytes(self) -> int:
        with self._lock:
            return self._nbytes_locked()

    def __len__(self) -> int:
        with self._lock:
            return self._nodes

    def _evict_until_fits(self) -> None:
        if self.budget_bytes <= 0:
            return
        try:
            while self._nbytes_locked() > self.budget_bytes:
                victim = None
                stack = [self._root]
                while stack:
                    n = stack.pop()
                    stack.extend(n.children.values())
                    if n is self._root or n.children or n.refs > 0:
                        continue                # interior or pinned: keep
                    if victim is None or n.last_use < victim.last_use:
                        victim = n
                if victim is None:
                    return                      # everything pinned: stay over
                del victim.parent.children[victim.key]
                victim.block = None
                self._nodes -= 1
                self._evictions += 1
                PREFIX_EVICTIONS.labels(reason="capacity").inc()
        finally:
            PREFIX_BYTES.set(self._nbytes_locked())

    # -- public API ----------------------------------------------------------
    def lookup(self, tokens: Sequence[int],
               max_blocks: Optional[int] = None):
        """Longest cached prefix of ``tokens``, pinned.

        Returns ``(blocks, pin)``: the device block trees covering the
        first ``len(blocks)·T`` tokens, and an opaque pin the caller
        MUST :meth:`release` once the blocks have been restored (the pin
        holds every chain node's refcount up, so eviction cannot race a
        restore in flight).  ``max_blocks`` clamps the walk — the slot
        loop passes ``(len(prompt) − 1) // T`` so at least one true
        suffix token always remains to produce the activation logits."""
        toks = [int(t) for t in tokens]
        limit = len(toks) // self.T if max_blocks is None \
            else min(max_blocks, len(toks) // self.T)
        with self._lock:
            chain: List[_Node] = []
            node = self._root
            for j in range(limit):
                key = tuple(toks[j * self.T:(j + 1) * self.T])
                child = node.children.get(key)
                if child is None:
                    break
                chain.append(child)
                node = child
            for n in chain:
                n.refs += 1
                self._touch(n)
            if chain:
                self._hits += 1
                self._hit_tokens += len(chain) * self.T
                PREFIX_HIT_TOKENS.inc(len(chain) * self.T)
            else:
                self._misses += 1
            return [n.block for n in chain], tuple(chain)

    def release(self, pin) -> None:
        """Unpin a lookup chain (restore complete or abandoned)."""
        if not pin:
            return
        with self._lock:
            for n in pin:
                if n.refs > 0:
                    n.refs -= 1
            self._evict_until_fits()

    def publish(self, tokens: Sequence[int],
                fetch: Callable[[int], Any]) -> int:
        """Insert the full blocks of ``tokens``, deduped.  ``fetch(j)``
        is called ONLY for block indices not already cached and must
        return the device block tree for columns ``[j·T, (j+1)·T)`` of
        the (relative-position) prefix — the slot loop dispatches a
        ``pull_block`` there.  Returns the number of new blocks."""
        toks = [int(t) for t in tokens]
        new = 0
        with self._lock:
            node = self._root
            for j in range(len(toks) // self.T):
                key = tuple(toks[j * self.T:(j + 1) * self.T])
                child = node.children.get(key)
                if child is None:
                    child = _Node(key, node)
                    child.block = fetch(j)
                    node.children[key] = child
                    self._nodes += 1
                    new += 1
                self._touch(child)
                node = child
            self._evict_until_fits()
            PREFIX_BYTES.set(self._nbytes_locked())
        return new

    def clear(self) -> None:
        with self._lock:
            n = self._nodes
            self._root = _Node(None, None)
            self._nodes = 0
            if n:
                PREFIX_EVICTIONS.labels(reason="clear").inc(n)
            PREFIX_BYTES.set(0)

    def stats(self) -> dict:
        with self._lock:
            return {"blocks": self._nodes, "bytes": self._nbytes_locked(),
                    "hits": self._hits, "misses": self._misses,
                    "hit_tokens": self._hit_tokens,
                    "evictions": self._evictions}
