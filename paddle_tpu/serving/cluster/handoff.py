"""KV-cache handoff between prefill and decode worker pools.

A prefill pool's product is exactly the decode pool's working set: the
per-layer ring-cache planes (bf16 ``(k, v)`` or int8 ``(k, v, k_scale,
v_scale)`` — PR 12's quantized planes ride unchanged), the next-token
logits, and the validity-window metadata (``cache_position`` to resume
at, per-row ``start`` offsets).  Two transports:

  * **device** — both pools share one process/mesh: the handoff is the
    device arrays themselves, zero copies (the decode executable's input
    shardings match the prefill executable's pinned output shardings,
    sharding.py's KV layout rule);
  * **wire** — pools in different processes: planes serialize to one
    contiguous blob (JSON header + raw row-major plane bytes, exact to
    the bit — bf16/int8 planes move as their raw 2/1-byte payloads, so a
    deserialized cache is byte-identical and decode resumes
    bit-identically to the in-process continuation).

Both transports feed the ``kv_handoff_bytes_total`` counter and the
``kv_handoff_seconds`` histogram (docs/METRICS.md inventory).
"""
from __future__ import annotations

import io
import json
import struct
import time
from dataclasses import dataclass, field
from typing import Any, List, Tuple

import numpy as np

from ...framework.enforce import InvalidArgumentError
from ...profiler.metrics import default_registry as _registry

__all__ = ["KVHandoff", "serialize_kv", "deserialize_kv",
           "serialize_session", "deserialize_session"]

_MAGIC = b"PTKV1\n"
_SS_MAGIC = b"PTSS1\n"

_HANDOFF_BYTES = _registry().counter(
    "kv_handoff_bytes_total",
    "KV-cache plane bytes moved between the prefill and decode pools, "
    "by transport (wire = serialized cross-process blob, device = "
    "same-mesh device-to-device pass-through).",
    labels=("transport",))
_HANDOFF_SECONDS = _registry().histogram(
    "kv_handoff_seconds",
    "Wall time of one prefill→decode KV-cache handoff leg (serialize, "
    "deserialize, or device pass-through).",
    buckets=(0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0))


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, resolving the ml_dtypes extension types
    (bfloat16, float8_*) numpy itself does not know."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _host(plane) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(plane))


@dataclass
class KVHandoff:
    """One prefill result in flight to a decode pool.

    ``cache`` is the Generator-shape plane list (one tuple of 2 or 4
    planes per attention layer), ``logits0`` the [B, V] next-token
    logits, ``start`` the per-row first-valid-cache-column offsets and
    ``pos`` the traced ``cache_position`` decode resumes at (== the
    prompt bucket the prefill ran).  ``meta`` carries request context
    across the process boundary (model name, max_new, eos, trace_id).
    """

    cache: List[Tuple[Any, ...]]
    logits0: Any
    start: Any
    pos: int
    meta: dict = field(default_factory=dict)

    def nbytes(self) -> int:
        n = sum(_nbytes(p) for c in self.cache for p in c)
        return n + (_nbytes(self.logits0) if self.logits0 is not None else 0)

    # -- transports ----------------------------------------------------------
    def to_bytes(self) -> bytes:
        return serialize_kv(self)

    @classmethod
    def from_bytes(cls, blob: bytes) -> "KVHandoff":
        return deserialize_kv(blob)

    def device(self, kv_sharding_of=None) -> "KVHandoff":
        """Place every plane on device (the decode pool's ingest step).
        ``kv_sharding_of(shape)`` maps a plane's shape to its sharding
        (sharded replicas pin the KV layout; None = default device).
        Metered as the device transport leg."""
        import jax
        t0 = time.monotonic()
        put = (jax.device_put if kv_sharding_of is None
               else lambda p: jax.device_put(p, kv_sharding_of(np.shape(p))))
        cache = [tuple(put(np.asarray(p)) for p in c) for c in self.cache]
        logits = None if self.logits0 is None \
            else jax.device_put(np.asarray(self.logits0))
        out = KVHandoff(cache=cache, logits0=logits,
                        start=np.asarray(self.start, np.int32),
                        pos=self.pos, meta=dict(self.meta))
        _HANDOFF_BYTES.labels("device").inc(out.nbytes())
        _HANDOFF_SECONDS.observe(time.monotonic() - t0)
        return out


def _nbytes(plane) -> int:
    sz = int(np.prod(np.shape(plane))) if np.ndim(plane) else 1
    return sz * _np_dtype(str(np.asarray(plane).dtype
                              if isinstance(plane, np.ndarray)
                              else plane.dtype)).itemsize


def serialize_kv(h: KVHandoff) -> bytes:
    """One contiguous blob: magic + length-prefixed JSON header + raw
    row-major plane bytes (layer-major, plane order, then logits).  The
    payload is the planes' exact storage bytes — bf16 rows, int8 rows
    and f32 scale planes alike — so the roundtrip is bit-exact."""
    t0 = time.monotonic()
    planes_meta, buf = [], io.BytesIO()
    for c in h.cache:
        layer_meta = []
        for p in c:
            a = _host(p)
            layer_meta.append({"shape": list(a.shape),
                               "dtype": str(a.dtype)})
            buf.write(a.tobytes())
        planes_meta.append(layer_meta)
    logits_meta = None
    if h.logits0 is not None:
        a = _host(h.logits0)
        logits_meta = {"shape": list(a.shape), "dtype": str(a.dtype)}
        buf.write(a.tobytes())
    start = np.asarray(h.start, np.int32).reshape(-1)
    header = json.dumps({
        "version": 1, "planes": planes_meta, "logits": logits_meta,
        "start": start.tolist(), "pos": int(h.pos),
        "meta": dict(h.meta),
    }).encode()
    out = _MAGIC + struct.pack("<I", len(header)) + header + buf.getvalue()
    _HANDOFF_BYTES.labels("wire").inc(len(out))
    _HANDOFF_SECONDS.observe(time.monotonic() - t0)
    return out


def _encode_tree(tree, buf) -> Any:
    """Descriptor of an arbitrary list/tuple pytree of arrays, appending
    each leaf's raw storage bytes to ``buf``.  Container kinds are part
    of the descriptor — the slot-cache pytree structure (a LIST of
    per-layer TUPLEs; the speculative pair is a tuple of two such lists)
    must survive the roundtrip exactly or jax.tree_util would see a
    different treedef on restore."""
    if tree is None:
        return None
    if isinstance(tree, (list, tuple)):
        return {"t": "list" if isinstance(tree, list) else "tuple",
                "c": [_encode_tree(x, buf) for x in tree]}
    a = _host(tree)
    buf.write(a.tobytes())
    return {"shape": list(a.shape), "dtype": str(a.dtype)}


def _decode_tree(desc, take) -> Any:
    if desc is None:
        return None
    if "t" in desc:
        kids = [_decode_tree(d, take) for d in desc["c"]]
        return kids if desc["t"] == "list" else tuple(kids)
    return take(desc)


def serialize_session(payload: dict) -> bytes:
    """Parked-session snapshot wire format (magic ``PTSS1\\n``): same
    length-prefixed-JSON + raw-plane-bytes discipline as
    :func:`serialize_kv`, but the plane container is an arbitrary
    list/tuple pytree (plain slot caches and speculative (target, draft)
    pairs alike) and the scalar session state (tokens, resume payload,
    budget) rides the header.  ``payload['planes']`` and
    ``payload['logits']`` are array pytrees (or None); every other key
    must be JSON-serializable.  Bit-exact roundtrip — a restored session
    decodes byte-identically."""
    t0 = time.monotonic()
    buf = io.BytesIO()
    header_doc = {"version": 1}
    for k, v in payload.items():
        if k in ("planes", "logits"):
            header_doc[k] = _encode_tree(v, buf)
        else:
            header_doc[k] = v
    header = json.dumps(header_doc).encode()
    out = _SS_MAGIC + struct.pack("<I", len(header)) + header \
        + buf.getvalue()
    _HANDOFF_BYTES.labels("session").inc(len(out))
    _HANDOFF_SECONDS.observe(time.monotonic() - t0)
    return out


def deserialize_session(blob: bytes) -> dict:
    """Inverse of :func:`serialize_session`; plane leaves come back as
    host np.frombuffer views with the original container structure."""
    t0 = time.monotonic()
    if not blob.startswith(_SS_MAGIC):
        raise InvalidArgumentError(
            "not a session snapshot blob (bad magic); refusing to parse")
    off = len(_SS_MAGIC)
    (hlen,) = struct.unpack_from("<I", blob, off)
    off += 4
    header = json.loads(blob[off:off + hlen].decode())
    if header.get("version") != 1:
        raise InvalidArgumentError(
            f"session snapshot version {header.get('version')!r} is not "
            "supported (this build speaks version 1)")
    off += hlen

    def take(meta):
        nonlocal off
        dt = _np_dtype(meta["dtype"])
        shape = tuple(meta["shape"])
        n = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        a = np.frombuffer(blob, dtype=dt,
                          count=max(1, int(np.prod(shape))),
                          offset=off).reshape(shape)
        off += n
        return a

    out = {}
    for k, v in header.items():
        if k == "version":
            continue
        out[k] = _decode_tree(v, take) if k in ("planes", "logits") else v
    _HANDOFF_SECONDS.observe(time.monotonic() - t0)
    return out


def deserialize_kv(blob: bytes) -> KVHandoff:
    """Inverse of :func:`serialize_kv`; returns host-resident planes
    (np.frombuffer views reshaped — call :meth:`KVHandoff.device` to
    ingest onto the decode pool's mesh)."""
    t0 = time.monotonic()
    if not blob.startswith(_MAGIC):
        raise InvalidArgumentError(
            "not a KV handoff blob (bad magic); refusing to parse")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", blob, off)
    off += 4
    header = json.loads(blob[off:off + hlen].decode())
    if header.get("version") != 1:
        raise InvalidArgumentError(
            f"KV handoff version {header.get('version')!r} is not "
            "supported (this build speaks version 1)")
    off += hlen

    def take(meta):
        nonlocal off
        dt = _np_dtype(meta["dtype"])
        shape = tuple(meta["shape"])
        n = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        a = np.frombuffer(blob, dtype=dt, count=max(1, int(np.prod(shape))),
                          offset=off).reshape(shape)
        off += n
        return a

    cache = [tuple(take(m) for m in layer) for layer in header["planes"]]
    logits = take(header["logits"]) if header["logits"] is not None else None
    h = KVHandoff(cache=cache, logits0=logits,
                  start=np.asarray(header["start"], np.int32),
                  pos=int(header["pos"]), meta=dict(header.get("meta") or {}))
    _HANDOFF_SECONDS.observe(time.monotonic() - t0)
    return h


# -- declared protocol: the prefill->decode handoff ---------------------------
# serialize_kv/deserialize_kv above are the ``prefill``/``decode`` legs;
# the magic + version checks are the ``reject`` door (a torn blob is a
# retryable failure, never input).  Verified by analysis/protocol.
from ...analysis.protocol.spec import ProtocolSpec, register_protocol

KV_HANDOFF_SPEC = register_protocol(ProtocolSpec(
    name="kv-handoff",
    description="One disaggregated request: prefill serializes the KV "
                "blob, decode ingests it behind the integrity check, "
                "retryable failures re-enter, replies are "
                "exactly-once.",
    module=__name__,
    states=("pending", "in_flight", "decoded", "replied", "failed"),
    initial="pending",
    terminal=("replied", "failed"),
    transitions=(
        ("pending", "prefill", "in_flight"),
        ("in_flight", "decode", "decoded"),
        ("in_flight", "reject", "pending"),
        ("in_flight", "fail", "failed"),
        ("decoded", "reply", "replied"),
    ),
    invariants=(
        ("no-torn-decode",
         "decode never executes over a torn handoff blob"),
        ("reply-at-most-once",
         "a request is replied to at most once"),
    ),
))
