"""Cluster observability plane: federation, trace assembly, signals.

PR 15 made the repo a multi-process serving cluster; this module makes
that cluster *observable as one system* instead of N processes that each
keep secrets.  Three planes, all Router-side and pull-based (the replica
RPC dialect is request/reply — a replica never needs a client back to
the router):

  * **Federated metrics** — every poll, the :class:`ClusterObserver`
    issues the ``scrape`` RPC op and receives each live replica's full
    typed-registry dump (:meth:`MetricsRegistry.dump` — raw per-bucket
    histogram counts, the mergeable form the reservoir ``LatencyWindow``
    can never provide).  :func:`federated_prometheus_text` renders the
    merged view as ONE strict Prometheus exposition: every family
    re-emitted with a ``replica`` label per source, plus ``cluster_*``
    rollup families — sum for counters, bucket-sum for histograms
    (cluster counts equal the sum of per-replica counts by
    construction), and ``_max``/``_min`` gauges (a summed queue depth
    would hide the hot replica).
  * **Cross-process trace assembly** — replicas buffer finished spans in
    a bounded drop-counted export buffer
    (``profiler.tracing.enable_span_export``) which the scrape drains;
    the router re-stamps each span onto its own wall timeline and writes
    one merged trace JSONL that ``tools/obs_report.py --cluster`` joins
    by trace_id.  Clock-skew correction rides the scrape request/reply
    itself: the reply carries the replica's ``time.monotonic()`` at
    serve time, and the router pins it to the midpoint of its own
    send/recv walls — ``delta = (t_send + t_recv)/2 - replica_mono``
    maps replica-monotonic span starts directly onto router wall time
    (error bounded by half the RTT asymmetry), immune to the fact that
    cross-thread child spans stamp ``wall`` at creation rather than at
    their monotonic ``t0``.
  * **ClusterSignals** — the typed snapshot ROADMAP item 4's autoscaler
    polls: per-replica queue depth, retry-after EWMA, batch occupancy,
    heartbeat staleness, steady-compile count, and the live-replica set,
    published as ``cluster_replica_*`` gauges on every poll.

Everything is host-side and fail-open per replica: one replica failing
its scrape increments ``cluster_scrape_errors_total{replica}`` and the
rest of the cluster stays observable.
"""
from __future__ import annotations

import threading
import time
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple

from ...profiler import tracing as _tracing
from ...profiler.metrics import (_esc_label, _flat_stat_name, _fmt_value,
                                 default_registry as _registry,
                                 merge_dumps)

__all__ = ["ClusterObserver", "ClusterSignals", "ReplicaSignals",
           "federated_prometheus_text", "serve_cluster_metrics"]

_SCRAPE_ERRORS = _registry().counter(
    "cluster_scrape_errors_total",
    "Failed scrape polls per replica — the federation stays partial "
    "(and says so) instead of dying with one replica.",
    labels=("replica",))
_SPANS_SHIPPED = _registry().counter(
    "cluster_trace_spans_shipped_total",
    "Spans shipped from a replica's bounded export buffer into the "
    "router's merged cluster trace, by replica.",
    labels=("replica",))
_SPAN_DROPS = _registry().gauge(
    "cluster_trace_span_drops",
    "Cumulative spans a replica dropped from its bounded export buffer "
    "before any scrape drained them (a dead or slow router must never "
    "grow replica memory).",
    labels=("replica",))
_SIG_QDEPTH = _registry().gauge(
    "cluster_replica_queue_depth",
    "ClusterSignals: serving-queue depth per live replica (scrape-poll "
    "fresh — the autoscaler's primary load input).",
    labels=("replica",))
_SIG_RETRY = _registry().gauge(
    "cluster_replica_retry_after_seconds",
    "ClusterSignals: the replica queue's own drain-EWMA retry-after "
    "estimate — the backpressure signal, before any rejection happens.",
    labels=("replica",))
_SIG_STALENESS = _registry().gauge(
    "cluster_replica_heartbeat_staleness_seconds",
    "ClusterSignals: seconds since the replica's last rendezvous-store "
    "heartbeat at poll time (eviction fires past "
    "FLAGS_router_stale_after_s).",
    labels=("replica",))
_SIG_STEADY = _registry().gauge(
    "cluster_replica_steady_compiles",
    "ClusterSignals: post-warm-up XLA recompiles per replica — any "
    "nonzero value is a bucketing bug surfaced cluster-wide.",
    labels=("replica",))
_SIG_OCCUPANCY = _registry().gauge(
    "cluster_replica_batch_occupancy_rows",
    "ClusterSignals: average real rows per executed batch on the "
    "replica (capacity-utilization input to scale-down decisions).",
    labels=("replica",))
_SIG_SLOT_OCC = _registry().gauge(
    "cluster_replica_decode_slot_occupancy",
    "ClusterSignals: token-level decode-slot occupancy ratio on the "
    "replica (FLAGS_decode_slots loops; 0.0 on the scanned path) — the "
    "real decode-load input batch-level queue depth cannot provide.",
    labels=("replica",))
_SIG_SESSIONS = _registry().gauge(
    "cluster_replica_sessions_parked",
    "ClusterSignals: parked conversations held by the replica's session "
    "store (FLAGS_session_store; 0 when the store is off) — drain "
    "planning reads this to size the migration leg.",
    labels=("replica",))
_SIG_CLOCK = _registry().gauge(
    "cluster_replica_clock_offset_seconds",
    "Estimated replica wall-clock offset vs the router (scrape "
    "request/reply midpoint) — the trace-assembly skew correction, "
    "exposed so operators can see clock drift before it lies to them.",
    labels=("replica",))
_SIG_LIVE = _registry().gauge(
    "cluster_signals_replicas_live",
    "ClusterSignals: live-replica count at the last signals snapshot "
    "(the scrape-plane view; router_replicas_live is the dispatch "
    "plane's).")


# ---------------------------------------------------------------------------
# ClusterSignals: the autoscaler's typed snapshot
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ReplicaSignals:
    """One replica's control inputs, as of the last scrape poll."""

    replica_id: str
    role: str
    alive: bool
    queue_depth: int
    retry_after_s: float
    batch_occupancy_rows: float
    steady_compiles: int
    heartbeat_staleness_s: float
    inflight: int
    dispatched: int
    clock_offset_s: float
    # token-level decode-slot occupancy (serving/slots.py; 0.0 when the
    # replica serves the scanned path).  Appended with a default so
    # positional constructions from before the slot loop keep working.
    decode_slot_occupancy_ratio: float = 0.0
    # parked-session accounting (serving/sessions.py; zeros when
    # FLAGS_session_store is off) — appended with defaults, same
    # positional-compatibility discipline as the slot field above
    sessions_parked: int = 0
    session_store_bytes: int = 0


@dataclass(frozen=True)
class ClusterSignals:
    """The cluster-wide snapshot ROADMAP item 4's autoscaler polls.

    Scalar rollups are derived, never authoritative: ``replicas`` is the
    ground truth and the rollups are what a threshold rule needs without
    re-deriving (total backlog, worst backpressure, worst staleness)."""

    wall: float
    replicas_live: int
    live_replicas: Tuple[str, ...]
    total_queue_depth: int
    max_retry_after_s: float
    max_heartbeat_staleness_s: float
    total_steady_compiles: int
    # worst token-level decode-slot occupancy across live replicas —
    # a scale-UP trigger long before queue depth moves (0.0 when every
    # replica serves the scanned path)
    max_decode_slot_occupancy: float = 0.0
    replicas: Tuple[ReplicaSignals, ...] = field(default_factory=tuple)
    # cluster-wide parked-conversation count (FLAGS_session_store) —
    # appended after ``replicas`` so positional constructions from
    # before the session store keep working
    total_sessions_parked: int = 0

    def to_dict(self) -> dict:
        return asdict(self)


# ---------------------------------------------------------------------------
# Federated exposition rendering
# ---------------------------------------------------------------------------

def _render_hist(lines: List[str], name: str, buckets, base: str,
                 payload: dict) -> None:
    """Append cumulative-bucket exposition lines for one histogram child
    whose ``payload`` carries RAW per-bucket counts."""
    acc = 0
    cum = []
    for c in payload["counts"]:
        acc += int(c)
        cum.append(acc)
    for b, c in zip(buckets, cum):
        le = f'le="{_fmt_value(b)}"'
        lab = f"{base},{le}" if base else le
        lines.append(f"{name}_bucket{{{lab}}} {c}")
    lab = f'{base},le="+Inf"' if base else 'le="+Inf"'
    lines.append(f"{name}_bucket{{{lab}}} {cum[-1] if cum else 0}")
    sfx = f"{{{base}}}" if base else ""
    lines.append(f"{name}_sum{sfx} {_fmt_value(payload['sum'])}")
    lines.append(f"{name}_count{sfx} {int(payload['count'])}")


def _labels_str(names, values) -> str:
    return ",".join(f'{k}="{_esc_label(v)}"'
                    for k, v in zip(names, values))


def federated_prometheus_text(dumps: Dict[str, dict],
                              include_stats: bool = True) -> str:
    """One cluster exposition from per-source registry dumps
    (``{source_id: MetricsRegistry.dump()}``).

    Per family: every source's children re-emitted with a ``replica``
    label (unless the family already carries one — router-owned
    ``cluster_replica_*`` gauges pass through as-is), then a
    ``cluster_<name>`` rollup — counter sum, histogram bucket-sum,
    gauge ``_max``/``_min``.  With ``include_stats``, each source's
    legacy monitor gauges follow as ``paddle_tpu_stat{name=,replica=}``
    minus the keys its typed plane already mirrors.  Output parses under
    ``tools/obs_report.py``'s strict parser — that IS the format gate."""
    merged = merge_dumps(dumps)
    lines: List[str] = []
    for name in sorted(merged):
        fam = merged[name]
        has_children = any(fam["per_source"].values())
        if not has_children:
            continue
        pass_through = "replica" in fam["labels"]
        lines.append(f"# HELP {name} {fam['doc'] or name}")
        lines.append(f"# TYPE {name} {fam['kind']}")
        if pass_through:
            # router-owned per-replica family: rollup would double-label
            for values, payload in sorted(fam["rollup"].items()):
                base = _labels_str(fam["labels"], values)
                if fam["kind"] == "histogram":
                    _render_hist(lines, name, fam["buckets"], base,
                                 payload)
                elif fam["kind"] == "gauge":
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{name}{sfx} {_fmt_value(payload['max'])}")
                else:
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(f"{name}{sfx} {_fmt_value(payload)}")
            continue
        for src in sorted(fam["per_source"]):
            for values, payload in sorted(fam["per_source"][src].items()):
                base = _labels_str(fam["labels"] + ("replica",),
                                   values + (src,))
                if fam["kind"] == "histogram":
                    _render_hist(lines, name, fam["buckets"], base,
                                 payload)
                else:
                    lines.append(
                        f"{name}{{{base}}} {_fmt_value(payload)}")
        # cluster rollup family
        roll = f"cluster_{name}"
        if fam["kind"] == "histogram":
            lines.append(f"# HELP {roll} Cluster bucket-sum of {name}.")
            lines.append(f"# TYPE {roll} histogram")
            for values, payload in sorted(fam["rollup"].items()):
                _render_hist(lines, roll, fam["buckets"],
                             _labels_str(fam["labels"], values), payload)
        elif fam["kind"] == "counter":
            lines.append(f"# HELP {roll} Cluster sum of {name}.")
            lines.append(f"# TYPE {roll} counter")
            for values, payload in sorted(fam["rollup"].items()):
                base = _labels_str(fam["labels"], values)
                sfx = f"{{{base}}}" if base else ""
                lines.append(f"{roll}{sfx} {_fmt_value(payload)}")
        else:
            for agg in ("max", "min"):
                lines.append(f"# HELP {roll}_{agg} Cluster {agg} "
                             f"of {name}.")
                lines.append(f"# TYPE {roll}_{agg} gauge")
                for values, payload in sorted(fam["rollup"].items()):
                    base = _labels_str(fam["labels"], values)
                    sfx = f"{{{base}}}" if base else ""
                    lines.append(
                        f"{roll}_{agg}{sfx} {_fmt_value(payload[agg])}")
    if include_stats:
        emitted_help = False
        for src in sorted(dumps):
            d = dumps[src]
            stats = d.get("stats") or {}
            if not stats:
                continue
            skip = set()
            for fam in d.get("families", []):
                for values, _ in fam["children"]:
                    flat = _flat_stat_name(fam["name"], tuple(values))
                    skip.add(flat + "_count"
                             if fam["kind"] == "histogram" else flat)
            if not emitted_help:
                lines.append("# HELP paddle_tpu_stat monitor.h "
                             "StatRegistry int64 gauges (legacy untyped "
                             "plane, federated per replica)")
                lines.append("# TYPE paddle_tpu_stat gauge")
                emitted_help = True
            for k in sorted(stats):
                if k in skip:
                    continue
                lines.append(
                    f'paddle_tpu_stat{{name="{_esc_label(k)}",'
                    f'replica="{_esc_label(src)}"}} {stats[k]}')
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# The Router-side observer
# ---------------------------------------------------------------------------

class ClusterObserver:
    """Polls every live replica's ``scrape`` op; owns federation state,
    the merged cluster trace sink, and the ClusterSignals snapshot.

    Attach to a :class:`Router` (``router.attach_observer(obs)`` makes
    the watch loop drive it at heartbeat cadence) or call :meth:`poll`
    on your own clock.  ``trace_dir`` arms cross-process trace assembly:
    shipped spans land in ONE merged JSONL, re-stamped onto the router
    wall timeline, tagged with their origin process."""

    def __init__(self, router, trace_dir: Optional[str] = None,
                 max_spans_per_poll: int = 2048):
        self._router = router
        self._lock = threading.Lock()
        self._dumps: Dict[str, dict] = {}      # guarded-by: _lock
        self._deltas: Dict[str, float] = {}    # guarded-by: _lock
        self._offsets: Dict[str, float] = {}   # guarded-by: _lock
        self._shipped: Dict[str, int] = {}     # guarded-by: _lock
        self._signals: Optional[ClusterSignals] = None  # guarded-by: _lock
        self._max_spans = int(max_spans_per_poll)
        self._trace_dir = trace_dir
        self._writer = None
        if trace_dir:
            from ...utils.monitor import LogWriter
            self._writer = LogWriter(logdir=trace_dir,
                                     filename_suffix=".cluster")
            # the router's own spans (route/dispatch) join the merged
            # trace through the same export buffer mechanism
            _tracing.enable_span_export()

    # -- polling -------------------------------------------------------------
    def poll(self) -> ClusterSignals:
        """One federation round: scrape live replicas, merge metrics,
        assemble shipped spans, publish signal gauges.  Per-replica
        failures count and skip — never raise."""
        per_replica: List[ReplicaSignals] = []
        handles = self._router.handles()
        staleness = self._heartbeat_staleness(
            [h.id for h in handles if h.alive])
        for h in handles:
            if not h.alive:
                continue
            try:
                t_send = time.time()
                scrape = h.scrape(max_spans=self._max_spans)
                t_recv = time.time()
            except Exception:   # noqa: BLE001 — observability is fail-open
                _SCRAPE_ERRORS.labels(h.id).inc()
                continue
            mid = 0.5 * (t_send + t_recv)
            delta = mid - float(scrape.get("mono", mid))
            offset = float(scrape.get("wall", mid)) - mid
            with self._lock:
                # EWMA over polls: each estimate is midpoint-noisy by
                # half the RTT; smoothing converges on the true offset
                prev = self._deltas.get(h.id)
                self._deltas[h.id] = delta if prev is None \
                    else 0.5 * prev + 0.5 * delta
                prevo = self._offsets.get(h.id)
                self._offsets[h.id] = offset if prevo is None \
                    else 0.5 * prevo + 0.5 * offset
                if scrape.get("dump"):
                    self._dumps[h.id] = scrape["dump"]
                delta = self._deltas[h.id]
                offset = self._offsets[h.id]
            self._sink_spans(h.id, scrape.get("spans") or [], delta)
            drops = int(scrape.get("span_drops", 0))
            if drops:
                _SPAN_DROPS.labels(h.id).set(drops)
            sig = scrape.get("signals") or {}
            rs = ReplicaSignals(
                replica_id=h.id, role=h.role, alive=True,
                queue_depth=int(sig.get("queue_depth", 0)),
                retry_after_s=float(sig.get("retry_after_s", 0.0)),
                batch_occupancy_rows=float(
                    sig.get("batch_occupancy_rows", 0.0)),
                steady_compiles=int(sig.get("steady_compiles", 0)),
                heartbeat_staleness_s=float(staleness.get(h.id, 0.0)),
                inflight=int(h.inflight), dispatched=int(h.dispatched),
                clock_offset_s=offset,
                decode_slot_occupancy_ratio=float(
                    sig.get("decode_slot_occupancy_ratio", 0.0)),
                sessions_parked=int(sig.get("sessions_parked", 0)),
                session_store_bytes=int(
                    sig.get("session_store_bytes", 0)))
            per_replica.append(rs)
            _SIG_QDEPTH.labels(h.id).set(rs.queue_depth)
            _SIG_RETRY.labels(h.id).set(rs.retry_after_s)
            _SIG_STALENESS.labels(h.id).set(rs.heartbeat_staleness_s)
            _SIG_STEADY.labels(h.id).set(rs.steady_compiles)
            _SIG_OCCUPANCY.labels(h.id).set(rs.batch_occupancy_rows)
            _SIG_SLOT_OCC.labels(h.id).set(rs.decode_slot_occupancy_ratio)
            _SIG_SESSIONS.labels(h.id).set(rs.sessions_parked)
            _SIG_CLOCK.labels(h.id).set(rs.clock_offset_s)
        if self._writer is not None:
            # the router's own finished spans, mono -> own wall
            spans, _ = _tracing.drain_exported_spans()
            self._sink_spans("router", spans,
                             time.time() - time.monotonic())
        sig = ClusterSignals(
            wall=time.time(),
            replicas_live=len(per_replica),
            live_replicas=tuple(sorted(r.replica_id
                                       for r in per_replica)),
            total_queue_depth=sum(r.queue_depth for r in per_replica),
            max_retry_after_s=max(
                [r.retry_after_s for r in per_replica] or [0.0]),
            max_heartbeat_staleness_s=max(
                [r.heartbeat_staleness_s for r in per_replica] or [0.0]),
            total_steady_compiles=sum(r.steady_compiles
                                      for r in per_replica),
            max_decode_slot_occupancy=max(
                [r.decode_slot_occupancy_ratio for r in per_replica]
                or [0.0]),
            replicas=tuple(per_replica),
            total_sessions_parked=sum(r.sessions_parked
                                      for r in per_replica))
        _SIG_LIVE.set(sig.replicas_live)
        with self._lock:
            self._signals = sig
        return sig

    def _heartbeat_staleness(self, ids) -> Dict[str, float]:
        store = getattr(self._router, "_store", None)
        if store is None:
            return {}
        out = {}
        now = time.time()
        for rid in ids:
            try:
                raw = store.get(f"__hb/replica:{rid}", wait=False)
                if raw:
                    out[rid] = max(0.0, now - float(raw.decode()))
            except Exception:   # noqa: BLE001 — staleness is best-effort
                pass
        return out

    def _sink_spans(self, source: str, spans, delta: float) -> None:
        """Re-stamp spans from ``source``'s monotonic domain onto the
        router wall timeline (t0 += delta) and append to the merged
        trace JSONL.  Original stamps ride along for forensics."""
        if self._writer is None or not spans:
            return
        for s in spans:
            rec = dict(s)
            rec["t0_mono"] = rec["t0"]
            rec["t0"] = float(rec["t0"]) + delta
            rec["process"] = source
            self._writer.add_event("trace/span", rec)
        _SPANS_SHIPPED.labels(source).inc(len(spans))
        with self._lock:
            self._shipped[source] = \
                self._shipped.get(source, 0) + len(spans)

    # -- read surface --------------------------------------------------------
    def signals(self) -> Optional[ClusterSignals]:
        """Latest ClusterSignals snapshot (None before the first poll) —
        the API the autoscaler polls."""
        with self._lock:
            return self._signals

    def dumps(self) -> Dict[str, dict]:
        """Last-known registry dump per source, router's own included
        (the federation input set)."""
        with self._lock:
            out = dict(self._dumps)
        out["router"] = _registry().dump(include_stats=True)
        return out

    def federated_text(self) -> str:
        """The cluster ``/metrics`` exposition (strict Prometheus
        0.0.4): replica-labeled families + ``cluster_*`` rollups."""
        return federated_prometheus_text(self.dumps())

    def write_textfile(self, path: str) -> str:
        """Atomically persist the federated exposition (node-exporter
        textfile convention, same as profiler.metrics.write_textfile)."""
        import os
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(self.federated_text())
        os.replace(tmp, path)
        return path

    def stats(self) -> dict:
        with self._lock:
            return {"sources": sorted(self._dumps),
                    "spans_shipped": dict(self._shipped),
                    "clock_offset_s": dict(self._offsets),
                    "trace_dir": self._trace_dir}

    def close(self) -> None:
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:   # noqa: BLE001
                pass
            self._writer = None


def serve_cluster_metrics(observer: ClusterObserver, port: int = 0,
                          addr: str = "127.0.0.1"):
    """Serve the FEDERATED exposition over HTTP (``GET /metrics``) —
    the cluster's single scrape door, same stdlib server as
    profiler.metrics.serve_metrics; ``.port`` on the handle reports the
    bound port."""
    import threading as _threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from ...profiler.metrics import _MetricsServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?")[0] not in ("/metrics", "/"):
                self.send_error(404)
                return
            body = observer.federated_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):      # no stderr chatter per scrape
            pass

    httpd = ThreadingHTTPServer((addr, int(port)), Handler)
    t = _threading.Thread(target=httpd.serve_forever,
                          name="paddle-tpu-cluster-metrics", daemon=True)
    t.start()
    return _MetricsServer(httpd, t)
