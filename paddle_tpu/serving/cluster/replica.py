"""One cluster serving replica: a Server behind an RPC endpoint.

The fleet-inference seat of the reference (multi-instance
``AnalysisPredictor`` behind the ``distributed/`` RPC layer): a replica
process owns ONE ``serving.Server`` (warm-up, continuous batching,
steady-state discipline all unchanged), exposes it over the cluster RPC
dialect, announces itself through the TCPStore rendezvous the elastic
runtime already uses (``__serving_replica/<n>`` entries under a
monotonic ``add`` counter — the same idempotent-join discipline as
barrier generations) and heartbeats like an elastic rank
(``__hb/replica:<id>``), so the router's join/evict loop is literally
PR 3's HeartbeatMonitor pointed at replica ids.

``FLAGS_serving_role`` decides the worker pool: a ``prefill`` replica
serves ``prefill`` RPCs only (and warmed only the prefill grid), a
``decode`` replica serves ``decode_from``; ``both`` serves everything.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from ...framework import flags as _flags
from ...profiler import flight as _flight
from ...profiler import tracing as _tracing
from ...profiler.metrics import default_registry as _metrics_registry
from .rpc import RpcServer, decode_arrays, encode_arrays

__all__ = ["Replica", "replica_main", "REPLICA_PREFIX"]

REPLICA_PREFIX = "__serving_replica"


class Replica:
    """Wrap a (started or startable) Server as one cluster replica."""

    def __init__(self, server, replica_id: Optional[str] = None,
                 store=None, host: str = "127.0.0.1", port: int = 0,
                 heldout: bool = False):
        self.server = server
        self.id = str(replica_id if replica_id is not None
                      else f"r{os.getpid()}")
        self.role = str(_flags.flag("serving_role")).lower()
        self.host = host
        self.port = int(port)
        self._store = store
        # held-out (canary) mode: heartbeat so the router's liveness
        # verdict works once the canary is PROMOTED into rotation, but
        # never write a rendezvous record — discovery must not find it,
        # so it takes zero traffic until RollingUpdate adds it.
        self._heldout = bool(heldout)
        self._rpc: Optional[RpcServer] = None
        self._reporter = None
        self._reg_idx: Optional[int] = None
        # set when the replica should exit its serve loop (a drain-and-
        # retire order, or stop()); replica_main blocks on it
        self._exit = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "Replica":
        if not self.server._started:
            self.server.start()
        # cluster observability: buffer finished spans for the router's
        # scrape to drain (bounded + drop-counted; empty while tracing
        # is off) and arm the flight recorder when FLAGS_flight_dir is
        # set — a replica that dies must leave evidence.
        _tracing.enable_span_export()
        _flight.install(ident=self.id)
        self._rpc = RpcServer(self._handlers(), port=self.port)
        self.port = self._rpc.port
        if self._store is not None:
            if self._heldout:
                self._start_heartbeat()
            else:
                self._register()
        return self

    def _register(self):
        """Rendezvous: reserve a slot on the monotonic counter, publish
        the endpoint under it, start heartbeating.  A restarted replica
        re-registers under a fresh slot with the SAME id — the router
        treats that as a rejoin (update the endpoint), not a twin."""
        entry = {"id": self.id, "host": self.host, "port": self.port,
                 "role": self.role, "pid": os.getpid(),
                 "models": self.server.models(),
                 "version": self.server.version}
        idx = self._store.add(f"{REPLICA_PREFIX}/seq", 1)
        self._store.set(f"{REPLICA_PREFIX}/{idx}",
                        json.dumps(entry).encode())
        self._reg_idx = int(idx)
        self._start_heartbeat()

    def _start_heartbeat(self):
        from ...distributed.fleet.elastic import HeartbeatReporter
        self._reporter = HeartbeatReporter(
            self._store, f"replica:{self.id}",
            interval=float(_flags.flag("router_heartbeat_s"))).start()

    def stop(self, drain: bool = True):
        if self._reporter is not None:
            self._reporter.stop()
        if self._rpc is not None:
            self._rpc.close()
        self.server.stop(drain=drain)
        self._exit.set()

    def deregister(self):
        """Clean retirement: stop heartbeating and write a tombstone
        (``__serving_replica/retired/<id>`` = this registration's slot)
        so a router discovering the rendezvous prefix later skips the
        stale entry — a rejoin under a FRESH slot still wins, because
        the tombstone only covers slots up to the retired one."""
        if self._reporter is not None:
            self._reporter.stop()
            self._reporter = None
        if self._store is not None and self._reg_idx is not None:
            self._store.set(f"{REPLICA_PREFIX}/retired/{self.id}",
                            str(self._reg_idx).encode())

    # -- RPC surface ---------------------------------------------------------
    def _handlers(self) -> Dict[str, Any]:
        return {"ping": self._op_ping, "health": self._op_health,
                "stats": self._op_stats, "scrape": self._op_scrape,
                "infer": self._op_infer, "decode": self._op_decode,
                "prefill": self._op_prefill,
                "decode_from": self._op_decode_from,
                "drain": self._op_drain,
                "sessions": self._op_sessions,
                "session_export": self._op_session_export,
                "session_import": self._op_session_import}

    def _op_ping(self, meta, parts):
        return {"id": self.id, "role": self.role}, []

    def _op_health(self, meta, parts):
        q = self.server._queue
        steady = sum(rt.counters.get("steady_compiles", 0)
                     for rt in self.server._models.values())
        return {"id": self.id, "role": self.role,
                "models": self.server.models(),
                "queue_depth": q.depth() if q is not None else 0,
                "steady_compiles": steady, "pid": os.getpid(),
                "version": self.server.version,
                "draining": bool(getattr(self.server, "draining",
                                         False))}, []

    def _op_stats(self, meta, parts):
        return {"stats": self.server.stats(meta.get("model"))}, []

    def _op_scrape(self, meta, parts):
        """The federation op: full typed-registry dump (mergeable raw
        histogram counts), the drained span export buffer (bounded,
        drop-counted), this replica's signal snapshot, and a
        (monotonic, wall) clock pair for the router's skew estimate."""
        spans, drops = _tracing.drain_exported_spans(
            limit=meta.get("max_spans"))
        return {"id": self.id, "role": self.role,
                "wall": time.time(), "mono": time.monotonic(),
                "dump": _metrics_registry().dump(include_stats=True),
                "spans": spans, "span_drops": drops,
                "signals": self.server.signals()}, []

    def _op_infer(self, meta, parts):
        inputs = decode_arrays(meta["arrays"], parts)
        fut = self.server.submit(meta["model"], inputs,
                                 timeout=meta.get("timeout", 5.0),
                                 trace_id=meta.get("trace_id"),
                                 tenant=meta.get("tenant", "default"),
                                 priority=meta.get("priority"))
        outs = fut.result(timeout=meta.get("result_timeout", 60.0))
        ometa, oparts = encode_arrays([np.asarray(o) for o in outs])
        return {"arrays": ometa}, oparts

    def _op_decode(self, meta, parts):
        prompts = decode_arrays(meta["prompts"], parts)
        fut = self.server.submit_decode(
            meta["model"], prompts, max_new_tokens=meta.get("max_new"),
            timeout=meta.get("timeout", 5.0),
            trace_id=meta.get("trace_id"),
            tenant=meta.get("tenant", "default"),
            priority=meta.get("priority"),
            session_id=meta.get("session_id"))
        outs = fut.result(timeout=meta.get("result_timeout", 60.0))
        ometa, oparts = encode_arrays([np.asarray(outs[0])])
        return {"arrays": ometa}, oparts

    # -- parked-session migration (FLAGS_session_store) ----------------------
    def _op_sessions(self, meta, parts):
        store = getattr(self.server, "session_store", None)
        return {"ids": [] if store is None else store.peek_ids()}, []

    def _op_session_export(self, meta, parts):
        store = getattr(self.server, "session_store", None)
        blob = None if store is None \
            else store.export_bytes(meta["session_id"])
        if blob is None:
            return {"found": False}, []
        return {"found": True, "nbytes": len(blob)}, [blob]

    def _op_session_import(self, meta, parts):
        store = getattr(self.server, "session_store", None)
        if store is None or not parts:
            return {"session_id": None}, []
        return {"session_id": store.import_bytes(bytes(parts[0]))}, []

    def _op_drain(self, meta, parts):
        """Graceful-retirement op: flip the server to stop-accepting
        (new submissions bounce with a retry_after hint so the router
        redirects), finish everything admitted, and — when the order
        says ``retire`` and the drain completed — deregister from the
        rendezvous and schedule process exit AFTER this reply flushes.
        A ``drain_hang`` fault clause wedges here deterministically:
        the replica stops accepting but never reports drained, so the
        caller's timeout/eviction escalation is what gets exercised."""
        from ...testing import faults as _faults
        timeout = float(meta.get("timeout",
                                 _flags.flag("drain_timeout_s")))
        plan = _faults.active_plan()
        if plan is not None and plan.should_hang_drain():
            self.server.request_drain()
            time.sleep(timeout)
            _flight.dump("drain_hang")
            return {"id": self.id, "drained": False, "hang": True}, []
        report = self.server.drain(timeout_s=timeout)
        report["id"] = self.id
        if report.get("drained") and meta.get("retire", True):
            self.deregister()
            _flight.dump("drain_retire")
            # let the RPC reply leave the socket before the serve loop
            # unblocks and the process exits
            threading.Timer(0.5, self._exit.set).start()
            report["retired"] = True
        return report, []

    def _op_prefill(self, meta, parts):
        # the prefill leg of a disaggregated chain joins the router's
        # trace: a "prefill" span for the compute, a "handoff" child for
        # the serialize leg — obs_report --cluster reassembles
        # route -> prefill -> handoff -> decode across processes
        prompts = decode_arrays(meta["prompts"], parts)
        tr = _tracing.start_span("prefill", trace_id=meta.get("trace_id"),
                                 replica=self.id, pool="prefill",
                                 model=meta["model"])
        with _tracing.use_span(tr):
            h = self.server.prefill_handoff(meta["model"], prompts,
                                            meta.get("max_new"))
        if meta.get("trace_id"):
            h.meta["trace_id"] = meta["trace_id"]
        t0 = time.monotonic()
        blob = h.to_bytes()
        _tracing.child(tr, "handoff", t0, time.monotonic(),
                       leg="serialize", nbytes=len(blob),
                       replica=self.id)
        _tracing.finish(tr)
        return {"rows": int(h.meta.get("rows", 0)),
                "max_new": int(h.meta.get("max_new", 0)),
                "nbytes": len(blob)}, [blob]

    def _op_decode_from(self, meta, parts):
        from .handoff import deserialize_kv
        tr = _tracing.start_span("decode", trace_id=meta.get("trace_id"),
                                 replica=self.id, pool="decode",
                                 model=meta["model"])
        t0 = time.monotonic()
        handoff = deserialize_kv(bytes(parts[0]))
        _tracing.child(tr, "handoff", t0, time.monotonic(),
                       leg="deserialize", nbytes=len(parts[0]),
                       replica=self.id)
        with _tracing.use_span(tr):
            toks = self.server.decode_from_handoff(meta["model"], handoff)
        _tracing.finish(tr)
        ometa, oparts = encode_arrays([np.asarray(toks)])
        return {"arrays": ometa}, oparts


# -- declared protocol: the replica lifecycle state machine ------------------
# Registered beside the implementation so the model checker
# (analysis/protocol) and a reader of this file see the same machine.
# ``drain`` may land in ``wedged`` (the drain_hang fault clause above);
# ``retire`` is _op_drain's deregister leg — tombstone + heartbeat stop,
# atomic with the drain reply; ``sigkill`` is the environment.
from ...analysis.protocol.spec import ProtocolSpec, register_protocol

REPLICA_LIFECYCLE_SPEC = register_protocol(ProtocolSpec(
    name="replica-lifecycle",
    description="One serving replica from rendezvous registration to "
                "clean retirement (tombstone) or eviction (heartbeat "
                "staleness / drain-timeout escalation).",
    module=__name__,
    states=("boot", "serving", "draining", "drained", "retired",
            "wedged", "dead"),
    initial="boot",
    terminal=("retired", "dead"),
    transitions=(
        ("boot", "register", "serving"),
        ("serving", "drain", "draining"),
        ("serving", "drain", "wedged"),          # drain_hang fault
        ("draining", "drain_complete", "drained"),
        ("drained", "retire", "retired"),
        ("wedged", "evict", "dead"),             # timeout escalation
        ("serving", "sigkill", "dead"),
        ("draining", "sigkill", "dead"),
        ("drained", "sigkill", "dead"),
        ("wedged", "sigkill", "dead"),
    ),
    invariants=(
        ("dispatch-targets-live",
         "no request is ever executed by a retired or dead replica"),
        ("tombstone-evict-exclusive",
         "tombstone-deregister and heartbeat-eviction are mutually "
         "exclusive outcomes for one registration"),
        ("no-retire-with-inflight",
         "the tombstone only lands after the drain actually drained"),
    ),
))


def replica_main(server, replica_id: Optional[str] = None,
                 store_host: Optional[str] = None,
                 store_port: Optional[int] = None, port: int = 0,
                 block: bool = True, heldout: bool = False) -> Replica:
    """Process entry for a spawned replica (tools/serve.py --router
    children): build the store client, start the replica, and (by
    default) serve until the process is killed — the router's heartbeat
    eviction is the shutdown path, exactly like an elastic rank."""
    store = None
    if store_host is not None:
        from ...distributed.fleet.base.tcp_store import TCPStore
        store = TCPStore(store_host, int(store_port), is_master=False)
    rep = Replica(server, replica_id=replica_id, store=store,
                  port=port, heldout=heldout).start()
    if block:
        # serve until killed (heartbeat eviction) OR cleanly retired by
        # a drain order — the graceful alternative to SIGKILL
        rep._exit.wait()
    return rep
