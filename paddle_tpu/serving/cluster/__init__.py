"""paddle_tpu.serving.cluster — multi-host disaggregated serving.

The reference's inference seat is multi-instance ``AnalysisPredictor``
clones fronted by the ``distributed/`` RPC layer; this package is that
shape done TPU-style, turning four single-process subsystems (serving
engine PR 6, decode runtime PR 7, elastic runtime PR 3, persistent
executable cache PR 13) into one serving *system*:

  * **KV-cache handoff** (handoff.py): prefill is compute-bound, decode
    is memory-bound, and the continuous batcher already compiles them as
    separate executables — so they can run on separate worker pools with
    an explicit cache handoff.  Device-to-device when both pools share
    one process/mesh; serialized ring-cache plane transfer (bf16 or
    int8 + scale planes, PR 12) across processes, carrying
    ``cache_position`` / per-row validity-window metadata so decode
    resumes bit-identically.
  * **replicas** (replica.py + rpc.py): one serving process = a
    ``serving.Server`` behind a tiny length-prefixed RPC endpoint,
    registered through the fleet TCPStore rendezvous and heartbeating
    like an elastic training rank.  ``FLAGS_serving_role`` restricts a
    replica to the prefill or decode pool (warm-up then compiles only
    that pool's grid).
  * **sharded replicas** (sharding.py): a replica serving a model too
    big for one chip AOT-compiles its bucket grids over a TP/dp mesh
    with params sharded by the same autoshard rules tables training
    uses, HLO-audited at admission, loaded through the persistent
    executable cache so replica N boots O(load).
  * **front-end router** (router.py): health-checked least-loaded
    dispatch over N replicas, heartbeat-evicting dead ones and
    re-dispatching their in-flight work (no request is lost past the
    submit ack), honoring per-replica retry-after backpressure hints,
    and propagating ``trace_id`` across the process boundary.

CLI: ``tools/serve.py --router --replicas N``.  Flags:
``FLAGS_serving_replicas`` / ``FLAGS_serving_role`` /
``FLAGS_router_heartbeat_s`` / ``FLAGS_router_stale_after_s`` /
``FLAGS_router_retry_backoff_s`` (all off-by-default; a bare Server
never takes the cluster branch).
"""
from __future__ import annotations

from .handoff import (KVHandoff, deserialize_kv,  # noqa: F401
                      serialize_kv)
from .lifecycle import (AutoscaleController, RollingUpdate,  # noqa: F401
                        RolloutJournal)
from .obs import (ClusterObserver, ClusterSignals,  # noqa: F401
                  ReplicaSignals, federated_prometheus_text,
                  serve_cluster_metrics)
from .replica import Replica, replica_main  # noqa: F401
from .router import (LocalReplica, RemoteReplica,  # noqa: F401
                     ReplicaHandle, Router)
from .rpc import RpcClient, RpcError, RpcServer  # noqa: F401
from .sharding import (ShardedModelSpec, serving_shard_specs,  # noqa: F401
                       shard_admission_audit)

__all__ = [
    "KVHandoff", "serialize_kv", "deserialize_kv",
    "RpcServer", "RpcClient", "RpcError",
    "Replica", "replica_main",
    "Router", "ReplicaHandle", "LocalReplica", "RemoteReplica",
    "AutoscaleController", "RollingUpdate", "RolloutJournal",
    "ClusterObserver", "ClusterSignals", "ReplicaSignals",
    "federated_prometheus_text", "serve_cluster_metrics",
    "ShardedModelSpec", "serving_shard_specs", "shard_admission_audit",
]
