"""Elastic cluster lifecycle: autoscaling, graceful drain, rollouts.

PR 15 froze the replica set at boot; PR 16 made the cluster legible
(ClusterSignals); this module makes it *dynamic* — the serving seat of
the reference's ``distributed/fleet/elastic`` layer (ElasticManager
scale events), rebuilt around three primitives:

  * :class:`AutoscaleController` — consumes one ClusterSignals snapshot
    per poll and converges the live replica count toward load: spawns a
    replica (through a caller-supplied ``spawn`` — tools/serve.py passes
    an ElasticLaunch-style ``Popen`` closure; tests pass an in-process
    handle factory) when per-replica queue depth or retry-after pressure
    crosses ``FLAGS_autoscale_queue_high``, and retires the least-loaded
    replica after ``FLAGS_autoscale_idle_polls`` consecutive idle polls.
    Retirement is **graceful drain**: the replica's ``drain`` RPC flips
    it to stop-accepting (UnavailableError + retry_after, so the Router
    redirects), queued batches and slot-loop rows finish at token
    boundaries, then the replica deregisters (rendezvous tombstone) and
    the router removes it cleanly — SIGKILL eviction becomes the
    escalation for a drain that wedges past ``FLAGS_drain_timeout_s``,
    not the default.
  * :class:`RollingUpdate` — zero-downtime version rollouts behind a
    canary gate: a held-out replica of the new version must BIT-MATCH a
    current-version control on held-back traffic before anything enters
    rotation; then old replicas are replaced one at a time,
    spawn-before-drain so capacity never dips.  A mismatch (or a
    ``canary_mismatch`` fault clause) rolls back instantly —
    ``rollout_rollback_total`` counts it, the flight recorder keeps the
    evidence.  Every completed step commits to an atomic JSON journal,
    so a controller killed mid-rollout resumes where it stopped instead
    of replacing anything twice.

Chaos drills: the PR-3 fault plans grew ``spawn_fail`` / ``drain_hang``
/ ``canary_mismatch`` clauses; every escalation path here consults them
and dumps a flight-recorder postmortem when armed.  Deterministic — a
drill reproduces bit-for-bit.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ...framework import flags as _flags
from ...framework.enforce import UnavailableError
from ...profiler import flight as _flight
from ...profiler.metrics import default_registry as _registry
from ...testing import faults as _faults
from .router import ReplicaHandle, Router

__all__ = ["AutoscaleController", "RollingUpdate", "RolloutJournal"]

# -- typed metrics (docs/METRICS.md inventory) --------------------------------
AUTOSCALE_UP = _registry().counter(
    "autoscale_up_total",
    "Replicas the autoscaling controller spawned (scale-up decisions "
    "that actually launched a replica).")
AUTOSCALE_DOWN = _registry().counter(
    "autoscale_down_total",
    "Replicas the controller retired on the scale-down path (graceful "
    "drain completed and the router deregistered them cleanly).")
AUTOSCALE_SPAWN_FAILURES = _registry().counter(
    "autoscale_spawn_failures_total",
    "Replica spawns that failed (the spawn callable raised, or a "
    "spawn_fail fault clause fired); the controller retries on a later "
    "poll under its retry budget.")
AUTOSCALE_TARGET = _registry().gauge(
    "autoscale_target_replicas",
    "The controller's current target replica count — compare with "
    "router_replicas_live to see convergence lag.")
DRAIN_INITIATED = _registry().counter(
    "drain_initiated_total",
    "Graceful-drain orders sent to replicas (scale-down retirements "
    "and rolling-update replacements).")
DRAIN_COMPLETED = _registry().counter(
    "drain_completed_total",
    "Drains that finished inside FLAGS_drain_timeout_s: queue empty, "
    "every admitted request resolved, replica deregistered cleanly.")
DRAIN_TIMEOUTS = _registry().counter(
    "drain_timeouts_total",
    "Drains that wedged past the budget and were escalated to eviction "
    "(the drain_hang chaos drill exercises exactly this path).")
ROLLOUT_STEPS = _registry().counter(
    "rollout_steps_total",
    "Rolling-update replacement steps committed (one old replica "
    "drained out, one new-version replica serving in its place).")
ROLLOUT_CANARY_CHECKS = _registry().counter(
    "rollout_canary_checks_total",
    "Canary bit-match comparisons run against the control replica "
    "before a rollout was allowed to proceed.")
ROLLOUT_ROLLBACKS = _registry().counter(
    "rollout_rollback_total",
    "Rollouts aborted by the canary gate (bit-mismatch, real or "
    "fault-injected): the canary was destroyed before entering "
    "rotation, the old version kept serving.")
ROLLOUT_ACTIVE = _registry().gauge(
    "rollout_active",
    "1 while a rolling update is in progress, else 0 — alert route for "
    "'a deploy is half-done'.")


class AutoscaleController:
    """Converge the live replica count toward load, politely.

    ``spawn(replica_id, version)`` launches one replica and returns
    either a :class:`ReplicaHandle` (in-process replicas: the
    controller adds it to the router immediately) or an opaque process
    token — anything with ``poll()``/``send_signal()``, typically a
    ``Popen`` — whose replica rendezvouses through the TCPStore and is
    discovered by the router's watch loop.  Scale-down picks the
    least-loaded live replica and retires it through :meth:`retire`'s
    drain-then-deregister path, escalating to eviction only when the
    drain wedges.

    The controller itself is poll-driven and thread-free: call
    :meth:`step` with each ClusterSignals snapshot (the router's
    observer cadence), or drive :meth:`scale_to` imperatively (the
    tools/serve.py ``--ramp`` drill).
    """

    def __init__(self, router: Router,
                 spawn: Callable[[str, str], Any], *,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 version: str = "v0",
                 queue_high: Optional[float] = None,
                 idle_polls: Optional[int] = None,
                 cooldown_polls: Optional[int] = None,
                 drain_timeout_s: Optional[float] = None,
                 max_spawn_retries: int = 3,
                 spawn_grace_s: float = 120.0):
        self.router = router
        self._spawn = spawn
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.version = str(version)
        self._queue_high = float(
            queue_high if queue_high is not None
            else _flags.flag("autoscale_queue_high"))
        self._idle_polls = int(
            idle_polls if idle_polls is not None
            else _flags.flag("autoscale_idle_polls"))
        self._cooldown_polls = int(
            cooldown_polls if cooldown_polls is not None
            else _flags.flag("autoscale_cooldown_polls"))
        self._drain_timeout = float(
            drain_timeout_s if drain_timeout_s is not None
            else _flags.flag("drain_timeout_s"))
        self._max_spawn_retries = int(max_spawn_retries)
        self._spawn_grace_s = float(spawn_grace_s)
        self._lock = threading.Lock()
        self._idle = 0
        self._cooldown = 0
        self._spawn_seq = 0                      # guarded-by: _lock
        self._spawn_failures = 0                 # guarded-by: _lock
        self._spawning: Dict[str, float] = {}    # guarded-by: _lock
        self._tokens: Dict[str, Any] = {}        # guarded-by: _lock
        self.decisions: List[dict] = []         # drill-report trail
        AUTOSCALE_TARGET.set(self.min_replicas)

    # -- membership helpers ---------------------------------------------------
    def _live(self) -> List[ReplicaHandle]:
        return [h for h in self.router.handles() if h.alive]

    def _reconcile_spawning(self) -> None:
        """Forget pending spawns that joined the router (or died)."""
        live_ids = {h.id for h in self._live()}
        now = time.monotonic()
        lost = 0
        with self._lock:
            # the poll loop and a rolling update's direct spawn/retire
            # calls race on this bookkeeping — mutate only under _lock
            for rid in list(self._spawning):
                if rid in live_ids:
                    del self._spawning[rid]
                    continue
                tok = self._tokens.get(rid)
                died = tok is not None and \
                    getattr(tok, "poll", lambda: None)() is not None
                if died or now - self._spawning[rid] > self._spawn_grace_s:
                    # spawned but never rendezvoused: count it failed so
                    # a later poll can try again
                    del self._spawning[rid]
                    self._tokens.pop(rid, None)
                    lost += 1
        for _ in range(lost):
            AUTOSCALE_SPAWN_FAILURES.inc()
            _flight.dump("spawn_lost")

    def pending_spawns(self) -> int:
        with self._lock:
            return len(self._spawning)

    # -- scale actions --------------------------------------------------------
    def spawn_replica(self, replica_id: Optional[str] = None,
                      version: Optional[str] = None) -> Optional[str]:
        """Launch one replica; returns its id, or None when the spawn
        failed (fault-injected or real) — the caller retries on a later
        poll under ``max_spawn_retries`` consecutive failures."""
        with self._lock:
            rid = replica_id or f"auto{self._spawn_seq}"
            self._spawn_seq += 1
        ver = str(version or self.version)
        plan = _faults.active_plan()
        failed: Optional[str] = None
        token: Any = None
        if plan is not None and plan.should_fail_spawn():
            failed = "fault:spawn_fail"
        else:
            try:
                token = self._spawn(rid, ver)
            except Exception as e:   # noqa: BLE001 — spawn is external
                failed = f"{type(e).__name__}: {e}"
        if failed is not None:
            AUTOSCALE_SPAWN_FAILURES.inc()
            with self._lock:
                self._spawn_failures += 1
                failures = self._spawn_failures
            _flight.dump("spawn_fail")
            if failures > self._max_spawn_retries:
                raise UnavailableError(
                    f"replica spawn failed {failures} times "
                    f"in a row (last: {failed}) — scale-up abandoned")
            return None
        with self._lock:
            self._spawn_failures = 0
        if isinstance(token, ReplicaHandle):
            token.version = ver
            self.router.add_replica(token)
        else:
            with self._lock:
                self._spawning[rid] = time.monotonic()
                if token is not None:
                    self._tokens[rid] = token
        AUTOSCALE_UP.inc()
        return rid

    def retire(self, replica_id: str) -> dict:
        """Gracefully retire one replica: drain (stop-accepting →
        in-flight work finishes → rendezvous tombstone), then
        deregister from the router.  A drain that wedges past the
        budget escalates to eviction — the SIGKILL-style path the
        drill asserts we normally avoid."""
        h = next((x for x in self._live() if x.id == str(replica_id)),
                 None)
        if h is None:
            return {"action": "retire", "replica": str(replica_id),
                    "skipped": "not live"}
        DRAIN_INITIATED.inc()
        t0 = time.monotonic()
        migrated = 0
        try:
            if bool(_flags.flag("session_store")):
                # two-phase session-stateful retirement: phase 1 drains
                # WITHOUT retiring (live conversations park into the
                # session store, their futures bounce retryably), then
                # the router moves the parked sessions to survivors and
                # rewrites affinity; phase 2 is a short re-drain that
                # deregisters.  A phase-1 wedge skips migration and
                # falls through to the eviction escalation unchanged.
                report = h.drain(timeout=self._drain_timeout,
                                 retire=False)
                if report.get("drained"):
                    migrated = self.router.migrate_sessions_from(h.id)
                    report = h.drain(timeout=self._drain_timeout,
                                     retire=True)
            else:
                report = h.drain(timeout=self._drain_timeout,
                                 retire=True)
        except Exception as e:   # noqa: BLE001 — transport died mid-drain
            report = {"drained": False, "error": f"{type(e).__name__}: {e}"}
        out = {"action": "retire", "replica": h.id,
               "drained": bool(report.get("drained")),
               "duration_s": round(time.monotonic() - t0, 3),
               "migrated_sessions": migrated,
               "report": report}
        if report.get("drained"):
            self.router.deregister(h.id, reason="drained")
            DRAIN_COMPLETED.inc()
            AUTOSCALE_DOWN.inc()
            self._await_token_exit(h.id)
        else:
            DRAIN_TIMEOUTS.inc()
            _flight.dump("drain_timeout")
            self.router.evict(h.id, reason="drain_timeout")
            self._kill_token(h.id)
            out["escalated"] = "evict"
        self.decisions.append(out)
        return out

    def _await_token_exit(self, rid: str, grace_s: float = 10.0) -> None:
        with self._lock:
            tok = self._tokens.pop(rid, None)
        if tok is None or not hasattr(tok, "poll"):
            return
        deadline = time.monotonic() + grace_s
        while tok.poll() is None and time.monotonic() < deadline:
            time.sleep(0.05)
        if tok.poll() is None:           # drained but lingering: SIGTERM
            try:
                tok.terminate()
            except Exception:   # noqa: BLE001
                pass

    def _kill_token(self, rid: str) -> None:
        with self._lock:
            tok = self._tokens.pop(rid, None)
        if tok is not None and getattr(tok, "poll", lambda: 0)() is None:
            try:
                tok.kill()
            except Exception:   # noqa: BLE001
                pass

    def _pick_victim(self) -> Optional[ReplicaHandle]:
        """Least-loaded live replica — the cheapest one to drain."""
        live = self._live()
        if not live:
            return None
        return min(live, key=lambda h: (h.inflight, h.queue_depth,
                                        h.dispatched))

    # -- the poll-driven policy ----------------------------------------------
    def step(self, signals=None) -> dict:
        """One control decision from one ClusterSignals snapshot (falls
        back to the router's attached observer when None).  Returns the
        decision record it also appends to ``self.decisions``."""
        if signals is None and self.router.observer() is not None:
            signals = self.router.observer().poll()
        self._reconcile_spawning()
        live = self._live()
        n = len(live)
        booting = self.pending_spawns()
        decision = {"action": "none", "live": n, "booting": booting}
        if self._cooldown > 0:
            self._cooldown -= 1
            decision["action"] = "cooldown"
            self.decisions.append(decision)
            return decision
        qdepth = float(getattr(signals, "total_queue_depth", 0) or 0)
        retry = float(getattr(signals, "max_retry_after_s", 0.0) or 0.0)
        slot_occ = float(getattr(signals, "max_decode_slot_occupancy",
                                 0.0) or 0.0)
        per_replica_q = qdepth / max(1, n)
        pressured = (per_replica_q >= self._queue_high
                     or retry >= 1.0 or slot_occ >= 0.95)
        if pressured and n + booting < self.max_replicas:
            self._idle = 0
            rid = self.spawn_replica()
            decision["action"] = "scale_up" if rid else "spawn_fail"
            decision["replica"] = rid
            decision["per_replica_queue"] = round(per_replica_q, 2)
            AUTOSCALE_TARGET.set(n + booting + (1 if rid else 0))
            self._cooldown = self._cooldown_polls
        elif (not pressured and qdepth == 0 and booting == 0
                and n > self.min_replicas):
            self._idle += 1
            if self._idle >= self._idle_polls:
                self._idle = 0
                victim = self._pick_victim()
                if victim is not None:
                    decision = self.retire(victim.id)
                    decision["live"] = n
                    AUTOSCALE_TARGET.set(max(self.min_replicas, n - 1))
                    self._cooldown = self._cooldown_polls
                    return decision      # retire() already recorded it
            decision["action"] = "idle"
            decision["idle_polls"] = self._idle
        else:
            self._idle = 0
        self.decisions.append(decision)
        return decision

    # -- imperative scaling (the --ramp drill) --------------------------------
    def scale_to(self, n: int, version: Optional[str] = None) -> List[dict]:
        """Imperatively converge toward ``n`` live replicas: spawn up
        (respecting pending boots) or drain down, one decision list
        back.  Discovery/boot is asynchronous for process spawns — pair
        with :meth:`wait_live`."""
        n = int(n)
        out: List[dict] = []
        AUTOSCALE_TARGET.set(n)
        self._reconcile_spawning()
        while len(self._live()) + self.pending_spawns() < n:
            rid = self.spawn_replica(version=version)
            out.append({"action": "scale_up" if rid else "spawn_fail",
                        "replica": rid})
            if rid is None:
                break                    # retry budget handles repeats
        while len(self._live()) > n:
            victim = self._pick_victim()
            if victim is None:
                break
            out.append(self.retire(victim.id))
        return out

    def wait_live(self, n: int, timeout_s: float = 120.0) -> bool:
        """Poll the router until ``n`` replicas are live (discovery is
        the router's watch loop; this just waits on its effect)."""
        deadline = time.monotonic() + float(timeout_s)
        while time.monotonic() < deadline:
            self.router.poll()
            self._reconcile_spawning()
            if len(self._live()) >= int(n):
                return True
            time.sleep(0.1)
        return len(self._live()) >= int(n)


class RolloutJournal:
    """Atomic on-disk rollout state: which replicas the rolling update
    has already replaced, and whether the canary was promoted.  One
    JSON file, committed with write-temp-then-rename after EVERY step —
    a controller SIGKILLed mid-rollout resumes from the journal and
    never replaces (or double-spawns) a replica twice."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.state: Dict[str, Any] = {"version": None, "promoted": None,
                                      "replaced": [], "done": False}
        if path and os.path.exists(path):
            try:
                with open(path, "r") as f:
                    self.state.update(json.load(f))
            except (OSError, ValueError):
                pass                     # unreadable journal = fresh start

    def reset(self, version: str) -> None:
        self.state = {"version": str(version), "promoted": None,
                      "replaced": [], "done": False}
        self.commit()

    def commit(self) -> None:
        if not self.path:
            return
        from ...checkpoint.atomic import atomic_write_bytes
        atomic_write_bytes(self.path,
                           json.dumps(self.state, indent=1).encode())

    def resumable_for(self, version: str) -> bool:
        return self.state.get("version") == str(version) \
            and not self.state.get("done")


class RollingUpdate:
    """Replace the cluster's replicas with a new artifact version, one
    at a time, with zero downtime and a canary gate.

    ``spawn_heldout(replica_id, version)`` must return a LIVE
    :class:`ReplicaHandle` that is NOT in the router's rotation (an
    in-process handle, or a RemoteReplica dialed directly at a child
    started without the rendezvous store) — the canary takes held-back
    traffic only.  ``canary_requests`` is a list of request specs::

        {"op": "infer",  "model": m, "inputs":  [arr, ...]}
        {"op": "decode", "model": m, "prompts": [ids, ...], "max_new": k}

    Each spec runs on the canary AND on a current-version control
    replica; every output must bit-match (``np.array_equal``) or the
    rollout aborts — canary destroyed, ``rollout_rollback_total``
    bumped, postmortem dumped, old version untouched.  A
    ``canary_mismatch`` fault clause forces the mismatch verdict for
    the drill.  After promotion, replacement steps are
    spawn-before-drain (capacity never dips below the old count) and
    journaled atomically for crash resume.
    """

    def __init__(self, controller: AutoscaleController,
                 spawn_heldout: Callable[[str, str], ReplicaHandle],
                 canary_requests: List[dict], *,
                 journal_path: Optional[str] = None):
        self._ctrl = controller
        self._router = controller.router
        self._spawn_heldout = spawn_heldout
        self._canary_requests = list(canary_requests)
        self._journal = RolloutJournal(journal_path)

    # -- canary traffic -------------------------------------------------------
    @staticmethod
    def _call(handle: ReplicaHandle, spec: dict):
        if spec["op"] == "decode":
            out = handle.submit_decode(
                spec["model"],
                [np.asarray(p, np.int32) for p in spec["prompts"]],
                max_new=spec.get("max_new"))
            return [np.asarray(out)]
        return [np.asarray(o) for o in handle.submit(
            spec["model"], [np.asarray(a) for a in spec["inputs"]])]

    def _canary_matches(self, canary: ReplicaHandle,
                        control: ReplicaHandle) -> bool:
        plan = _faults.active_plan()
        ok = True
        for spec in self._canary_requests:
            ROLLOUT_CANARY_CHECKS.inc()
            got = self._call(canary, spec)
            want = self._call(control, spec)
            if plan is not None and plan.should_mismatch_canary():
                ok = False
            elif len(got) != len(want) or not all(
                    np.array_equal(g, w) for g, w in zip(got, want)):
                ok = False
            if not ok:
                break
        return ok

    # -- the rollout ----------------------------------------------------------
    def run(self, new_version: str,
            wait_live_s: float = 120.0) -> dict:
        """Execute (or resume) the rollout to ``new_version``.  Returns
        a report: ``rolled_back`` True means the canary gate fired and
        the old version is still serving everywhere."""
        new_version = str(new_version)
        if not self._journal.resumable_for(new_version):
            self._journal.reset(new_version)
        st = self._journal.state
        ROLLOUT_ACTIVE.set(1)
        try:
            old = [h for h in self._ctrl._live()
                   if h.version != new_version]
            # -- canary gate (skipped on resume past promotion) ------------
            if st["promoted"] is None:
                control = next((h for h in old), None)
                if control is None:
                    return {"version": new_version, "rolled_back": False,
                            "updated": 0, "note": "nothing to update"}
                cid = f"canary-{new_version}"
                canary = self._spawn_heldout(cid, new_version)
                if not self._canary_matches(canary, control):
                    canary.alive = False
                    canary.close()
                    ROLLOUT_ROLLBACKS.inc()
                    self._ctrl._kill_token(cid)
                    _flight.dump("canary_mismatch")
                    self._journal.state["done"] = True
                    self._journal.commit()
                    return {"version": new_version, "rolled_back": True,
                            "reason": "canary bit-mismatch", "updated": 0}
                # promote: the canary is a certified new-version replica
                # — it enters rotation as the first replacement capacity
                canary.version = new_version
                self._router.add_replica(canary)
                st["promoted"] = cid
                self._journal.commit()
            # -- replica-by-replica replacement ----------------------------
            updated = 0
            for k, h in enumerate(sorted(old, key=lambda x: x.id)):
                if h.id in st["replaced"]:
                    continue
                _faults.step_hook(step=k)         # mid-rollout kill seat
                if h.alive:
                    if updated + 1 < len(old):
                        # spawn-before-drain: keep capacity flat (the
                        # promoted canary already covers one slot, later
                        # steps pre-spawn their replacement)
                        target = len(self._ctrl._live()) + 1
                        rid = self._ctrl.spawn_replica(
                            replica_id=f"{new_version}-{k}",
                            version=new_version)
                        if rid is not None:
                            self._ctrl.wait_live(target,
                                                 timeout_s=wait_live_s)
                    self._ctrl.retire(h.id)
                st["replaced"].append(h.id)
                self._journal.commit()
                ROLLOUT_STEPS.inc()
                updated += 1
            st["done"] = True
            self._journal.commit()
            return {"version": new_version, "rolled_back": False,
                    "updated": updated,
                    "live": len(self._ctrl._live())}
        finally:
            ROLLOUT_ACTIVE.set(0)


# -- declared protocol: the rolling-update state machine ---------------------
# RollingUpdate.run() above implements exactly this machine; the
# journal's resume (``resumable_for``) re-enters at ``promoting`` and
# derives remaining ``replace_step``s from ``replaced`` — which is why
# the model checker's journal-implies-applied invariant is the one that
# matters: a committed step must be fully applied or resume breaks.
from ...analysis.protocol.spec import ProtocolSpec, register_protocol

ROLLING_UPDATE_SPEC = register_protocol(ProtocolSpec(
    name="rolling-update",
    description="Canary gate, promote-or-rollback, then journaled "
                "spawn-before-drain replacement of the old fleet.",
    module=__name__,
    states=("idle", "canary_gate", "promoting", "complete",
            "rolled_back"),
    initial="idle",
    terminal=("complete", "rolled_back"),
    transitions=(
        ("idle", "spawn_canary", "canary_gate"),
        ("canary_gate", "promote", "promoting"),
        ("canary_gate", "rollback", "rolled_back"),
        ("promoting", "replace_step", "promoting"),
        ("promoting", "finish", "complete"),
    ),
    invariants=(
        ("journal-implies-applied",
         "a journal-committed replacement step is never half-applied"),
        ("spawn-before-drain",
         "an old replica retires only after its replacement spawned"),
        ("no-mismatched-promotion",
         "a canary that failed the bit-match gate never enters "
         "rotation"),
        ("rollback-is-clean",
         "rollback leaves the old fleet serving, nothing new behind"),
    ),
))
