"""Length-prefixed request/reply RPC between the router and replicas.

The fleet TCPStore (distributed/fleet/base/tcp_store.py) already proved
the framing discipline — ``<I`` part counts + length-prefixed byte parts
over one TCP stream — so the serving plane speaks the same dialect
rather than inventing another: a request is ``[op, json_meta,
binary_part...]``, a reply is ``[b"ok"|b"err", json_meta,
binary_part...]``.  Numpy arrays ride as raw row-major bytes with their
shape/dtype in the JSON meta (the KV handoff blob is itself one opaque
binary part).

The client never reuses a connection after a transport error (no
mid-stream resync point, the TCPStore lesson) and surfaces server-side
``err`` replies as :class:`RpcError` carrying the error code and the
machine-readable ``retry_after_s`` backpressure hint the router's
per-replica backoff honors.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...framework.enforce import UnavailableError

__all__ = ["RpcServer", "RpcClient", "RpcError",
           "encode_arrays", "decode_arrays"]


def _send_msg(sock, *parts: bytes):
    payload = struct.pack("<I", len(parts))
    for p in parts:
        payload += struct.pack("<I", len(p)) + p
    sock.sendall(payload)


def _recv_exact(sock, n):
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(1 << 20, n - got))
        if not chunk:
            raise ConnectionError("rpc connection closed")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _recv_msg(sock):
    (n,) = struct.unpack("<I", _recv_exact(sock, 4))
    parts = []
    for _ in range(n):
        (ln,) = struct.unpack("<I", _recv_exact(sock, 4))
        parts.append(_recv_exact(sock, ln))
    return parts


def encode_arrays(arrays: Sequence[np.ndarray]
                  ) -> Tuple[List[dict], List[bytes]]:
    """Arrays -> ([{shape, dtype}, ...], [raw bytes, ...])."""
    meta, parts = [], []
    for a in arrays:
        a = np.ascontiguousarray(a)
        meta.append({"shape": list(a.shape), "dtype": str(a.dtype)})
        parts.append(a.tobytes())
    return meta, parts


def decode_arrays(meta: Sequence[dict], parts: Sequence[bytes]
                  ) -> List[np.ndarray]:
    from .handoff import _np_dtype
    out = []
    for m, raw in zip(meta, parts):
        dt = _np_dtype(m["dtype"])
        shape = tuple(m["shape"])
        out.append(np.frombuffer(raw, dtype=dt,
                                 count=max(1, int(np.prod(shape)))
                                 ).reshape(shape))
    return out


class RpcError(RuntimeError):
    """A replica-side failure, re-raised router-side with the replica's
    error taxonomy code and (for UNAVAILABLE backpressure rejections)
    the machine-readable retry-after hint."""

    def __init__(self, code: str, message: str,
                 retry_after_s: Optional[float] = None):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.retry_after_s = retry_after_s


class RpcServer:
    """Thread-per-connection RPC endpoint over ``handlers``:
    ``{op: fn(meta: dict, parts: List[bytes]) -> (meta, parts)}``.
    Handler exceptions become ``err`` replies carrying the enforce
    error-code taxonomy (and the UnavailableError retry-after hint);
    the connection survives, matching the store server's discipline."""

    def __init__(self, handlers: Dict[str, Callable], port: int = 0,
                 host: str = "0.0.0.0"):
        self._handlers = handlers
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self.port = self._srv.getsockname()[1]
        self._srv.listen(64)
        self._stop = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="cluster-rpc", daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                op, meta_raw, *parts = _recv_msg(conn)
                try:
                    fn = self._handlers.get(op.decode())
                    if fn is None:
                        raise KeyError(f"unknown rpc op {op.decode()!r}")
                    rmeta, rparts = fn(json.loads(meta_raw.decode()), parts)
                    _send_msg(conn, b"ok", json.dumps(rmeta).encode(),
                              *rparts)
                except (ConnectionError, OSError):
                    raise
                except Exception as e:   # noqa: BLE001 — reply, don't die
                    err = {"code": getattr(e, "code", type(e).__name__),
                           "message": str(e)}
                    hint = getattr(e, "retry_after_s", None)
                    if hint is not None:
                        err["retry_after_s"] = float(hint)
                    _send_msg(conn, b"err", json.dumps(err).encode())
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def close(self):
        self._stop = True
        try:
            self._srv.close()
        except OSError:
            pass


class RpcClient:
    """One replica connection: serialized request/reply with a lock (the
    router opens one client per replica; concurrency comes from the
    router's dispatch threads fanning out over replicas).  Any transport
    error poisons the socket — the next call reconnects fresh."""

    def __init__(self, host: str, port: int, timeout: float = 60.0):
        self.host, self.port = host, int(port)
        self._timeout = timeout
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None  # guarded-by: _lock

    def _ensure(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self._timeout)

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def request(self, op: str, meta: Optional[dict] = None,
                parts: Sequence[bytes] = (),
                timeout: Optional[float] = None
                ) -> Tuple[dict, List[bytes]]:
        with self._lock:
            try:
                self._ensure()
                if timeout is not None:
                    self._sock.settimeout(timeout)
                _send_msg(self._sock, op.encode(),
                          json.dumps(meta or {}).encode(), *parts)
                status, rmeta_raw, *rparts = _recv_msg(self._sock)
                if timeout is not None:
                    self._sock.settimeout(self._timeout)
            except (ConnectionError, OSError):
                self._drop()
                raise
        rmeta = json.loads(rmeta_raw.decode())
        if status == b"err":
            code = rmeta.get("code", "RPC")
            exc = RpcError(code, rmeta.get("message", "?"),
                           rmeta.get("retry_after_s"))
            if code == UnavailableError.code:
                # preserve the backpressure taxonomy across the wire so
                # router-side policy matches the in-process behavior
                ue = UnavailableError(rmeta.get("message", "?"))
                ue.retry_after_s = rmeta.get("retry_after_s")
                raise ue
            raise exc
        if status != b"ok":
            with self._lock:
                self._drop()
            raise ConnectionError("rpc protocol desync")
        return rmeta, rparts

    def close(self):
        with self._lock:
            self._drop()


def encode_handoff_part(blob: bytes) -> List[bytes]:
    """A KV handoff blob is already a self-describing binary frame — it
    rides as one opaque part."""
    return [blob]
