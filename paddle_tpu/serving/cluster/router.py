"""Front-end router: health-checked least-loaded dispatch over replicas.

The cluster's single client-facing door.  Dispatch policy, in order:

  * only **live** replicas whose pool role can serve the op (full dense
    / decode traffic needs ``both``; disaggregated decode routes
    ``prefill`` to the prefill pool and ``decode_from`` to the decode
    pool, carrying the serialized KV handoff between them);
  * a replica that rejected with UNAVAILABLE backpressure is **backed
    off** until its machine-readable ``retry_after_s`` hint expires —
    backpressure is a full queue, not a death, so the router waits
    instead of evicting;
  * among candidates, **least-loaded** wins: fewest router-side
    in-flight requests, then the smallest last-reported queue depth
    (the per-replica gauge the health poll refreshes);
  * a transport error mid-request marks the replica suspect (out of
    rotation until the heartbeat verdict) and the request **retries on
    another replica** — requests are pure (dense inference / greedy
    decode), so re-dispatch is safe and nothing is lost past the
    submit ack;
  * the watch thread discovers joins through the TCPStore rendezvous
    and **evicts** replicas whose heartbeat went stale (PR 3's
    HeartbeatMonitor pointed at ``replica:<id>`` ranks).

Every request gets a root ``route`` span whose ``trace_id`` crosses the
process boundary in the RPC meta — the replica's ``request`` span joins
the same trace, so one waterfall covers submit → dispatch → replica →
reply.  Typed metrics: ``router_replicas_live``,
``router_dispatch_total{replica}``, ``router_evictions_total``,
``router_replica_queue_depth{replica}`` (docs/METRICS.md).
"""
from __future__ import annotations

import json
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ...framework import flags as _flags
from ...framework.enforce import UnavailableError
from ...profiler import flight as _flight
from ...profiler import tracing as _tracing
from ...profiler.metrics import default_registry as _registry
from .replica import REPLICA_PREFIX
from .rpc import RpcClient, RpcError, decode_arrays, encode_arrays

__all__ = ["Router", "ReplicaHandle", "LocalReplica", "RemoteReplica"]

_REPLICAS_LIVE = _registry().gauge(
    "router_replicas_live",
    "Replicas currently in the router's dispatch rotation (joined and "
    "heartbeat-fresh).")
_DISPATCH_TOTAL = _registry().counter(
    "router_dispatch_total",
    "Requests the router dispatched, by replica (retries on another "
    "replica count again — the metric is dispatch attempts that "
    "reached a replica).",
    labels=("replica",))
_EVICTIONS_TOTAL = _registry().counter(
    "router_evictions_total",
    "Replicas evicted from the dispatch rotation (stale heartbeat or "
    "explicit evict); their in-flight requests re-dispatch to "
    "survivors.")
_DEREGISTERED_TOTAL = _registry().counter(
    "router_deregistered_total",
    "Replicas removed from rotation CLEANLY after a graceful drain "
    "(the scale-down path that is not an eviction: nothing was "
    "in-flight, nothing re-dispatches).")
_REPLICA_QDEPTH = _registry().gauge(
    "router_replica_queue_depth",
    "Last health-reported serving-queue depth per replica — the "
    "least-loaded dispatch signal beyond the router's own in-flight "
    "counts.",
    labels=("replica",))
_STATS_POLL_ERRORS = _registry().counter(
    "router_stats_poll_errors_total",
    "Health/stats polls that raised, by replica.  The heartbeat still "
    "decides death — but a replica whose stats are silently stale is "
    "visible here BEFORE the eviction verdict.",
    labels=("replica",))
SESSION_MIGRATE = _registry().counter(
    "session_migrate_total",
    "Parked sessions moved between replicas through the router "
    "(drain-time migration off a retiring replica; the affinity map "
    "follows the session to its new owner).")


class ReplicaHandle:
    """Router-side view of one replica: identity, pool role, liveness,
    backoff state and load accounting.  Subclasses implement the ops."""

    def __init__(self, replica_id: str, role: str = "both"):
        self.id = str(replica_id)
        self.role = str(role)
        self.alive = True
        self.backoff_until = 0.0         # monotonic; 0 = in rotation
        self.inflight = 0
        self.queue_depth = 0
        self.dispatched = 0
        self.version = "v0"              # artifact version (rollouts)
        self._lock = threading.Lock()

    def serves(self, op: str) -> bool:
        if op == "decode":               # full prefill+decode request
            return self.role == "both"
        if op == "prefill":
            return self.role in ("both", "prefill")
        if op == "decode_from":
            return self.role in ("both", "decode")
        return True                      # dense ops ignore the pool role

    # subclass surface -------------------------------------------------------
    def submit(self, model, inputs, trace_id=None, timeout=60.0):
        raise NotImplementedError

    def submit_decode(self, model, prompts, max_new=None, trace_id=None,
                      timeout=60.0):
        raise NotImplementedError

    def prefill(self, model, prompts, max_new=None, trace_id=None,
                timeout=60.0):
        raise NotImplementedError

    def decode_from(self, model, handoff, trace_id=None, timeout=60.0):
        raise NotImplementedError

    def health(self) -> dict:
        raise NotImplementedError

    def drain(self, timeout: Optional[float] = None,
              retire: bool = True) -> dict:
        """Order a graceful drain: stop accepting, finish in-flight
        work, and (with ``retire``) deregister from the rendezvous.
        Returns the replica's drain report; ``drained`` False is the
        caller's cue to escalate to eviction."""
        raise NotImplementedError

    def model_stats(self) -> dict:
        """Per-model serving stats of the replica (Server.stats())."""
        return {}

    # parked-session migration surface (FLAGS_session_store); the base
    # replies "no sessions" so session-less pools need no override
    def session_ids(self) -> List[str]:
        return []

    def session_export(self, sid: str) -> Optional[bytes]:
        """Serialize-and-remove one parked session (move semantics)."""
        return None

    def session_import(self, blob: bytes) -> Optional[str]:
        """Ingest a migrated session; returns its id (None = stale)."""
        return None

    def scrape(self, max_spans: Optional[int] = None) -> dict:
        """Observability pull (cluster/obs.py federation): the replica's
        registry dump, drained export-buffer spans + drop count, signal
        snapshot, and a (mono, wall) clock pair for skew estimation."""
        return {"id": self.id, "role": self.role, "wall": time.time(),
                "mono": time.monotonic(), "dump": None, "spans": [],
                "span_drops": 0, "signals": {}}

    def close(self):
        pass


class LocalReplica(ReplicaHandle):
    """An in-process Server as a replica (single-process clusters,
    tests): the device KV-handoff path — no serialization between the
    pools when they share the process."""

    def __init__(self, server, replica_id: str, role: Optional[str] = None):
        super().__init__(replica_id,
                         role or str(_flags.flag("serving_role")).lower())
        self.server = server
        self.version = str(getattr(server, "version", "v0"))

    def submit(self, model, inputs, trace_id=None, timeout=60.0,
               tenant="default", priority=None):
        fut = self.server.submit(model, inputs, trace_id=trace_id,
                                 tenant=tenant, priority=priority)
        return [np.asarray(o) for o in fut.result(timeout=timeout)]

    def submit_decode(self, model, prompts, max_new=None, trace_id=None,
                      timeout=60.0, tenant="default", priority=None,
                      session_id=None):
        fut = self.server.submit_decode(model, prompts,
                                        max_new_tokens=max_new,
                                        trace_id=trace_id,
                                        tenant=tenant, priority=priority,
                                        session_id=session_id)
        return np.asarray(fut.result(timeout=timeout)[0])

    def drain(self, timeout: Optional[float] = None,
              retire: bool = True) -> dict:
        from ...testing import faults as _faults
        plan = _faults.active_plan()
        if plan is not None and plan.should_hang_drain():
            # deterministic wedge: stop accepting, never report drained
            # — what the controller's timeout escalation is drilled on
            self.server.request_drain()
            return {"id": self.id, "drained": False, "hang": True}
        report = self.server.drain(timeout_s=timeout)
        report["id"] = self.id
        return report

    def prefill(self, model, prompts, max_new=None, trace_id=None,
                timeout=60.0):
        h = self.server.prefill_handoff(model, prompts, max_new)
        if trace_id:
            h.meta["trace_id"] = trace_id
        return h                        # device transport (same process)

    def decode_from(self, model, handoff, trace_id=None, timeout=60.0):
        return np.asarray(self.server.decode_from_handoff(model, handoff))

    def health(self) -> dict:
        q = self.server._queue
        return {"id": self.id, "role": self.role,
                "queue_depth": q.depth() if q is not None else 0,
                "models": self.server.models()}

    def model_stats(self) -> dict:
        return self.server.stats()

    def session_ids(self) -> List[str]:
        store = getattr(self.server, "session_store", None)
        return [] if store is None else store.peek_ids()

    def session_export(self, sid: str) -> Optional[bytes]:
        store = getattr(self.server, "session_store", None)
        return None if store is None else store.export_bytes(sid)

    def session_import(self, blob: bytes) -> Optional[str]:
        store = getattr(self.server, "session_store", None)
        return None if store is None else store.import_bytes(blob)

    def scrape(self, max_spans: Optional[int] = None) -> dict:
        """In-process scrape: same contract as the RPC op.  NOTE: local
        replicas share one process, hence ONE registry/span buffer — the
        first local handle scraped per poll drains it; the federation
        sees process-truth, not per-handle fiction."""
        from ...profiler import tracing as _tr
        from ...profiler.metrics import default_registry
        spans, drops = _tr.drain_exported_spans(limit=max_spans)
        return {"id": self.id, "role": self.role, "wall": time.time(),
                "mono": time.monotonic(),
                "dump": default_registry().dump(include_stats=True),
                "spans": spans, "span_drops": drops,
                "signals": self.server.signals()}


class RemoteReplica(ReplicaHandle):
    """A replica process reached over the cluster RPC; the KV handoff
    crosses as its serialized wire blob."""

    def __init__(self, replica_id: str, host: str, port: int,
                 role: str = "both", timeout: float = 60.0,
                 version: str = "v0"):
        super().__init__(replica_id, role)
        self.host, self.port = host, int(port)
        self.version = str(version)
        self._client = RpcClient(host, port, timeout=timeout)

    def submit(self, model, inputs, trace_id=None, timeout=60.0,
               tenant="default", priority=None):
        ameta, parts = encode_arrays([np.asarray(a) for a in inputs])
        meta, rparts = self._client.request(
            "infer", {"model": model, "arrays": ameta,
                      "trace_id": trace_id, "result_timeout": timeout,
                      "tenant": tenant, "priority": priority},
            parts, timeout=timeout)
        return decode_arrays(meta["arrays"], rparts)

    def submit_decode(self, model, prompts, max_new=None, trace_id=None,
                      timeout=60.0, tenant="default", priority=None,
                      session_id=None):
        pmeta, parts = encode_arrays([np.asarray(p) for p in prompts])
        meta, rparts = self._client.request(
            "decode", {"model": model, "prompts": pmeta,
                       "max_new": max_new, "trace_id": trace_id,
                       "result_timeout": timeout,
                       "tenant": tenant, "priority": priority,
                       "session_id": session_id},
            parts, timeout=timeout)
        return decode_arrays(meta["arrays"], rparts)[0]

    def drain(self, timeout: Optional[float] = None,
              retire: bool = True) -> dict:
        if timeout is None:
            timeout = float(_flags.flag("drain_timeout_s"))
        # the op itself may lawfully take the whole drain budget (and a
        # drain-hang drill sleeps it out) — pad the transport deadline
        meta, _ = self._client.request(
            "drain", {"timeout": float(timeout), "retire": bool(retire)},
            timeout=float(timeout) + 15.0)
        return meta

    def prefill(self, model, prompts, max_new=None, trace_id=None,
                timeout=60.0):
        pmeta, parts = encode_arrays([np.asarray(p) for p in prompts])
        _meta, rparts = self._client.request(
            "prefill", {"model": model, "prompts": pmeta,
                        "max_new": max_new, "trace_id": trace_id},
            parts, timeout=timeout)
        return rparts[0]                # the serialized handoff blob

    def decode_from(self, model, handoff, trace_id=None, timeout=60.0):
        if not isinstance(handoff, (bytes, bytearray, memoryview)):
            handoff = handoff.to_bytes()
        meta, rparts = self._client.request(
            "decode_from", {"model": model, "trace_id": trace_id},
            [bytes(handoff)], timeout=timeout)
        return decode_arrays(meta["arrays"], rparts)[0]

    def health(self) -> dict:
        meta, _ = self._client.request("health", {}, timeout=5.0)
        return meta

    def model_stats(self) -> dict:
        meta, _ = self._client.request("stats", {}, timeout=10.0)
        return meta["stats"]

    def session_ids(self) -> List[str]:
        meta, _ = self._client.request("sessions", {}, timeout=10.0)
        return list(meta.get("ids") or [])

    def session_export(self, sid: str) -> Optional[bytes]:
        meta, parts = self._client.request(
            "session_export", {"session_id": str(sid)}, timeout=30.0)
        return bytes(parts[0]) if meta.get("found") and parts else None

    def session_import(self, blob: bytes) -> Optional[str]:
        meta, _ = self._client.request("session_import", {},
                                       [bytes(blob)], timeout=30.0)
        return meta.get("session_id")

    def scrape(self, max_spans: Optional[int] = None) -> dict:
        meta, _ = self._client.request(
            "scrape", {"max_spans": max_spans}, timeout=10.0)
        return meta

    def close(self):
        self._client.close()


class Router:
    """Health-checked least-loaded dispatch over N replica handles.

    Construct with explicit handles (in-process clusters), a rendezvous
    ``store`` to discover replicas as they join (spawned clusters), or
    both.  ``close()`` stops the watch thread and the dispatch pool;
    replica Servers are not owned and keep running.
    """

    def __init__(self, replicas: Tuple[ReplicaHandle, ...] = (),
                 store=None, stale_after_s: Optional[float] = None,
                 watch: bool = True, dispatch_workers: int = 8):
        self._handles: Dict[str, ReplicaHandle] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # session affinity (FLAGS_session_store): session_id -> the
        # replica holding its parked KV planes.  Advisory — a missing or
        # dead owner degrades to least-loaded dispatch and the turn
        # re-prefills (bit-identical), never fails.
        self._affinity: Dict[str, str] = {}           # guarded-by: _lock
        self._store = store
        self._seen_seq = 0                            # guarded-by: _lock
        self._stale_after = float(
            stale_after_s if stale_after_s is not None
            else _flags.flag("router_stale_after_s"))
        self._monitor = None
        self._observer = None
        self._stop = threading.Event()
        self._watcher = None
        self._pool = ThreadPoolExecutor(max_workers=int(dispatch_workers),
                                        thread_name_prefix="router")
        for h in replicas:
            self.add_replica(h)
        if store is not None:
            from ...distributed.fleet.elastic import HeartbeatMonitor
            self._monitor = HeartbeatMonitor(
                store, stale_after=self._stale_after, ranks=[])
            self.poll()                  # pick up already-joined replicas
            if watch:
                self._watcher = threading.Thread(
                    target=self._watch_loop, name="router-watch",
                    daemon=True)
                self._watcher.start()

    # -- membership ----------------------------------------------------------
    def add_replica(self, handle: ReplicaHandle) -> ReplicaHandle:
        with self._lock:
            old = self._handles.get(handle.id)
            if old is not None and old is not handle:
                old.alive = False
                old.close()              # rejoin: endpoint superseded
            self._handles[handle.id] = handle
        _REPLICAS_LIVE.set(self.replicas_live())
        return handle

    def evict(self, replica_id: str, reason: str = "stale") -> bool:
        """Remove a replica from rotation.  In-flight requests on it
        will fail their transport op and re-dispatch to survivors."""
        with self._lock:
            h = self._handles.get(str(replica_id))
            if h is None or not h.alive:
                return False
            h.alive = False
            self._drop_affinity_locked(str(replica_id))
        h.close()
        _EVICTIONS_TOTAL.inc()
        _REPLICAS_LIVE.set(self.replicas_live())
        _tracing.event("router_evict", replica=str(replica_id),
                       reason=reason)
        # an eviction is a postmortem-worthy cluster event: snapshot the
        # router's own flight recorder (no-op while disarmed)
        _flight.dump("watchdog_evict")
        return True

    def deregister(self, replica_id: str, reason: str = "drained") -> bool:
        """Remove a replica from rotation CLEANLY (graceful-drain
        retirement): it already reported drained, so nothing is
        in-flight, nothing re-dispatches, and this is not an eviction —
        no eviction counter, no postmortem."""
        with self._lock:
            h = self._handles.pop(str(replica_id), None)
            self._drop_affinity_locked(str(replica_id))
        if h is None:
            return False
        h.alive = False
        h.close()
        _DEREGISTERED_TOTAL.inc()
        _REPLICAS_LIVE.set(self.replicas_live())
        _tracing.event("router_deregister", replica=str(replica_id),
                       reason=reason)
        return True

    def _drop_affinity_locked(self, replica_id: str) -> None:
        """Forget affinity to a removed replica — the sessions either
        migrated (affinity rewritten before this) or died with it, and a
        stale pointer would just cost one wasted preference."""
        for sid in [s for s, r in self._affinity.items()
                    if r == replica_id]:
            del self._affinity[sid]

    def session_affinity(self, session_id: str) -> Optional[str]:
        with self._lock:
            return self._affinity.get(str(session_id))

    def handles(self) -> List[ReplicaHandle]:
        with self._lock:
            return list(self._handles.values())

    def _alive(self) -> List[ReplicaHandle]:
        return [h for h in self.handles() if h.alive]

    def replicas_live(self) -> int:
        return len(self._alive())

    # -- discovery + heartbeat eviction --------------------------------------
    def poll(self) -> None:
        """One watch-loop iteration, callable directly (tests, or a
        caller owning its own cadence): discover joins, refresh health,
        evict stale heartbeats."""
        if self._store is not None:
            self._discover()
            self._evict_stale()
        self._refresh_health()
        if self._observer is not None:
            try:
                self._observer.poll()
            except Exception:   # noqa: BLE001 — observability is fail-open
                pass

    def _discover(self):
        raw = self._store.get(f"{REPLICA_PREFIX}/seq", wait=False)
        n = int(raw) if raw else 0
        with self._lock:
            # claim the range before walking it: poll() runs on the
            # watch thread AND directly (tests, wait_live), and two
            # unsynchronized walks would both add the same registrations
            start = self._seen_seq + 1
            self._seen_seq = max(self._seen_seq, n)
        done = n
        for i in range(start, n + 1):
            raw = self._store.get(f"{REPLICA_PREFIX}/{i}", wait=False)
            if raw is None:
                # reserved but not yet published: retry next poll
                done = i - 1
                break
            info = json.loads(raw.decode())
            tomb = self._store.get(
                f"{REPLICA_PREFIX}/retired/{info['id']}", wait=False)
            if tomb is not None and int(tomb) >= i:
                # retired at (or after) this registration: skip the
                # stale entry — a rejoin claims a fresh slot past the
                # tombstone and still wins
                continue
            self.add_replica(RemoteReplica(
                info["id"], info["host"], info["port"],
                role=info.get("role", "both"),
                version=info.get("version", "v0")))
        if done < n:
            with self._lock:
                # un-claim the unpublished tail; re-walking an entry a
                # concurrent poll claimed past this point is harmless
                # (add_replica supersedes the endpoint idempotently)
                self._seen_seq = min(self._seen_seq, done)

    def _evict_stale(self):
        alive = self._alive()
        self._monitor.set_ranks([f"replica:{h.id}" for h in alive])
        for rank in self._monitor.stale_ranks():
            self.evict(str(rank)[len("replica:"):], reason="stale")

    def _refresh_health(self):
        for h in self._alive():
            try:
                info = h.health()
                h.queue_depth = int(info.get("queue_depth", 0))
                _REPLICA_QDEPTH.labels(h.id).set(h.queue_depth)
            except Exception:   # noqa: BLE001 — the heartbeat decides death
                _STATS_POLL_ERRORS.labels(h.id).inc()
                h.backoff_until = time.monotonic() + self._stale_after

    def _watch_loop(self):
        interval = float(_flags.flag("router_heartbeat_s"))
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception:   # noqa: BLE001 — watching must not die
                pass
            self._stop.wait(interval)

    # -- dispatch core -------------------------------------------------------
    def _pick(self, op: str, prefer: Optional[str] = None):
        """(handle, wake_monotonic): the least-loaded live replica that
        serves ``op`` and is not backed off; handle=None with a wake
        time means every candidate is backing off; both None means no
        live replica can ever serve the op.  ``prefer`` (session
        affinity) wins outright when that replica is a candidate —
        restoring parked KV beats load-balance — and silently falls
        back to least-loaded when it is dead or backed off."""
        now = time.monotonic()
        best, wake = None, None
        for h in self._alive():
            if not h.serves(op):
                continue
            if h.backoff_until > now:
                wake = h.backoff_until if wake is None \
                    else min(wake, h.backoff_until)
                continue
            if prefer is not None and h.id == prefer:
                return h, None
            key = (h.inflight, h.queue_depth, h.dispatched)
            if best is None or key < (best.inflight, best.queue_depth,
                                      best.dispatched):
                best = h
        return best, wake

    def _dispatch(self, op: str, call, timeout: float, span=None,
                  prefer: Optional[str] = None):
        """Retry loop: pick → call → (backoff | suspect | return)."""
        deadline = time.monotonic() + timeout
        last_err: Optional[Exception] = None
        while True:
            h, wake = self._pick(op, prefer=prefer)
            prefer = None        # affinity is one preference, not a pin:
            # a failed attempt on the owner retries least-loaded
            if h is None:
                now = time.monotonic()
                if wake is None or now >= deadline:
                    hint = None if wake is None else max(0.0, wake - now)
                    raise last_err if isinstance(last_err,
                                                 UnavailableError) else \
                        UnavailableError(
                            f"no live replica can serve {op!r} "
                            f"({self.replicas_live()} live)",
                            retry_after_s=hint)
                time.sleep(min(wake - now, deadline - now))
                continue
            with h._lock:
                h.inflight += 1
                h.dispatched += 1
            _DISPATCH_TOTAL.labels(h.id).inc()
            t0 = time.monotonic()
            try:
                out = call(h)
                if span is not None:
                    _tracing.child(span, "dispatch", t0, time.monotonic(),
                                   replica=h.id, op=op)
                return out
            except UnavailableError as e:
                # backpressure: honor the replica's retry-after hint —
                # back off THIS replica, try another
                hint = getattr(e, "retry_after_s", None)
                if hint is None:
                    hint = float(_flags.flag("router_retry_backoff_s"))
                h.backoff_until = time.monotonic() + float(hint)
                if span is not None:
                    _tracing.child(span, "backpressure", t0,
                                   time.monotonic(), replica=h.id,
                                   retry_after_s=float(hint))
                last_err = e
            except (ConnectionError, OSError, RpcError) as e:
                # transport/replica fault: out of rotation until the
                # heartbeat verdict; the request retries elsewhere
                h.backoff_until = time.monotonic() + self._stale_after
                if span is not None:
                    _tracing.child(span, "redispatch", t0,
                                   time.monotonic(), replica=h.id,
                                   error=type(e).__name__)
                last_err = e
            finally:
                with h._lock:
                    h.inflight -= 1

    # -- traffic -------------------------------------------------------------
    def submit(self, model: str, inputs, timeout: float = 60.0,
               tenant: str = "default",
               priority: Optional[int] = None) -> Future:
        """Dense inference through the cluster: returns a Future of the
        per-output numpy arrays, exactly Server.submit's contract.
        ``tenant``/``priority`` ride the RPC meta into the replica's
        per-tenant admission."""
        return self._pool.submit(self._run_dense, model,
                                 [np.asarray(a) for a in inputs], timeout,
                                 tenant, priority)

    def run(self, model: str, inputs, timeout: float = 60.0,
            tenant: str = "default", priority: Optional[int] = None):
        return self._run_dense(model, [np.asarray(a) for a in inputs],
                               timeout, tenant, priority)

    def _run_dense(self, model, inputs, timeout, tenant="default",
                   priority=None):
        tr = _tracing.start_span("route", model=model, kind="dense")
        try:
            out = self._dispatch(
                "infer",
                lambda h: h.submit(model, inputs,
                                   trace_id=getattr(tr, "trace_id", None),
                                   timeout=timeout, tenant=tenant,
                                   priority=priority),
                timeout, span=tr)
            _tracing.finish(tr)
            return out
        except Exception:
            if tr is not None:
                tr.set_attr(error=True)
                _tracing.finish(tr)
            raise

    def submit_decode(self, model: str, prompts,
                      max_new_tokens: Optional[int] = None,
                      timeout: float = 60.0, tenant: str = "default",
                      priority: Optional[int] = None,
                      session_id: Optional[str] = None) -> Future:
        """Decode through the cluster: full-decode replicas when the
        pools are unified; prefill-pool → KV handoff → decode-pool when
        disaggregated (mixed clusters prefer the disaggregated path
        only when no 'both' replica is live).  ``session_id`` routes the
        turn to the replica holding the conversation's parked KV planes
        (session affinity) and records the replica that served it."""
        return self._pool.submit(
            self._run_decode, model,
            [np.asarray(p) for p in prompts], max_new_tokens, timeout,
            tenant, priority, session_id)

    def run_decode(self, model: str, prompts,
                   max_new_tokens: Optional[int] = None,
                   timeout: float = 60.0, tenant: str = "default",
                   priority: Optional[int] = None,
                   session_id: Optional[str] = None):
        return self._run_decode(model,
                                [np.asarray(p) for p in prompts],
                                max_new_tokens, timeout, tenant,
                                priority, session_id)

    def _run_decode(self, model, prompts, max_new, timeout,
                    tenant="default", priority=None, session_id=None):
        tr = _tracing.start_span("route", model=model, kind="decode")
        tid = getattr(tr, "trace_id", None)
        served: List[str] = []

        def _decode_call(h):
            # forward session identity only when tagged, so replica
            # handles that predate the session plane keep working for
            # stateless traffic
            extra = {} if session_id is None \
                else {"session_id": session_id}
            out = h.submit_decode(model, prompts, max_new=max_new,
                                  trace_id=tid, timeout=timeout,
                                  tenant=tenant, priority=priority,
                                  **extra)
            served.append(h.id)
            return out

        try:
            if any(h.serves("decode") for h in self._alive()):
                prefer = None
                if session_id is not None:
                    with self._lock:
                        prefer = self._affinity.get(str(session_id))
                out = self._dispatch("decode", _decode_call, timeout,
                                     span=tr, prefer=prefer)
                if session_id is not None and served:
                    with self._lock:
                        self._affinity[str(session_id)] = served[-1]
            else:
                handoff = self._dispatch(
                    "prefill",
                    lambda h: h.prefill(model, prompts, max_new=max_new,
                                        trace_id=tid, timeout=timeout),
                    timeout, span=tr)
                out = self._dispatch(
                    "decode_from",
                    lambda h: h.decode_from(model, handoff,
                                            trace_id=tid,
                                            timeout=timeout),
                    timeout, span=tr)
            _tracing.finish(tr)
            return [np.asarray(out)]     # Server.submit_decode parity
        except Exception:
            if tr is not None:
                tr.set_attr(error=True)
                _tracing.finish(tr)
            raise

    # -- session migration (drain-time) --------------------------------------
    def migrate_sessions_from(self, replica_id: str,
                              target_id: Optional[str] = None) -> int:
        """Move every parked session off ``replica_id`` (a drained
        replica about to retire) into surviving decode replicas and
        point the affinity map at the new owners.  Returns sessions
        moved.  Fail-open per session: an export/import that raises
        leaves that session behind — a shared spill directory still
        recovers it, and without one the next turn falls back to a
        plain (bit-identical) re-prefill."""
        src = next((h for h in self.handles()
                    if h.id == str(replica_id)), None)
        if src is None:
            return 0
        candidates = [h for h in self._alive()
                      if h.id != src.id and h.serves("decode")]
        if target_id is not None:
            candidates = [h for h in candidates
                          if h.id == str(target_id)]
        if not candidates:
            return 0
        try:
            ids = src.session_ids()
        except Exception:   # noqa: BLE001 — a dead source has nothing
            return 0
        moved = 0
        for sid in ids:
            dst = min(candidates, key=lambda h: (h.inflight,
                                                 h.queue_depth,
                                                 h.dispatched))
            try:
                blob = src.session_export(sid)
                if blob is None:
                    continue
                got = dst.session_import(blob)
            except Exception:   # noqa: BLE001 — per-session fail-open
                continue
            if got is not None:
                with self._lock:
                    self._affinity[str(got)] = dst.id
                moved += 1
                SESSION_MIGRATE.inc()
        if moved:
            _tracing.event("session_migrate", source=str(replica_id),
                           moved=moved)
        return moved

    # -- observability + lifecycle -------------------------------------------
    def attach_observer(self, observer):
        """Attach a cluster observer (cluster/obs.ClusterObserver): the
        watch loop drives ``observer.poll()`` at heartbeat cadence right
        after health refresh, so federation/trace-assembly/signals share
        the liveness view they were sampled under.  The observer's
        lifetime stays the caller's."""
        self._observer = observer
        return observer

    def observer(self):
        return self._observer

    def stats(self) -> dict:
        out = {"replicas_live": self.replicas_live(), "replicas": {}}
        for h in self.handles():
            out["replicas"][h.id] = {
                "alive": h.alive, "role": h.role,
                "dispatched": h.dispatched, "inflight": h.inflight,
                "queue_depth": h.queue_depth,
                "backing_off": h.backoff_until > time.monotonic(),
            }
        return out

    def close(self):
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
        self._pool.shutdown(wait=True)
        for h in self.handles():
            h.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# -- declared protocol: the router's membership view -------------------------
# The router-side half of the replica lifecycle: a registration is
# discovered into rotation exactly once, and leaves it through exactly
# one of two doors — the controller's deregister after a clean drain,
# or the heartbeat/escalation evict.  Tombstoned slots are never
# discovered; an evicted handle is remembered, so discovery cannot
# resurrect it.  Verified by analysis/protocol (model_check).
from ...analysis.protocol.spec import ProtocolSpec, register_protocol

ROUTER_MEMBERSHIP_SPEC = register_protocol(ProtocolSpec(
    name="router-membership",
    description="A replica registration through the router's rotation: "
                "discovered once, removed through deregister XOR evict.",
    module=__name__,
    states=("unknown", "in_rotation", "deregistered", "evicted"),
    initial="unknown",
    terminal=("deregistered", "evicted"),
    transitions=(
        ("unknown", "discover", "in_rotation"),
        ("in_rotation", "deregister", "deregistered"),
        ("in_rotation", "evict", "evicted"),
    ),
    invariants=(
        ("tombstone-evict-exclusive",
         "one registration exits rotation through deregister or evict, "
         "never both"),
    ),
))
