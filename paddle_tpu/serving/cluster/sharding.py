"""Sharded serving replicas: the autoshard rules tables, served.

A model sharded for training by the PR-9 rules tables could not be
*served* — every serving runtime held a full replica.  Here a replica's
warm-up AOT-compiles its bucket grids over a TP/dp mesh with params
sharded by the SAME rules tables (``analysis.autoshard.propose`` over
the live layer's dotted param paths), so the serving layout is the
training layout by construction:

  * :func:`serving_shard_specs` — layer + mesh → {param: PartitionSpec}
    via the active (or given) rules table; hand annotations win exactly
    as in training;
  * :class:`ShardedModelSpec` / :class:`_ShardedRuntime` — a DENSE
    served model backed by a live layer compiled per bucket with sharded
    param avals (persistent-executable-cache-loaded, so replica N boots
    O(load)); registered on a Server like any other spec;
  * :func:`shard_admission_audit` — the PR-8 HLO audit run at admission
    over each compiled bucket executable (collective census + budget
    passes) plus the serving-specific containment check: a param the
    rules sharded must KEEP its live mesh axes in the compiled input
    layout — an executable that quietly replicated the TP shards is
    refused, not served.  Gated by ``FLAGS_hlo_audit`` (off-path = one
    branch, PR-5/8 discipline).

Decode models shard through the same specs via
``DecodeModelSpec(mesh=...)`` → ``Generator(mesh=, param_specs=)``
(text/generation.py), which additionally pins the KV-cache plane layout
(heads sharded by ``mp`` when divisible) so the prefill→decode handoff
is layout-stable across the pools.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ...framework import flags as _flags
from ...framework.enforce import PreconditionNotMetError
from ...profiler.metrics import LatencyWindow, RateMeter

__all__ = ["ShardedModelSpec", "serving_shard_specs",
           "shard_admission_audit", "kv_plane_spec"]


def serving_shard_specs(layer, mesh, rules=None) -> Dict[str, Any]:
    """{dotted param path: PartitionSpec-or-None} for serving ``layer``
    over ``mesh``, derived from the autoshard rules table training uses
    (``rules=None`` reads FLAGS_autoshard_rules' active table).  Hand
    annotations win over rule proposals — the training precedence."""
    from ...analysis.autoshard import propose
    if rules is not None and isinstance(rules, str):
        from ...analysis.autoshard import rules_table
        rules = rules_table(rules)
    plan = propose(layer, rules=rules, mesh=mesh)
    return plan.specs()


def kv_plane_spec(shape: Sequence[int], mesh) -> Any:
    """The pinned KV-cache plane layout for sharded decode: ring planes
    are [B, heads, C, H] (rows) / [B, heads, C] (int8 scales) — shard
    the heads axis by ``mp`` when it is live and divides, replicate
    otherwise.  This single rule makes prefill outputs, decode inputs
    and cross-pool device ingests agree without consulting each other."""
    from jax.sharding import PartitionSpec as P
    mp = dict(mesh.shape).get("mp", 1)
    if len(shape) >= 3 and mp > 1 and int(shape[1]) % mp == 0:
        return P(None, "mp")
    return P()


def _spec_live_axes(spec, mesh_axes: Dict[str, int]) -> set:
    axes = set()
    if spec is None:
        return axes
    for e in tuple(spec):
        for a in (e if isinstance(e, (tuple, list)) else (e,)):
            if a is not None and mesh_axes.get(a, 1) > 1:
                axes.add(a)
    return axes


def shard_admission_audit(compiled, *, site: str, mesh,
                          param_specs: Optional[Dict[str, Any]] = None,
                          mesh_label: str = "") -> None:
    """Admission-time HLO audit of one serving executable (PR-8 pass
    family: collective census, wire/HBM budgets) plus the serving
    containment contract: every param the rules sharded over a live
    axis must carry that axis in the compiled INPUT layout — a program
    that re-replicated the shards would silently multiply per-device
    HBM by the mesh size, which is exactly what sharded serving exists
    to prevent.  ERROR findings (or a dropped axis) refuse admission.
    Rides FLAGS_hlo_audit; off = this one branch."""
    from ... import analysis
    from ...analysis.hlo import audit_compiled, audit_enabled
    if not audit_enabled():
        return
    res = audit_compiled(compiled, site=site, mesh=mesh,
                         mesh_label=mesh_label, do_emit=True)
    errors = res.report.by_severity(analysis.Severity.ERROR)
    dropped = []
    if param_specs:
        mesh_axes = dict(mesh.shape)
        try:
            in_params = compiled.input_shardings[0][0]
        except Exception:
            in_params = None
        if isinstance(in_params, dict):
            for name, spec in sorted(param_specs.items()):
                want = _spec_live_axes(spec, mesh_axes)
                s = in_params.get(name)
                if not want or s is None:
                    continue
                if getattr(s, "is_fully_replicated", False):
                    dropped.append((name, sorted(want)))
    if errors or dropped:
        lines = ["  " + str(d) for d in errors]
        lines += [f"  param {n!r} lost its sharded axes {a} in the "
                  "compiled input layout (stored full per device)"
                  for n, a in dropped]
        raise PreconditionNotMetError(
            f"serving admission HLO audit refused {site!r} at "
            f"{mesh_label or 'mesh'}:\n" + "\n".join(lines))


# ---------------------------------------------------------------------------
# Dense sharded runtime
# ---------------------------------------------------------------------------

@dataclass
class ShardedModelSpec:
    """One dense served model backed by a LIVE layer sharded over
    ``mesh`` (jax.sharding.Mesh, e.g. ``parallel.make_mesh({'dp': 2,
    'mp': 4})``).  ``input_specs`` is the executor-spec convention
    ``[(shape-with-None-lead, dtype), ...]``; ``rules`` optionally
    names/provides the autoshard table (default: the active table)."""

    name: str
    layer: Any
    input_specs: Sequence[Tuple[Sequence[Optional[int]], Any]]
    mesh: Any
    rules: Any = None
    buckets: Optional[Sequence[int]] = None

    def make_runtime(self):
        return _ShardedRuntime(self)


class _ShardedExec:
    """One compiled (model, bucket) SPMD executable: sharded params +
    replicated buffers held resident, inputs re-placed to the compiled
    input shardings per call (the worker's plain device_put committed
    them to one device; this transfer re-shards them onto the mesh)."""

    __slots__ = ("compiled", "params_dev", "buffers_dev", "in_shardings")

    def __init__(self, compiled, params_dev, buffers_dev, in_shardings):
        self.compiled = compiled
        self.params_dev = params_dev
        self.buffers_dev = buffers_dev
        self.in_shardings = in_shardings

    def __call__(self, dev_inputs):
        import jax
        placed = [jax.device_put(x, s)
                  for x, s in zip(dev_inputs, self.in_shardings)]
        return self.compiled(self.params_dev, self.buffers_dev, *placed)


class _ShardedRuntime:
    """Serving runtime for one sharded dense model — the live-layer
    analogue of server._ModelRuntime, duck-typing its worker-facing
    surface (templates/ladder/executables/late_compile/stats)."""

    kind = None                     # dense traffic (Server.submit)
    backend = "sharded"
    primary = None                  # no Predictor to clone

    def __init__(self, spec: ShardedModelSpec):
        from ..bucketing import BucketLadder
        self.spec = spec
        self.name = spec.name
        self.site = f"serving:{spec.name}"
        self.ladder = BucketLadder.from_flag(spec.buckets)
        self.mesh = spec.mesh
        self.executables = {}
        self.templates = []
        self.n_inputs = 0
        self.n_outputs = 0
        self.admitted = False
        self.param_specs: Dict[str, Any] = {}
        self.latency = LatencyWindow(int(_flags.flag("serving_metrics_window")))
        self.rate = RateMeter()
        self._mlock = threading.Lock()
        self.counters = {"requests": 0, "completed": 0,  # guarded-by: _mlock
                         "errors": 0,
                         "batches": 0, "rows": 0, "padded_rows": 0,
                         "steady_compiles": 0}

    def bump(self, **kw):
        with self._mlock:
            for k, v in kw.items():
                self.counters[k] += v

    def publish(self):
        self.latency.publish(f"serving_{self.name}")
        self.rate.publish(f"serving_{self.name}")

    @property
    def mesh_label(self) -> str:
        return "x".join(f"{a}{n}" for a, n in dict(self.mesh.shape).items())

    # -- loading -------------------------------------------------------------
    def load(self):
        from ...framework.functional import layer_state
        from ...static import InputSpec
        self.spec.layer.eval()
        for s in self.spec.input_specs:
            if isinstance(s, InputSpec):
                shape, dtype = list(s.shape), s.dtype
            else:
                shape, dtype = list(s[0]), s[1]
            self.templates.append((tuple(int(d) for d in shape[1:]),
                                   np.dtype(dtype)))
        self.n_inputs = len(self.templates)
        self.param_specs = serving_shard_specs(self.spec.layer, self.mesh,
                                               self.spec.rules)
        import jax
        params, buffers = layer_state(self.spec.layer)
        self._params = {n: jax.device_put(v, self._sharding(
            self.param_specs.get(n))) for n, v in params.items()}
        self._buffers = {n: jax.device_put(v, self._sharding())
                         for n, v in buffers.items()}

    def _sharding(self, spec=None):
        from jax.sharding import NamedSharding, PartitionSpec as P
        return NamedSharding(self.mesh, spec if spec is not None else P())

    def _input_sharding(self, bucket):
        """Batch rows shard over dp when the bucket divides; otherwise
        the (small) activations replicate — correctness first, the
        params are where the memory is."""
        from jax.sharding import PartitionSpec as P
        dp = dict(self.mesh.shape).get("dp", 1)
        spec = P("dp") if dp > 1 and bucket % dp == 0 else P()
        return self._sharding(spec)

    # -- abstract view (lint + AOT avals) ------------------------------------
    def _abstract_callable(self, bucket):
        import jax
        from ...framework import core
        from ...framework.functional import _bound_state
        from ...framework.tensor import Tensor, unwrap
        layer = self.spec.layer

        def call(params, buffers, *inputs):
            with core.no_grad_guard(), _bound_state(layer, params, buffers):
                out = layer(*[Tensor(x) for x in inputs])
            outs = out if isinstance(out, (list, tuple)) else (out,)
            return tuple(unwrap(o) for o in outs)

        in_sh = self._input_sharding(bucket)
        p_avals = {n: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=self._sharding(
                                               self.param_specs.get(n)))
                   for n, a in self._params.items()}
        b_avals = {n: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=self._sharding())
                   for n, a in self._buffers.items()}
        x_avals = [jax.ShapeDtypeStruct((bucket,) + rest, dt, sharding=in_sh)
                   for rest, dt in self.templates]
        return call, [p_avals, b_avals] + x_avals, None

    def _bucket_key(self, bucket):
        return tuple([("arg:bucket", bucket),
                      ("arg:mesh", self.mesh_label)]
                     + [(f"arg:inputs[{i}]", (bucket,) + rest, str(dt))
                        for i, (rest, dt) in enumerate(self.templates)])

    def _program_identity(self):
        """Restart-stable identity for the persistent executable cache:
        layer architecture + param avals + mesh axes + the spec table —
        two replicas of one sharded model share entries, a different
        mesh or table never false-hits."""
        cfg = getattr(self.spec.layer, "config", None)
        cfg_r = repr(sorted(vars(cfg).items())) \
            if cfg is not None and hasattr(cfg, "__dict__") else repr(cfg)
        avals = tuple(sorted((n, tuple(int(d) for d in a.shape),
                              str(a.dtype))
                             for n, a in self._params.items()))
        specs = tuple(sorted((n, repr(s))
                             for n, s in self.param_specs.items()))
        return ("serving_sharded", type(self.spec.layer).__name__, cfg_r,
                avals, specs, self.mesh_label)

    # -- admission: lint gate (PR-6 discipline, shared shape) ----------------
    def lint_gate(self, bucket):
        from ... import analysis
        if not analysis.lint_enabled():
            return
        import jax
        fn, avals, _ = self._abstract_callable(bucket)
        try:
            closed = jax.make_jaxpr(fn)(*avals)
        except Exception as e:   # noqa: BLE001 — lint must not mask bugs
            import warnings
            warnings.warn(
                f"sharded serving warm-up lint for {self.name!r} "
                f"b{bucket} could not abstract-eval the program: "
                f"{type(e).__name__}: {e}",
                analysis.GraphLintWarning, stacklevel=2)
            return
        ctx = analysis.LintContext(
            site=self.site, kind="serving", closed_jaxpr=closed,
            cache_key=self._bucket_key(bucket), mesh=self.mesh)
        report = analysis.default_pass_manager().run(ctx)
        analysis.emit(report, mode="warn")
        errors = report.by_severity(analysis.Severity.ERROR)
        if errors:
            raise PreconditionNotMetError(
                f"serving refused to admit sharded model {self.name!r}: "
                f"graph lint found {len(errors)} ERROR finding(s) at "
                f"bucket {bucket}:\n"
                + "\n".join("  " + str(d) for d in errors))

    # -- warm-up -------------------------------------------------------------
    def _compile_bucket(self, bucket, kind):
        import jax
        from jax.sharding import PartitionSpec as P
        from ...jit import persistent_cache as _pcache
        fn, avals, _ = self._abstract_callable(bucket)
        compiled, _loaded = _pcache.load_or_compile(
            lambda: jax.jit(fn, out_shardings=self._sharding(P()))
            .lower(*avals).compile(),
            site=self.site, kind=kind, key=self._bucket_key(bucket),
            extra_key=self._program_identity(),
            extra={"bucket": bucket, "model": self.name,
                   "mesh": self.mesh_label})
        shard_admission_audit(compiled, site=self.site, mesh=self.mesh,
                              param_specs=self.param_specs,
                              mesh_label=self.mesh_label)
        in_sh = self._input_sharding(bucket)
        return _ShardedExec(compiled, self._params, self._buffers,
                            [in_sh] * self.n_inputs)

    def warmup(self):
        import jax
        for bucket in self.ladder:
            self.lint_gate(bucket)
            ex = self._compile_bucket(bucket, "serving_aot")
            zeros = [jax.device_put(np.zeros((bucket,) + rest, dt), s)
                     for (rest, dt), s in zip(self.templates,
                                              ex.in_shardings)]
            outs = ex.compiled(self._params, self._buffers, *zeros)
            jax.block_until_ready(outs)
            self.executables[bucket] = ex
            self.n_outputs = len(outs)
        self.admitted = True

    # -- steady-state escape hatch (server._ModelRuntime contract) -----------
    def late_compile(self, bucket):
        from ...utils.monitor import stat_add
        if bool(_flags.flag("serving_strict")):
            raise PreconditionNotMetError(
                f"sharded serving model {self.name!r}: bucket {bucket} "
                "has no warm-up executable (FLAGS_serving_strict=True "
                "refuses steady-state compiles — extend the bucket "
                "ladder and re-warm instead)")
        ex = self._compile_bucket(bucket, "serving_recompile")
        stat_add("serving_steady_compiles")
        self.bump(steady_compiles=1)
        self.executables[bucket] = ex
        return ex
