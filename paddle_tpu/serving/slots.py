"""Iteration-level continuous batching: the slot-based decode loop.

The run-to-completion decode path (serving/decode.py ``execute``) runs
every batch as ONE scanned program: a 5-token request waits on its
500-token neighbor and arrivals queue until the whole batch drains.
This module hoists the token loop onto the HOST — Orca-style iteration-
level scheduling — over TWO slot executables the Generator compiles per
(slot-count, cache-bucket):

  * ``step_exec(S, C)``: one greedy token step for all ``S`` slot rows
    (or one speculative propose/verify/accept step under a draft pair);
  * ``chunk_exec(S, T, C)``: one Sarathi-style prefill chunk — ``T``
    prompt tokens of ONE joining row, interleaved between decode steps
    so long prompts never stall the running rows' token cadence.

The scheduling invariants that make slot reuse BIT-EXACT against a
per-request ``generate()`` of the same prompt:

  * **scalar lockstep position** — every dispatch writes at the shared
    ``pos``; a joining request is a row whose validity window restarts
    (``start[s]`` moves), never a recompile or a cache copy;
  * **dead-column garbage discipline** — the step program does NOT
    mask its cache write per row (the cache is donated for in-place
    column updates; a per-row blend would force XLA into a full-plane
    protective copy every step).  A step therefore writes garbage into
    inactive rows' lanes of the written column(s) — which is safe
    because every such column is DEAD: it lies inside the row's
    pending chunk window ``[act-Pb, act)`` (rewritten by the row's own
    chunks, scheduled after the last garbage write — see
    ``_dispatch_chunks``), below the row's ``start`` (never visible),
    or at ``>= act`` where the row's own active dispatches rewrite it
    before any commit exposes it;
  * **planned-activation chunk schedule** — a prompt of ``Lp`` tokens
    left-pads into ``n = ceil(Lp/T)`` chunks.  Columns are PER-ROW
    state, so the prompt block is free to end wherever the row starts
    generating: admission at position ``a`` plans the activation at
    ``act = max(Pb, a + n)`` (``Pb = n*T``; the ``Pb`` floor keeps the
    left-padded block at non-negative columns), the chunks write
    ``[act-Pb, act)``.  Chunk ``k`` dispatches in the iteration at
    which ``pos > act - n + k`` — one chunk per iteration over the
    last ``n`` iterations before activation, so a long prompt costs
    ``n`` iterations of everyone's token cadence, not ``Lp``, AND the
    chunk rewrite of each column lands strictly after the last decode
    step that could garbage it (the no-blend invariant above; the
    interval algebra: chunk ``k`` covers ``[act-Pb+kT, act-Pb+(k+1)T)``
    and every step from that iteration on writes columns
    ``>= act-n+k+1``; overlap would need ``n(T-1) < (k+1)(T-1)``,
    i.e. ``k >= n`` — impossible).  The row
    activates exactly when the shared ``pos`` reaches ``act``.
    Speculative strides clamp via ``max_commit`` to land on activation
    boundaries (committing fewer than accepted is always exact), and a
    stride that arrives at ``act`` early just bursts the remaining
    chunks first — chunk writes never depend on ``pos``;
  * **bounded ring sessions** — the validity mask compares absolute
    columns, so ``pos`` must stay inside ``[0, C)``: a request admits
    only if ``act + max_new (+ gamma)`` fits, and when the FIFO head
    cannot fit the loop drains and restarts the session at ``pos = 0``
    (amortized cost shrinks with ``C``; documented in the README
    decoding walkthrough).

Host-side, lock-and-condvar concurrency exactly like scheduler.py; the
driver thread owns every device dispatch.  Token-level occupancy
accounting (``decode_slot_occupancy_ratio`` + joined/retired counters,
scheduler.py instruments) feeds Server.signals() and the PR-16
ClusterSignals snapshot.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass, field
from typing import Any, List, Optional

import numpy as np

from ..framework.enforce import (InvalidArgumentError, OutOfRangeError,
                                 UnavailableError)
from .scheduler import (SLOT_OCCUPANCY, SLOT_TTFT, SLOTS_JOINED,
                        SLOTS_RETIRED)

__all__ = ["SlotLoop", "SlotRequest"]

_EMPTY, _PREFILL, _GEN = 0, 1, 2


@dataclass
class SlotRequest:
    """One row of slot-loop work: a prompt to continue by ``max_new``
    tokens.  ``future`` resolves to int32 [max_new] generated ids.

    The restore fields are filled by ``SlotLoop.submit`` when a session
    snapshot rides along: ``prompt`` then holds the VIRTUAL prompt (the
    transcript a full re-prefill would run), ``preseed`` the tokens the
    parked turn already emitted (they count against ``max_new`` and are
    replayed into the result), ``planes``/``planes_len`` the host KV
    pytree covering the leading ``planes_len`` transcript tokens, and
    ``resume_logits``/``resume_cur`` the activation payload for the
    no-suffix mid-generation resume (plain / speculative loop)."""

    prompt: np.ndarray
    max_new: int
    future: Future = field(default_factory=Future)
    t_submit: float = field(default_factory=time.monotonic)
    session_id: Optional[str] = None
    preseed: List[int] = field(default_factory=list)
    planes: Any = None
    planes_len: int = 0
    resume_logits: Optional[np.ndarray] = None
    resume_cur: Optional[int] = None
    snapshot: Any = None                # original snapshot (re-park on abort)


class _Slot:
    __slots__ = ("state", "req", "chunks", "next_chunk", "act",
                 "start", "emitted", "_act_logits", "restore", "pin")

    def __init__(self):
        self.state = _EMPTY
        self.req: Optional[SlotRequest] = None
        self.chunks: List[np.ndarray] = []
        self.next_chunk = 0
        self.act = 0                    # planned activation position
        self.start = 0
        self.emitted: List[int] = []
        self.restore: List[tuple] = []  # pending (block_tree, base) pushes
        self.pin = None                 # prefix-cache pin held until pushed


class SlotLoop:
    """The iteration-level decode loop for one Generator (plain or
    speculative).  ``submit`` enqueues a request and returns a Future;
    a dedicated driver thread admits requests into free slots at token
    boundaries, interleaves prefill chunks, retires finished rows, and
    keeps the occupancy/TTFT accounting honest.  Unit-testable without
    a Server — serving/decode.py wires it behind FLAGS_decode_slots."""

    def __init__(self, gen, slots: int, cache_len: int, chunk: int,
                 eos_token_id: Optional[int] = None,
                 model: str = "decode", prefix_cache=None,
                 session_store=None):
        if slots < 1:
            raise InvalidArgumentError(
                f"slot loop needs >= 1 slot, got {slots}")
        self._gen = gen
        self.S = int(slots)
        self.C = int(cache_len)
        self.T = int(chunk)
        self._eos = eos_token_id
        self._end = -1 if eos_token_id is None else int(eos_token_id)
        self._model = model
        self._spec = getattr(gen, "_draft", None) is not None
        self._gamma = int(gen._gamma) if self._spec else 0
        # compiled once here (ledgered compile or warm cache hit); every
        # later dispatch is a plain __call__ — zero steady-state compiles
        self._step = gen.step_exec(self.S, self.C, eos_token_id)
        self._chunk = gen.chunk_exec(self.S, self.T, self.C)
        # the KV reuse plane (prefix cache / session store): its three
        # data movers compile HERE, with the step/chunk programs, so an
        # arbitrary steady-state hit/miss/park/restore mix never
        # compiles — and a loop with both features off compiles nothing
        # extra (off-path = this one branch)
        self._prefix = prefix_cache
        self._sessions = session_store
        self._push_block = self._pull_block = self._pull_row = None
        if prefix_cache is not None or session_store is not None:
            self._push_block = gen.push_block_exec(self.S, self.T, self.C)
        if prefix_cache is not None:
            self._pull_block = gen.pull_block_exec(self.S, self.T, self.C)
        if session_store is not None:
            self._pull_row = gen.pull_row_exec(self.S, self.C)
        self._park_req = None           # guarded-by: _cond  (drain-park handshake)
        self._cond = threading.Condition()
        self._pending: "deque[SlotRequest]" = deque()       # guarded-by: _cond
        self._slots = [_Slot() for _ in range(self.S)]  # driver-thread-owned
        self._closed = False                                # guarded-by: _cond
        self._dead: Optional[BaseException] = None          # guarded-by: _cond
        self._thread: Optional[threading.Thread] = None     # guarded-by: _cond
        # device/host loop state (driver-thread-owned after start)
        self._reset_session()
        self.counters = {"joined": 0, "retired": 0, "steps": 0,
                         "chunks": 0, "session_resets": 0,
                         "emitted_tokens": 0, "parked": 0, "restored": 0,
                         "prefix_hit_tokens": 0, "restore_pushes": 0}
        # child instruments resolved once — .labels() is a registry
        # lookup and the step path is hot
        self._m_occ = SLOT_OCCUPANCY.labels(model=self._model)
        self._m_joined = SLOTS_JOINED.labels(model=self._model)
        self._m_retired = SLOTS_RETIRED.labels(model=self._model)
        self._m_ttft = SLOT_TTFT.labels(model=self._model)
        self._occupancy = 0.0               # EWMA of generating/S
        self._ttft: "deque[float]" = deque(maxlen=512)
        if self._spec:
            self._accepted = 0
            self._proposed = 0

    # -- session state -------------------------------------------------------
    def _reset_session(self):
        """Fresh ring session: position 0, zero planes (stale data is
        invisible behind the validity windows, but a cold loop has no
        planes yet), neutral per-row vectors."""
        self.pos = 0
        self._cache = self._gen.init_slot_cache(self.S, self.C)
        self._start = np.zeros((self.S,), np.int32)
        self._finished = np.ones((self.S,), bool)
        self._active = np.zeros((self.S,), bool)
        if getattr(self, "_spec", False):
            self._cur = np.zeros((self.S,), np.int32)
        else:
            vocab = self._gen._vocab_size()
            self._logits = np.zeros((self.S, vocab), np.float32)

    def _need(self, prompt_len: int, max_new: int) -> int:
        """Ring columns a request consumes: padded chunk span + its own
        token budget (+ the speculative verify block's overshoot)."""
        n_chunks = -(-int(prompt_len) // self.T)
        return n_chunks * self.T + int(max_new) + self._gamma

    def _min_need(self, req: "SlotRequest") -> int:
        """Minimum ring columns ``req`` can ever consume (admitted at
        ``pos = 0``): the plane-restore path needs only the transcript
        length itself (restored columns are exact, never chunk-padded),
        the plain path the padded chunk span."""
        budget = req.max_new - len(req.preseed)
        if req.planes_len >= self.T:
            return req.prompt.size + budget + self._gamma
        return self._need(req.prompt.size, budget)

    def _prepare_restore(self, req: "SlotRequest", snap) -> None:
        """Fold a session snapshot into the request.  Any mismatch —
        transcript not a prefix of the prompt, wrong loop flavor, wrong
        KV storage dtype, missing or sub-chunk planes — quietly degrades
        (first to plane-less restore, then to a plain submit), which is
        always bit-identical to the full re-prefill; a snapshot can make
        the turn cheaper, never wrong."""
        p = req.prompt
        toks = np.asarray(snap.tokens, np.int32)
        preseed: List[int] = []
        if snap.remaining > 0:
            # mid-generation park (drain): the client redispatched the
            # ORIGINAL request; the transcript extends its prompt by the
            # tokens already emitted — resume, replaying those
            if toks.size < p.size or toks.size != p.size + len(snap.emitted) \
                    or not np.array_equal(toks[:p.size], p):
                return
            if len(snap.emitted) >= req.max_new:
                preseed = list(snap.emitted)[:req.max_new]
            else:
                preseed = list(snap.emitted)
            req.prompt = toks
            req.preseed = preseed
        else:
            # completed turn: the follow-up prompt must extend the
            # transcript (history ++ new turn), leaving a real suffix
            if toks.size >= p.size \
                    or not np.array_equal(p[:toks.size], toks):
                return
        req.snapshot = snap
        planes_ok = (snap.planes is not None
                     and toks.size >= self.T
                     and bool(snap.spec) == self._spec
                     and snap.kv_dtype == self._kv_dtype())
        if not planes_ok:
            return                      # plane-less: plain chunks, bit-exact
        if snap.remaining > 0:
            if self._spec and snap.cur is None:
                return
            if not self._spec and snap.logits is None:
                return
            req.resume_logits = None if snap.logits is None \
                else np.asarray(snap.logits, np.float32).reshape(-1)
            req.resume_cur = None if snap.cur is None else int(snap.cur)
        req.planes = snap.planes
        req.planes_len = int(toks.size)

    def _kv_dtype(self) -> str:
        from ..framework import flags as _flags
        return str(_flags.flag("kv_cache_dtype")).lower()

    # -- producer ------------------------------------------------------------
    def submit(self, prompt, max_new: int, session_id: Optional[str] = None,
               snapshot=None) -> Future:
        p = np.asarray(prompt).reshape(-1).astype(np.int32)
        if p.size == 0:
            raise InvalidArgumentError("empty prompt (0 tokens)")
        mn = int(max_new)
        if mn < 1:
            raise InvalidArgumentError("max_new must be >= 1")
        req = SlotRequest(prompt=p, max_new=mn, session_id=session_id)
        if snapshot is not None:
            self._prepare_restore(req, snapshot)
        if len(req.preseed) >= mn:
            # the parked turn already emitted the whole budget — resolve
            # without touching a slot (deterministic replay)
            req.future.set_result(
                np.asarray(req.preseed[:mn], np.int32))
            return req.future
        if self._min_need(req) > self.C:
            raise OutOfRangeError(
                f"prompt of {p.size} tokens + max_new {mn} can never fit "
                f"the slot cache (need {self._min_need(req)} columns, "
                f"C={self.C}, chunk={self.T}, gamma={self._gamma})")
        with self._cond:
            if self._closed:
                raise UnavailableError("slot loop is closed")
            if self._dead is not None:
                raise UnavailableError(
                    f"slot loop died: {self._dead!r}")
            self._pending.append(req)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drive, name=f"slot-loop-{self._model}",
                    daemon=True)
                self._thread.start()
            self._cond.notify_all()
        return req.future

    def close(self):
        """Stop the driver once in-flight work drains; pending requests
        not yet admitted fail with UnavailableError."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
            t = self._thread
        if t is not None:
            t.join(timeout=30)

    # -- the driver loop -----------------------------------------------------
    def _drive(self):
        try:
            while True:
                with self._cond:
                    while (not self._pending
                           and all(s.state == _EMPTY
                                   for s in self._slots)):
                        if self._closed:
                            if self._park_req is not None:
                                self._park_req[0].set()
                                self._park_req = None
                            return
                        if self._park_req is not None:
                            # nothing live to park — ack the handshake
                            # so a drain never waits on an idle loop
                            self._park_req[0].set()
                            self._park_req = None
                        self._cond.wait(0.05)
                    if self._closed and not self._any_live():
                        self._fail_pending(UnavailableError(
                            "slot loop closed before this request was "
                            "admitted"))
                        return
                    park = self._park_req
                    self._park_req = None
                    if park is not None:
                        self._do_park(park)
                    self._admit()
                self._dispatch_chunks()
                self._activate()
                if not any(s.state == _GEN for s in self._slots):
                    self._fast_forward()
                    continue
                self._decode_step()
        except BaseException as e:   # noqa: BLE001 — fail rows, not host
            with self._cond:
                self._dead = e
                if self._park_req is not None:
                    self._park_req[0].set()
                    self._park_req = None
                for s in self._slots:
                    if s.req is not None and not s.req.future.done():
                        s.req.future.set_exception(e)
                    s.state, s.req = _EMPTY, None
                self._fail_pending(e)

    def _any_live(self) -> bool:
        return bool(self._pending) or any(s.state != _EMPTY
                                          for s in self._slots)

    def _fail_pending(self, exc):
        while self._pending:
            r = self._pending.popleft()
            if not r.future.done():
                r.future.set_exception(exc)

    # -- admission (FIFO, no starvation) -------------------------------------
    def _plan_act(self, prompt_len: int) -> int:
        """The planned activation position for a prompt admitted NOW:
        one chunk dispatches per loop iteration and the shared ``pos``
        advances at most one token boundary per iteration, so the
        earliest exact meeting point is ``pos + n`` chunks out — floored
        at ``Pb`` so the left-padded block stays at columns >= 0."""
        n_chunks = -(-int(prompt_len) // self.T)
        return max(n_chunks * self.T, self.pos + n_chunks)

    def _plan_act_req(self, req: "SlotRequest") -> int:
        """Planned activation for a request admitted NOW, by mode.  A
        plane-restore row prefills only its uncached suffix (``n_s``
        chunks; zero for a mid-generation resume), floored at the
        transcript length so the restored block stays at columns >= 0
        — restored columns are exact, never chunk-padded."""
        ltot = req.prompt.size
        if req.planes_len >= self.T:
            n_s = self._suffix_chunks(req)
            return max(ltot, self.pos + n_s)
        return self._plan_act(ltot)

    def _suffix_chunks(self, req: "SlotRequest") -> int:
        ls = req.prompt.size - (req.planes_len // self.T) * self.T \
            if req.planes_len < req.prompt.size else 0
        return -(-ls // self.T)

    def _host_block(self, planes, lo, hi):
        import jax.tree_util as tu
        return tu.tree_map(lambda p: p[:, :, lo:hi, :], planes)

    def _admit(self):
        """Move pending FIFO heads into empty slots at the current token
        boundary.  Strict FIFO: if the head does not fit the remaining
        ring columns, nothing behind it jumps the line — the loop drains
        and restarts the session instead."""
        for slot in self._slots:
            if not self._pending or slot.state != _EMPTY:
                continue
            head = self._pending[0]
            if self._plan_act_req(head) + head.max_new \
                    - len(head.preseed) + self._gamma > self.C:
                if all(s.state == _EMPTY for s in self._slots) \
                        and self.pos > 0:
                    # whole loop idle: restart the ring session (windows
                    # restart, planes stay — stale columns are invisible)
                    self.pos = 0
                    self.counters["session_resets"] += 1
                else:
                    break                        # drain first
            self._pending.popleft()
            self._install(slot, head)

    def _install(self, slot: "_Slot", head: "SlotRequest"):
        """Stage one admitted request into a slot row: pick the restore
        source (session planes > prefix-cache hit > none), queue the
        restore block pushes, and plan the suffix chunks.  All three
        paths meet the same activation at ``slot.act`` and are
        bit-identical to the plain full prefill of ``head.prompt``."""
        p = head.prompt
        lp = int(p.size)
        slot.restore = []
        slot.pin = None
        if head.planes_len >= self.T:
            # -- session-snapshot restore (host planes) -------------------
            lc = head.planes_len
            m = lc // self.T
            n_s = self._suffix_chunks(head)
            slot.act = max(lp, self.pos + n_s)
            slot.start = slot.act - lp
            for j in range(m):
                slot.restore.append(
                    (self._host_block(head.planes, j * self.T,
                                      (j + 1) * self.T),
                     slot.start + j * self.T))
            if lc % self.T and lc == lp:
                # mid-generation resume: no suffix chunk will recompute
                # the partial tail block — restore it as a T-wide
                # overlap slice ending exactly at the transcript edge
                slot.restore.append(
                    (self._host_block(head.planes, lc - self.T, lc),
                     slot.start + lc - self.T))
            suffix = p[lp - n_s * self.T:] if n_s else p[:0]
            slot.chunks = [suffix[k * self.T:(k + 1) * self.T]
                           for k in range(n_s)]
            self.counters["restored"] += 1
        else:
            blocks, pin = ([], None)
            if self._prefix is not None and lp > self.T:
                # clamp so >= 1 true suffix token remains: the final
                # chunk's last column must be the last prompt token (it
                # produces the activation logits)
                blocks, pin = self._prefix.lookup(
                    p.tolist(), max_blocks=(lp - 1) // self.T)
            if blocks:
                # -- prefix-cache hit (device blocks) ---------------------
                lhit = len(blocks) * self.T
                ls = lp - lhit
                n_s = -(-ls // self.T)
                slot.act = max(lp, self.pos + n_s)
                slot.start = slot.act - lp
                slot.restore = [(b, slot.start + j * self.T)
                                for j, b in enumerate(blocks)]
                slot.pin = pin
                # overlap-repeat: the first suffix chunk re-feeds the
                # last n_s*T - ls cached tokens (recomputed K/V is
                # bit-identical, so rewriting restored columns is free)
                suffix = p[lp - n_s * self.T:]
                slot.chunks = [suffix[k * self.T:(k + 1) * self.T]
                               for k in range(n_s)]
                self.counters["prefix_hit_tokens"] += lhit
            else:
                if pin:
                    self._prefix.release(pin)
                # -- plain path: full left-padded chunked prefill ---------
                n_chunks = -(-lp // self.T)
                pb = n_chunks * self.T
                padded = np.zeros((pb,), np.int32)
                padded[pb - lp:] = p
                slot.chunks = [padded[k * self.T:(k + 1) * self.T]
                               for k in range(n_chunks)]
                slot.act = self._plan_act(lp)
                slot.start = slot.act - lp
        slot.req = head
        slot.next_chunk = 0
        slot.emitted = list(head.preseed)
        slot.state = _PREFILL
        self.counters["joined"] += 1
        self._m_joined.inc()

    # -- chunked prefill -----------------------------------------------------
    def _dispatch_chunks(self):
        """One chunk per prefilling slot per iteration (the Sarathi
        budget: a joining prompt taxes everyone's token cadence by its
        chunk count, not its length), scheduled over the LAST ``n``
        iterations before the row activates: chunk ``k`` dispatches
        once ``pos > act - n + k``.  That late placement is load-
        bearing, not cosmetic — the step program writes unmasked
        garbage into inactive rows' lanes (dead-column discipline, see
        the module docstring), and dispatching chunk ``k`` only after
        the step at ``act - n + k`` has retired guarantees the chunk's
        column block is rewritten strictly after the last step that
        could garbage it.  Chunk writes carry their own column base,
        independent of ``pos`` — a speculative stride that lands on an
        activation boundary early just bursts the remaining chunks
        back-to-back before the row activates (catch-up dispatches are
        safe: running a chunk LATER than planned only moves it further
        from the garbage frontier)."""
        for i, slot in enumerate(self._slots):
            if slot.state != _PREFILL:
                continue
            self._push_restores(i, slot)
            if slot.restore:
                # chunks READ restored columns through attention — hold
                # them until every pending push has dispatched.  Never
                # starves: all restore bases are push-eligible by the
                # first chunk's iteration (Ls >= n_s - 1, see _install)
                continue
            n = len(slot.chunks)
            while (slot.next_chunk < n
                   and slot.act - n + slot.next_chunk < self.pos):
                # fresh buffers per dispatch: the CPU runtime may alias
                # a numpy argument zero-copy and read it asynchronously,
                # so a buffer handed to a dispatch is immutable forever
                ids = slot.chunks[slot.next_chunk].reshape(1, self.T)
                start = np.array([slot.start], np.int32)
                base = slot.act - len(slot.chunks) * self.T \
                    + slot.next_chunk * self.T
                self._cache, logits = self._chunk(
                    *self._gen._state_args(), self._cache, ids, start,
                    np.int32(i), np.int32(base))
                slot.next_chunk += 1
                self.counters["chunks"] += 1
                if slot.next_chunk == len(slot.chunks):
                    # final chunk: its last column is the last prompt
                    # token — stash the activation logits for this row.
                    # MUST be a host copy: activation reads it one or
                    # more dispatches later, after the runtime may have
                    # reused the output buffer a zero-copy view aliases.
                    slot._act_logits = np.array(logits, np.float32)

    def _push_restores(self, i: int, slot: "_Slot"):
        """Dispatch every push-eligible restore block of one row.  A
        block ``[base, base+T)`` is eligible once ``base + T <= pos``:
        every later step writes columns ``>= pos`` (plain and
        speculative alike), so the pushed columns can never be garbaged
        by the dead-column discipline again.  The prefix-cache pin
        releases when the last block is in flight — from then on the
        restored columns live in the row, not the trie."""
        while slot.restore and slot.restore[0][1] + self.T <= self.pos:
            block, base = slot.restore.pop(0)
            self._cache = self._push_block(
                self._cache, block, np.int32(i), np.int32(base))
            self.counters["restore_pushes"] += 1
        if not slot.restore and slot.pin is not None:
            self._prefix.release(slot.pin)
            slot.pin = None

    # -- activation ----------------------------------------------------------
    def _activate(self):
        for i, slot in enumerate(self._slots):
            if slot.state != _PREFILL \
                    or slot.next_chunk < len(slot.chunks) \
                    or slot.restore \
                    or self.pos != slot.act:
                continue
            # copy-on-write: these vectors were handed to earlier
            # dispatches, which may alias them zero-copy — mutate a
            # fresh copy, never the buffer a dispatch has seen
            self._start = self._start.copy()
            self._start[i] = slot.start
            self._finished = self._finished.copy()
            self._finished[i] = False
            self._active = self._active.copy()
            self._active[i] = True
            if not slot.chunks:
                # mid-generation resume: no suffix chunk produced the
                # activation logits — the snapshot carried the payload
                # (the exact values the pre-park loop held for this row)
                if self._spec:
                    self._cur = self._cur.copy()
                    self._cur[i] = np.int32(slot.req.resume_cur)
                else:
                    lg = np.array(self._logits)
                    lg[i] = slot.req.resume_logits
                    self._logits = lg
            elif self._spec:
                # first committed token = target argmax over the final
                # chunk's logits (the joint-prefill cur0 computation)
                act = slot._act_logits
                self._cur = self._cur.copy()
                self._cur[i] = np.int32(np.argmax(act))
            else:
                lg = np.array(self._logits)
                lg[i] = slot._act_logits
                self._logits = lg
            slot.state = _GEN
            self._publish_prefix(i, slot)

    def _fast_forward(self):
        """No generating rows: the position counter is host state, so
        jump it to the EARLIEST planned activation instead of burning
        empty decode dispatches (never past it — a later row's window
        must still start exactly at its own ``act``)."""
        acts = [s.act for s in self._slots if s.state == _PREFILL]
        if acts:
            self.pos = max(self.pos, min(acts))

    # -- one decode iteration ------------------------------------------------
    def _decode_step(self):
        gen_slots = [i for i, s in enumerate(self._slots)
                     if s.state == _GEN]
        ratio = len(gen_slots) / self.S
        self._occupancy = ratio if self.counters["steps"] == 0 \
            else 0.9 * self._occupancy + 0.1 * ratio
        self._m_occ.set(round(ratio, 4))
        if self._spec:
            self._spec_step(gen_slots)
        else:
            self._plain_step(gen_slots)
        self.counters["steps"] += 1

    def _plain_step(self, gen_slots):
        self._cache, self._logits, finished, tok = self._step(
            *self._gen._state_args(), self._cache, self._logits,
            self._start, self._finished, self._active,
            np.int32(self.pos))
        tok = np.asarray(tok)
        self._finished = np.array(finished)
        self.pos += 1
        for i in gen_slots:
            slot = self._slots[i]
            self._emit(slot, [int(tok[i])])
            if self._finished[i] or len(slot.emitted) >= slot.req.max_new:
                self._retire(i)

    def _spec_step(self, gen_slots):
        # clamp the stride so the commit lands exactly on the nearest
        # activation boundary — a prefilling row's window must start
        # the moment the frontier reaches its planned position (every
        # remaining PREFILL act is > pos here: rows AT pos activated or
        # burst-chunked in this same iteration)
        boundaries = [s.act - self.pos
                      for s in self._slots if s.state == _PREFILL]
        mc = min([self._gamma + 1] + [b for b in boundaries if b > 0])
        (self._cache, cur, finished, e, ncommit, n) = self._step(
            *self._gen._state_args(), self._cache, self._cur,
            self._start, self._finished, self._active,
            np.int32(self.pos), np.int32(mc))
        self._cur = np.array(cur)
        self._finished = np.array(finished)
        e = np.asarray(e)
        k = int(ncommit)
        self.pos += k
        self._accepted += int(n)
        self._proposed += self._gamma
        for i in gen_slots:
            slot = self._slots[i]
            self._emit(slot, [int(t) for t in e[i, :k]])
            if self._finished[i] or len(slot.emitted) >= slot.req.max_new:
                self._retire(i)

    def _emit(self, slot, toks):
        if not slot.emitted:
            dt = time.monotonic() - slot.req.t_submit
            self._ttft.append(dt)
            self._m_ttft.observe(dt)
        take = slot.req.max_new - len(slot.emitted)
        slot.emitted.extend(toks[:take])
        self.counters["emitted_tokens"] += min(len(toks), take)

    def _publish_prefix(self, i: int, slot: "_Slot"):
        """Publish the activated row's prompt blocks into the prefix
        trie.  Dedup lives in the trie — the pull dispatches run only
        for blocks not already cached, so a hot shared prefix is pulled
        once and every later activation is pure bookkeeping.  Dispatch
        ordering makes the pulled copy immune to the row's later column
        writes (donation creates fresh buffers; the pull reads the
        pre-donation value)."""
        if self._prefix is None:
            return
        slot_start = slot.start
        self._prefix.publish(
            slot.req.prompt.tolist(),
            lambda j: self._pull_block(self._cache, np.int32(i),
                                       np.int32(slot_start + j * self.T)))

    def _park(self, i: int, slot: "_Slot", remaining: int):
        """Snapshot one session row into the store: one full-width row
        pull, host-sliced to the transcript's validity window (relative
        positions ``[0, Lc)``), plus the resume payload.  Called at
        turn-retire (remaining == 0: the follow-up turn restores instead
        of re-prefilling history) and at drain-park (remaining > 0)."""
        from .sessions import SessionSnapshot
        req = slot.req
        new = slot.emitted[len(req.preseed):]
        tokens = req.prompt.tolist() + [int(t) for t in new]
        lc = len(tokens)
        planes = None
        if self._pull_row is not None and lc >= self.T:
            import jax.tree_util as tu
            row = self._pull_row(self._cache, np.int32(i))
            planes = tu.tree_map(
                lambda p: np.asarray(p)[:, :, slot.start:slot.start + lc,
                                        :].copy(), row)
        logits = None
        cur = None
        if remaining > 0:
            if self._spec:
                cur = int(self._cur[i])
            else:
                logits = np.array(self._logits[i], np.float32)
        self._sessions.put(SessionSnapshot(
            session_id=req.session_id, model=self._model, tokens=tokens,
            remaining=int(remaining), emitted=[int(t) for t in slot.emitted],
            planes=planes, logits=logits, cur=cur,
            kv_dtype=self._kv_dtype(), spec=self._spec))
        self.counters["parked"] += 1

    def _retire(self, i):
        slot = self._slots[i]
        req = slot.req
        out = np.full((req.max_new,), self._end, np.int32)
        out[:len(slot.emitted)] = slot.emitted
        if req.session_id is not None and self._sessions is not None:
            # park BEFORE the future resolves and the slot frees: a new
            # admit could reuse this row and overwrite the columns the
            # snapshot needs (its padded block may start below pos)
            self._park(i, slot, remaining=0)
        # eos freeze: every position after finish reads eos, exactly the
        # scanned decode's padding — retiring early never changes bytes
        req.future.set_result(out)
        slot.state, slot.req = _EMPTY, None
        slot.emitted = []
        # copy-on-write for the same aliasing reason as _activate
        self._finished = self._finished.copy()
        self._finished[i] = True
        self._active = self._active.copy()
        self._active[i] = False
        if self._spec:
            self._cur = self._cur.copy()
            self._cur[i] = 0
        self.counters["retired"] += 1
        self._m_retired.inc()

    # -- drain-time parking --------------------------------------------------
    def park_sessions(self, timeout: float = 30.0) -> int:
        """Park every session-tagged row and pending request (the
        graceful-drain fast path: a conversation leaves as a snapshot in
        milliseconds instead of decoding to completion).  Generating
        rows snapshot mid-stream (``remaining > 0``) and their futures
        fail with a retryable UnavailableError — the router backs this
        replica off and redispatches the turn, which resumes from the
        snapshot (shared spill dir) or re-prefills (bit-identical
        either way).  Non-session rows keep decoding normally.  Thread-
        safe; the driver thread does the actual device pulls (it owns
        every dispatch).  Returns the number of sessions parked."""
        if self._sessions is None:
            return 0
        evt = threading.Event()
        out = [0]
        with self._cond:
            if self._dead is not None or self._thread is None \
                    or not self._any_live():
                return 0
            self._park_req = (evt, out)
            self._cond.notify_all()
        evt.wait(timeout)
        return out[0]

    def _do_park(self, park):
        """Driver-thread half of :meth:`park_sessions` (called with the
        condition held, between dispatch rounds — no dispatch races)."""
        evt, out = park
        try:
            exc = UnavailableError(
                "session parked for drain; redispatch to another "
                "replica", retry_after_s=0.05)
            for i, slot in enumerate(self._slots):
                if slot.req is None or slot.req.session_id is None:
                    continue
                if slot.state == _GEN:
                    self._park(i, slot,
                               remaining=slot.req.max_new
                               - len(slot.emitted))
                    out[0] += 1
                elif slot.state == _PREFILL:
                    # nothing committed yet: put the original snapshot
                    # back (if one rode in) and let the redispatched
                    # turn restore or re-prefill from scratch
                    if slot.req.snapshot is not None:
                        self._sessions.put(slot.req.snapshot)
                    if slot.pin is not None:
                        self._prefix.release(slot.pin)
                        slot.pin = None
                    out[0] += 1
                if not slot.req.future.done():
                    slot.req.future.set_exception(exc)
                slot.state, slot.req = _EMPTY, None
                slot.emitted = []
                slot.restore = []
                self._finished = self._finished.copy()
                self._finished[i] = True
                self._active = self._active.copy()
                self._active[i] = False
                if self._spec:
                    self._cur = self._cur.copy()
                    self._cur[i] = 0
            keep: "deque[SlotRequest]" = deque()
            while self._pending:
                r = self._pending.popleft()
                if r.session_id is not None:
                    if r.snapshot is not None:
                        self._sessions.put(r.snapshot)
                    if not r.future.done():
                        r.future.set_exception(exc)
                    out[0] += 1
                else:
                    keep.append(r)
            self._pending = keep
        finally:
            evt.set()

    def reset_stats(self):
        """Zero the loop-local accounting (the runtime calls this right
        after its warm-up round-trip so steady-state counters start
        clean — the registry instruments keep their monotonic totals)."""
        with self._cond:
            for k in self.counters:
                self.counters[k] = 0
            self._occupancy = 0.0
            self._ttft.clear()
            if self._spec:
                self._accepted = 0
                self._proposed = 0

    # -- observability -------------------------------------------------------
    def signals(self) -> dict:
        """Token-level load snapshot for Server.signals() and the PR-16
        ClusterSignals leg: the occupancy EWMA plus lifetime
        joined/retired counters and queue backlog."""
        with self._cond:
            c = dict(self.counters)
            pending = len(self._pending)
            occ = self._occupancy
        out = {"decode_slot_occupancy_ratio": round(occ, 4),
               "slots_joined_total": c["joined"],
               "slots_retired_total": c["retired"],
               "slot_steps_total": c["steps"],
               "slot_pending": pending}
        if self._sessions is not None:
            out["sessions_parked"] = len(self._sessions)
            out["session_store_bytes"] = self._sessions.nbytes()
        if self._prefix is not None:
            out["prefix_cache_blocks"] = len(self._prefix)
            out["prefix_cache_bytes"] = self._prefix.nbytes()
        return out

    def stats(self) -> dict:
        with self._cond:
            c = dict(self.counters)
            ttft = sorted(self._ttft)
        out = {"slots": self.S, "cache": self.C, "chunk": self.T,
               "occupancy_ewma": round(self._occupancy, 4), **c}
        if ttft:
            out["ttft_p50_ms"] = round(
                ttft[len(ttft) // 2] * 1e3, 3)
            out["ttft_p99_ms"] = round(
                ttft[min(len(ttft) - 1,
                         int(len(ttft) * 0.99))] * 1e3, 3)
        if self._spec:
            out["spec_accepted"] = self._accepted
            out["spec_proposed"] = self._proposed
            if self._proposed:
                out["spec_acceptance_rate"] = round(
                    self._accepted / self._proposed, 4)
        return out
